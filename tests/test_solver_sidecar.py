"""Solver sidecar: out-of-process Score/Assign over gRPC.

- loopback: RemoteSolver against an in-thread SolverGrpcServer returns
  placements identical to the in-proc engine;
- version fencing: scheduling against a stale snapshot version re-syncs;
- full propagation e2e with the solver in a REAL separate process
  (python -m karmada_tpu.solver) — the control plane schedules everything
  through the wire. Ref: pkg/estimator/service/service.proto:26-29 (the
  contract shape), SURVEY.md section 7 (sidecar north star).
"""

import os
import re
import subprocess
import sys
import time

import numpy as np
import pytest

from karmada_tpu.scheduler import BindingProblem, ClusterSnapshot, TensorScheduler
from karmada_tpu.solver import RemoteSolver, SolverGrpcServer, SolverService
from karmada_tpu.utils.builders import (
    duplicated_placement,
    dynamic_weight_placement,
    static_weight_placement,
    synthetic_fleet,
)
from karmada_tpu.utils.quantity import parse_resource_list

REQ = parse_resource_list({"cpu": "250m", "memory": "512Mi"})


def _problems(clusters, n=40, seed=0):
    rng = np.random.default_rng(seed)
    pls = [
        dynamic_weight_placement(),
        duplicated_placement(),
        static_weight_placement({clusters[0].name: 2, clusters[1].name: 1}),
    ]
    return [
        BindingProblem(
            key=f"b{i}",
            placement=pls[i % 3],
            replicas=int(rng.integers(0, 20)),
            requests=REQ,
            gvk="apps/v1/Deployment",
            prev={clusters[int(j)].name: int(rng.integers(1, 5))
                  for j in rng.choice(len(clusters), 2, replace=False)},
            fresh=bool(rng.random() < 0.2),
        )
        for i in range(n)
    ]


@pytest.fixture(scope="module")
def loopback():
    service = SolverService()
    server = SolverGrpcServer(service, "127.0.0.1:0")
    port = server.start()
    client = RemoteSolver(f"127.0.0.1:{port}")
    yield client, service
    client.close()
    server.stop()


def test_loopback_matches_in_proc_engine(loopback):
    client, _ = loopback
    clusters = synthetic_fleet(12, seed=3)
    problems = _problems(clusters)
    client.sync_clusters(clusters)
    remote = client.schedule(problems)
    local = TensorScheduler(ClusterSnapshot(sorted(clusters, key=lambda c: c.name))).schedule(problems)
    for r, l in zip(remote, local):
        assert r.success == l.success and r.error == l.error, r.key
        assert r.clusters == l.clusters, r.key
        assert sorted(r.feasible) == sorted(l.feasible), r.key
        assert r.affinity_name == l.affinity_name


def test_stale_snapshot_resyncs(loopback):
    client, service = loopback
    clusters = synthetic_fleet(8, seed=4)
    client.sync_clusters(clusters)
    # simulate a solver restart losing the snapshot
    service._engine = None
    service._version = 0
    client._cluster_source = lambda: clusters
    results = client.schedule(_problems(clusters, n=5))
    assert all(r.key.startswith("b") for r in results)
    assert service.snapshot_version == client._version


def test_propagation_e2e_with_out_of_process_solver():
    """The full control plane drives scheduling through a solver running in
    a separate OS process."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "karmada_tpu.solver", "--address", "127.0.0.1:0"],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    try:
        line = proc.stdout.readline()
        m = re.search(r"port (\d+)", line)
        assert m, f"no port line from solver process: {line!r}"
        port = int(m.group(1))

        from karmada_tpu import cli
        from karmada_tpu.api import (
            PropagationPolicy,
            PropagationSpec,
            ResourceSelector,
        )
        from karmada_tpu.api.core import ObjectMeta
        from karmada_tpu.controllers import execution_namespace
        from karmada_tpu.utils.builders import new_deployment

        solver = RemoteSolver(f"127.0.0.1:{port}")
        cp = cli.cmd_init(solver=solver)
        for i in range(1, 4):
            cli.cmd_join(cp, f"member{i}")
        cp.store.apply(
            PropagationPolicy(
                meta=ObjectMeta(name="web-policy", namespace="default"),
                spec=PropagationSpec(
                    resource_selectors=[
                        ResourceSelector(
                            api_version="apps/v1", kind="Deployment", name="web"
                        )
                    ],
                    placement=dynamic_weight_placement(),
                ),
            )
        )
        cp.store.apply(new_deployment("web", replicas=6))
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/web-deployment")
        assert rb is not None and rb.spec.clusters
        assert sum(tc.replicas for tc in rb.spec.clusters) == 6
        # Works landed in execution namespaces via the remote placements
        works = [
            w
            for w in cp.store.list("Work")
            if w.meta.namespace.startswith("karmada-es-")
        ]
        assert works
        solver.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


class TestHASolver:
    """HA solver replicas: schedule() sticks to the active backend, fails
    over on transport errors, and standbys answer correctly because syncs
    broadcast (or the FAILED_PRECONDITION re-sync heals a cold one)."""

    def _up(self):
        from karmada_tpu.solver.client import HASolver

        servers = []
        targets = []
        for _ in range(2):
            svc = SolverService()
            srv = SolverGrpcServer(svc, "127.0.0.1:0")
            port = srv.start()
            servers.append(srv)
            targets.append(f"127.0.0.1:{port}")
        return servers, HASolver(targets)

    def test_failover_mid_storm_is_placement_identical(self):
        servers, ha = self._up()
        try:
            clusters = synthetic_fleet(12, seed=5)
            problems = _problems(clusters, n=30, seed=9)
            ha._cluster_source = lambda: clusters
            ha.sync_clusters(clusters)
            want = TensorScheduler(
                ClusterSnapshot(sorted(clusters, key=lambda c: c.name))
            ).schedule(problems)

            def check(res):
                for r, w in zip(res, want):
                    assert r.success == w.success and r.clusters == w.clusters, r.key

            check(ha.schedule(problems))
            assert ha.active_target == 0
            # kill the active backend: the next schedule must fail over
            # and stay identical
            servers[0].stop()
            check(ha.schedule(problems))
            assert ha.active_target == 1
        finally:
            for s in servers:
                try:
                    s.stop()
                except Exception:
                    pass
            ha.close()

    def test_cold_standby_heals_via_resync(self):
        from karmada_tpu.solver.client import HASolver

        # standby never saw a sync (spawned later): FAILED_PRECONDITION
        # on failover triggers its own re-sync + retry
        svc_a, svc_b = SolverService(), SolverService()
        srv_a = SolverGrpcServer(svc_a, "127.0.0.1:0")
        srv_b = SolverGrpcServer(svc_b, "127.0.0.1:0")
        pa, pb = srv_a.start(), srv_b.start()
        ha = HASolver([f"127.0.0.1:{pa}", f"127.0.0.1:{pb}"])
        try:
            clusters = synthetic_fleet(10, seed=6)
            problems = _problems(clusters, n=12, seed=2)
            ha._cluster_source = lambda: clusters
            # sync ONLY the active (simulates b joining later)
            ha._solvers[0].sync_clusters(clusters)
            res_a = ha.schedule(problems)
            srv_a.stop()
            res_b = ha.schedule(problems)  # b is cold -> re-sync path
            for a, b in zip(res_a, res_b):
                assert a.clusters == b.clusters and a.error == b.error
        finally:
            for s in (srv_a, srv_b):
                try:
                    s.stop()
                except Exception:
                    pass
            ha.close()


class TestDeadlineBudget:
    """ISSUE 7 satellite: the re-sync-then-retry path used to stack
    ``self.timeout`` up to three times (score, sync, retry). One overall
    deadline budget now threads through the whole schedule() call."""

    def test_stalled_resync_path_fails_within_one_budget(self):
        """THE old stacking shape: the first score answers
        FAILED_PRECONDITION instantly (solver restarted, missed the sync),
        the re-sync succeeds but SLOWLY (0.8x the budget), and the retried
        score black-holes. The old code gave the retry a fresh full
        ``self.timeout`` on top of the sync's — ~1.8x total; the deadline
        budget bounds the whole call to ~1x."""
        import threading

        import grpc

        svc = SolverService()
        stall = threading.Event()

        real_sync = svc.sync_clusters
        real_score = svc.score_and_assign

        def slow_sync(clusters, version):
            time.sleep(1.2)  # succeeds, but eats most of the 1.5s budget
            return real_sync(clusters, version)

        def stalling_score(request):
            if svc.snapshot_version == request.snapshot_version:
                stall.wait(timeout=30.0)  # the RETRY black-holes
            return real_score(request)

        svc.sync_clusters = slow_sync
        svc.score_and_assign = stalling_score
        srv = SolverGrpcServer(svc, "127.0.0.1:0")
        port = srv.start()
        clusters = synthetic_fleet(6, seed=3)
        solver = RemoteSolver(
            f"127.0.0.1:{port}",
            timeout_seconds=1.5,
            cluster_source=lambda: clusters,
        )
        try:
            problems = _problems(clusters, n=4, seed=1)
            # never synced: the first score answers FAILED_PRECONDITION
            t0 = time.perf_counter()
            with pytest.raises(grpc.RpcError):
                solver.schedule(problems)
            elapsed = time.perf_counter() - t0
            assert elapsed < 1.5 * 1.4, (
                f"schedule took {elapsed:.2f}s — the deadline budget did "
                "not bound the re-sync retry path (old stacking would "
                "run ~2.7s here)"
            )
        finally:
            stall.set()
            solver.close()
            srv.stop(0)

    def test_dead_solver_fails_within_one_budget(self):
        import grpc

        clusters = synthetic_fleet(4, seed=2)
        solver = RemoteSolver(
            "127.0.0.1:1", timeout_seconds=1.0,
            cluster_source=lambda: clusters,
        )
        try:
            t0 = time.perf_counter()
            with pytest.raises(grpc.RpcError):
                solver.schedule(_problems(clusters, n=2, seed=4))
            assert time.perf_counter() - t0 < 1.8
        finally:
            solver.close()
