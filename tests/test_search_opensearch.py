"""OpenSearch wire-protocol backend (VERDICT r3 missing #4).

Ref: pkg/search/backendstore/opensearch.go — verifies the plane speaks
the real OpenSearch REST surface: index-per-kind creation with
already-exists tolerance, UID-keyed _doc index/delete, the reference's
document shape (cache-source annotation, spec/status as JSON strings),
NDJSON _bulk, _search, _count, and _delete_by_query for cluster drops —
against the stand-in node AND through the search controller.
"""

from __future__ import annotations

import json
import urllib.request

import pytest

from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.search.opensearch import (
    CACHE_SOURCE_ANNOTATION,
    OpenSearchBackend,
    OpenSearchServer,
    doc_to_resource,
    resource_to_doc,
)


def mk(name, ns="default", kind="Deployment", replicas=1, uid=""):
    return Resource(
        api_version="apps/v1", kind=kind,
        meta=ObjectMeta(name=name, namespace=ns, uid=uid,
                        labels={"app": name}),
        spec={"replicas": replicas},
        status={"ready": replicas},
    )


@pytest.fixture()
def node():
    server = OpenSearchServer()
    target = f"127.0.0.1:{server.start()}"
    yield server, target
    server.stop()


class TestDocumentShape:
    def test_reference_doc_shape_round_trips(self):
        obj = mk("web", replicas=3, uid="u-123")
        doc = resource_to_doc("member1", obj)
        # spec/status serialize as JSON STRINGS (opensearch.go:216-218)
        assert isinstance(doc["spec"], str) and isinstance(doc["status"], str)
        assert doc["metadata"]["annotations"][CACHE_SOURCE_ANNOTATION] == (
            "member1"
        )
        cluster, back = doc_to_resource(doc)
        assert cluster == "member1"
        assert back.spec == {"replicas": 3} and back.status == {"ready": 3}
        assert CACHE_SOURCE_ANNOTATION not in back.meta.annotations


class TestProtocol:
    def test_index_create_is_idempotent_like_opensearch(self, node):
        server, target = node
        be = OpenSearchBackend(target)
        be._ensure_index("Deployment")
        # a second client hitting the same index gets the OpenSearch
        # already-exists 400 and tolerates it
        be2 = OpenSearchBackend(target)
        be2._ensure_index("Deployment")
        assert "kubernetes-deployment" in server.indices

    def test_doc_crud_and_search(self, node):
        _, target = node
        be = OpenSearchBackend(target, batch_size=2)
        for i in range(5):
            be.upsert("member1", mk(f"web-{i}", replicas=i, uid=f"u{i}"))
        be.upsert("member2", mk("api", uid="u-api"))
        assert be.count() == 6
        hits = be.search("label:app=web-3")
        assert [h["name"] for h in hits] == ["web-3"]
        assert hits[0]["object"].spec == {"replicas": 3}
        assert len(be.search("", clusters=["member2"])) == 1
        be.delete("member1", "apps/v1/Deployment", "default", "web-0")
        assert be.count() == 5
        be.drop_cluster("member1")
        assert be.count() == 1

    def test_raw_rest_surface(self, node):
        """Drive the node with raw requests exactly as opensearch-go
        would (IndexRequest / DeleteRequest / IndicesCreateRequest)."""
        _, target = node

        def call(method, path, body=None, ct="application/json"):
            req = urllib.request.Request(
                f"http://{target}{path}",
                data=body, method=method,
                headers={"Content-Type": ct},
            )
            with urllib.request.urlopen(req, timeout=5) as r:
                return json.loads(r.read())

        assert call("PUT", "/kubernetes-deployment",
                    json.dumps({"mappings": {}}).encode())["acknowledged"]
        doc = resource_to_doc("m1", mk("raw", uid="u-raw"))
        out = call("PUT", "/kubernetes-deployment/_doc/u-raw",
                   json.dumps(doc).encode())
        assert out["result"] == "created"
        out = call("PUT", "/kubernetes-deployment/_doc/u-raw",
                   json.dumps(doc).encode())
        assert out["result"] == "updated"
        res = call("POST", "/_search", json.dumps(
            {"query": {"query_string": {"query": "label:app=raw"}}}
        ).encode())
        assert res["hits"]["total"]["value"] == 1
        assert res["hits"]["hits"][0]["_id"] == "u-raw"
        out = call("DELETE", "/kubernetes-deployment/_doc/u-raw")
        assert out["result"] == "deleted"
        out = call("DELETE", "/kubernetes-deployment/_doc/u-raw")
        assert out["result"] == "not_found"

    def test_bulk_ndjson(self, node):
        _, target = node
        lines = []
        for i in range(3):
            doc = resource_to_doc("m1", mk(f"b{i}", uid=f"ub{i}"))
            lines.append(json.dumps(
                {"index": {"_index": "kubernetes-deployment", "_id": f"ub{i}"}}
            ))
            lines.append(json.dumps(doc))
        lines.append(json.dumps(
            {"delete": {"_index": "kubernetes-deployment", "_id": "ub1"}}
        ))
        req = urllib.request.Request(
            f"http://{target}/_bulk",
            data=("\n".join(lines) + "\n").encode(),
            headers={"Content-Type": "application/x-ndjson"},
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            out = json.loads(r.read())
        assert not out["errors"]
        assert [list(i)[0] for i in out["items"]] == [
            "index", "index", "index", "delete",
        ]
        be = OpenSearchBackend(target)
        assert be.count() == 2


class TestControllerIntegration:
    def test_search_controller_ships_documents_over_opensearch(self, node):
        """ResourceRegistry backend: opensearch lands member documents in
        the external node through the real wire protocol."""
        from karmada_tpu.api.core import ObjectMeta as OM
        from karmada_tpu.search.registry import (
            ResourceRegistry, ResourceRegistrySpec,
        )
        from karmada_tpu.controlplane import ControlPlane
        from karmada_tpu.utils.builders import new_cluster, new_deployment

        _, target = node
        cp = ControlPlane()
        cp.search.indexer = OpenSearchBackend(target, batch_size=4)
        cp.join_cluster(new_cluster("member1"))
        cp.settle()
        cp.members.get("member1").apply(new_deployment("shipped", replicas=2))
        cp.store.apply(ResourceRegistry(
            meta=OM(name="rr"),
            spec=ResourceRegistrySpec(
                resource_selectors=[
                    {"apiVersion": "apps/v1", "kind": "Deployment"}
                ],
                backend="opensearch",
            ),
        ))
        cp.settle()
        be = OpenSearchBackend(target)
        hits = be.search("name:shipped")
        if not hits:  # hyphen-free names index whole; fall back to prefix
            hits = be.search("name:shipped*")
        assert hits and hits[0]["cluster"] == "member1"
        assert hits[0]["object"].spec.get("replicas") == 2
