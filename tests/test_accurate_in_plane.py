"""Accurate estimator wired into the control plane: node-level capacity
bounds the schedule (the config-3 deployment shape: estimator per member)."""

from karmada_tpu.api import PropagationPolicy, PropagationSpec, ResourceSelector
from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.estimator import NodeState
from karmada_tpu.utils.builders import dynamic_weight_placement, new_cluster, new_deployment
from karmada_tpu.utils.quantity import parse_resource_list


def test_node_capacity_bounds_schedule():
    cp = ControlPlane(enable_accurate_estimator=True)
    # member1 summary says huge, but nodes only fit 2 x 1cpu replicas
    m1 = cp.join_cluster(new_cluster("member1", cpu="1000", memory="4000Gi"))
    m1.nodes = [
        NodeState(
            name="n0",
            allocatable=parse_resource_list({"cpu": "2", "memory": "8Gi", "pods": 10}),
        )
    ]
    m2 = cp.join_cluster(new_cluster("member2", cpu="1000", memory="4000Gi"))
    m2.nodes = [
        NodeState(
            name="n0",
            allocatable=parse_resource_list({"cpu": "64", "memory": "256Gi",
                                             "pods": 100}),
        )
    ]
    cp.settle()
    cp.store.apply(new_deployment("app", replicas=10, cpu="1", memory="1Gi"))
    cp.store.apply(
        PropagationPolicy(
            meta=ObjectMeta(name="p", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(api_version="apps/v1", kind="Deployment")
                ],
                placement=dynamic_weight_placement(),
            ),
        )
    )
    cp.settle()
    rb = cp.store.get("ResourceBinding", "default/app-deployment")
    placed = {tc.name: tc.replicas for tc in rb.spec.clusters}
    assert sum(placed.values()) == 10
    assert placed.get("member1", 0) <= 2  # node-level cap, not the summary


def test_unjoin_repoints_estimator_fanout():
    """unjoin must rebuild the scheduler's batch-estimator fan-out: a stale
    one keeps the old cluster-column layout and breaks the estimator
    min-merge shape on the next reconcile (found via addons enable +
    unjoin)."""
    cp = ControlPlane(enable_accurate_estimator=True)
    for i in (1, 2, 3):
        m = cp.join_cluster(new_cluster(f"member{i}", cpu="64", memory="256Gi"))
        m.nodes = [
            NodeState(
                name="n0",
                allocatable=parse_resource_list(
                    {"cpu": "32", "memory": "128Gi", "pods": 50}
                ),
            )
        ]
    cp.settle()
    cp.store.apply(new_deployment("app", replicas=6, cpu="1", memory="1Gi"))
    cp.store.apply(
        PropagationPolicy(
            meta=ObjectMeta(name="p", namespace="default"),
            spec=PropagationSpec(
                resource_selectors=[
                    ResourceSelector(api_version="apps/v1", kind="Deployment")
                ],
                placement=dynamic_weight_placement(),
            ),
        )
    )
    cp.settle()
    cp.unjoin_cluster("member2")
    cp.settle()  # must not crash on a stale 3-column estimator
    # Divided bindings do not auto-move on cluster removal (faithful to
    # doScheduleBinding's gate); an explicit reschedule trigger must now
    # succeed against the 2-column fan-out and drop member2
    rb = next(iter(cp.store.list("ResourceBinding")))
    rb.spec.reschedule_triggered_at = cp.clock() + 1
    cp.store.apply(rb)
    cp.settle()
    rb = next(iter(cp.store.list("ResourceBinding")))
    names = {tc.name for tc in rb.spec.clusters}
    assert "member2" not in names and rb.spec.clusters
    assert sum(tc.replicas for tc in rb.spec.clusters) == 6
