"""Multi-cluster service tests (ref: test/e2e/mcs_test.go patterns)."""

from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.api.networking import (
    ExposureRange,
    MultiClusterService,
    MultiClusterServiceSpec,
    ServiceExport,
)
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.utils.builders import new_cluster


def endpoint_slice(name, service, addresses):
    return Resource(
        api_version="discovery.k8s.io/v1",
        kind="EndpointSlice",
        meta=ObjectMeta(
            name=name,
            namespace="default",
            labels={"kubernetes.io/service-name": service},
        ),
        spec={"endpoints": [{"addresses": [a]} for a in addresses]},
    )


def service(name):
    return Resource(
        api_version="v1",
        kind="Service",
        meta=ObjectMeta(name=name, namespace="default"),
        spec={"ports": [{"port": 80}], "clusterIP": "10.0.0.5"},
    )


def make_plane():
    cp = ControlPlane()
    for i in (1, 2, 3):
        cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
    cp.settle()
    return cp


class TestServiceExport:
    def test_slices_collected_to_control_plane(self):
        cp = make_plane()
        m1 = cp.members.get("member1")
        m1.apply(service("web"))
        m1.apply(endpoint_slice("web-abc", "web", ["10.1.0.1", "10.1.0.2"]))
        cp.store.apply(
            ServiceExport(meta=ObjectMeta(name="web", namespace="default"))
        )
        cp.settle()
        collected = cp.store.get("Resource", "default/member1-web-abc")
        assert collected is not None
        assert collected.meta.labels["endpointslice.karmada.io/source-cluster"] == "member1"


class TestMultiClusterService:
    def test_derived_service_dispatched_to_consumers(self):
        cp = make_plane()
        m1 = cp.members.get("member1")
        m1.apply(service("web"))
        m1.apply(endpoint_slice("web-abc", "web", ["10.1.0.1"]))
        cp.store.apply(
            MultiClusterService(
                meta=ObjectMeta(name="web", namespace="default"),
                spec=MultiClusterServiceSpec(
                    provider_clusters=[ExposureRange(cluster_names=["member1"])],
                    consumer_clusters=[ExposureRange(cluster_names=["member2"])],
                ),
            )
        )
        cp.settle()
        m2 = cp.members.get("member2")
        derived = m2.get("v1/Service", "default", "derived-web")
        assert derived is not None
        assert derived.spec["ports"] == [{"port": 80}]
        slice_obj = m2.get("discovery.k8s.io/v1/EndpointSlice", "default",
                           "member1-web-abc")
        assert slice_obj is not None
        assert slice_obj.spec["endpoints"] == [{"addresses": ["10.1.0.1"]}]
        # non-consumer cluster stays clean
        m3 = cp.members.get("member3")
        assert m3.get("v1/Service", "default", "derived-web") is None
