"""gRPC estimator transport: wire round-trips, mTLS, pool fan-out.

Ref behavior: pkg/estimator/server/server.go (mTLS serve),
client/accurate.go:139-162 (fan-out, error -> UnauthenticReplica),
client/cache.go (connection cache eviction on failure).
"""

import subprocess

import numpy as np
import pytest

from karmada_tpu.api.cluster import NO_SCHEDULE, Taint
from karmada_tpu.estimator.accurate import AccurateEstimator, NodeSnapshot, NodeState
from karmada_tpu.estimator.grpc_transport import (
    EstimatorGrpcServer,
    GrpcEstimatorConnection,
    conventional_target,
)
from karmada_tpu.estimator.service import (
    EstimatorClientPool,
    EstimatorService,
    MaxAvailableReplicasRequest,
    UnschedulableReplicasRequest,
)

DIMS = ["cpu", "memory", "pods"]


def make_service(cluster: str, cpu_free: int, n_nodes: int = 2) -> EstimatorService:
    nodes = [
        NodeState(
            name=f"{cluster}-n{i}",
            allocatable={"cpu": cpu_free, "memory": 1 << 32, "pods": 110},
            requested={"cpu": 0, "memory": 0},
        )
        for i in range(n_nodes)
    ]
    est = AccurateEstimator(cluster, NodeSnapshot(nodes, DIMS))
    est.unschedulable["default/web"] = 3
    return EstimatorService(est)


def test_insecure_round_trip():
    svc = make_service("m1", cpu_free=4000)
    server = EstimatorGrpcServer(svc)
    port = server.start()
    try:
        conn = GrpcEstimatorConnection("m1", f"127.0.0.1:{port}")
        resp = conn.call(
            "MaxAvailableReplicas",
            MaxAvailableReplicasRequest(cluster="m1", resource_request={"cpu": 1000}),
        )
        # 2 nodes x 4000/1000
        assert resp.max_replicas == 8
        un = conn.call(
            "GetUnschedulableReplicas",
            UnschedulableReplicasRequest(cluster="m1", namespace="default", name="web"),
        )
        assert un.unschedulable_replicas == 3
        conn.close()
    finally:
        server.stop()


def test_node_claim_survives_wire():
    """node_selector + tolerations shape the estimate through the pb hop."""
    nodes = [
        NodeState(
            name="gpu-node",
            allocatable={"cpu": 8000, "memory": 1 << 33, "pods": 110},
            labels={"accel": "tpu"},
        ),
        NodeState(
            name="tainted",
            allocatable={"cpu": 8000, "memory": 1 << 33, "pods": 110},
            labels={"accel": "tpu"},
            taints=[Taint(key="dedicated", value="infra", effect=NO_SCHEDULE)],
        ),
        NodeState(name="plain", allocatable={"cpu": 8000, "memory": 1 << 33, "pods": 110}),
    ]
    svc = EstimatorService(AccurateEstimator("m1", NodeSnapshot(nodes, DIMS)))
    server = EstimatorGrpcServer(svc)
    port = server.start()
    try:
        conn = GrpcEstimatorConnection("m1", f"127.0.0.1:{port}")
        # selector only: tainted node excluded, plain node label-mismatched
        resp = conn.call(
            "MaxAvailableReplicas",
            MaxAvailableReplicasRequest(
                cluster="m1",
                resource_request={"cpu": 2000},
                node_selector={"accel": "tpu"},
            ),
        )
        assert resp.max_replicas == 4
        # toleration unlocks the tainted node
        resp = conn.call(
            "MaxAvailableReplicas",
            MaxAvailableReplicasRequest(
                cluster="m1",
                resource_request={"cpu": 2000},
                node_selector={"accel": "tpu"},
                tolerations=[{"key": "dedicated", "operator": "Exists"}],
            ),
        )
        assert resp.max_replicas == 8
        conn.close()
    finally:
        server.stop()


def test_pool_fanout_over_grpc_and_failure_unauthentic():
    servers = {}
    ports = {}
    for name, cpu in [("m1", 2000), ("m2", 6000)]:
        s = EstimatorGrpcServer(make_service(name, cpu))
        ports[name] = s.start()
        servers[name] = s

    def resolver(cluster):
        if cluster == "gone":  # unreachable member: refused connection
            return GrpcEstimatorConnection(cluster, "127.0.0.1:1", timeout_seconds=0.5)
        if cluster not in ports:
            return None
        return GrpcEstimatorConnection(cluster, f"127.0.0.1:{ports[cluster]}")

    pool = EstimatorClientPool(resolver, timeout_seconds=5.0)
    try:
        got = pool.max_available_replicas(
            ["m1", "m2", "gone", "unknown"], {"cpu": 1000}
        )
        assert got == {"m1": 4, "m2": 12, "gone": -1, "unknown": -1}
        # failed channel was evicted so recovery re-resolves
        assert pool.connection("m1") is not None
        assert "gone" not in pool._conns
    finally:
        for s in servers.values():
            s.stop()


@pytest.fixture(scope="module")
def mtls_certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("pki")

    def run(*args):
        subprocess.run(args, check=True, capture_output=True, cwd=d)

    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes", "-keyout",
        "ca.key", "-out", "ca.crt", "-days", "1", "-subj", "/CN=karmada-ca")
    for who in ("server", "client"):
        run("openssl", "req", "-newkey", "rsa:2048", "-nodes", "-keyout",
            f"{who}.key", "-out", f"{who}.csr", "-subj", f"/CN={who}")
        run("openssl", "x509", "-req", "-in", f"{who}.csr", "-CA", "ca.crt",
            "-CAkey", "ca.key", "-CAcreateserial", "-out", f"{who}.crt",
            "-days", "1", "-extfile", _ext_file(d, who))
    return {p.name: p.read_bytes() for p in d.iterdir() if p.suffix in (".crt", ".key")}


def _ext_file(d, who):
    ext = d / f"{who}.ext"
    ext.write_text("subjectAltName=IP:127.0.0.1,DNS:localhost\n")
    return str(ext)


def test_mtls_round_trip(mtls_certs):
    """mTLS both ways (ref: grpcconnection/config.go — server cert+key,
    client CA, require_client_auth)."""
    svc = make_service("secure", cpu_free=3000)
    server = EstimatorGrpcServer(
        svc,
        server_cert=mtls_certs["server.crt"],
        server_key=mtls_certs["server.key"],
        client_ca=mtls_certs["ca.crt"],
    )
    port = server.start()
    try:
        conn = GrpcEstimatorConnection(
            "secure",
            f"127.0.0.1:{port}",
            root_ca=mtls_certs["ca.crt"],
            client_cert=mtls_certs["client.crt"],
            client_key=mtls_certs["client.key"],
        )
        resp = conn.call(
            "MaxAvailableReplicas",
            MaxAvailableReplicasRequest(cluster="secure", resource_request={"cpu": 500}),
        )
        assert resp.max_replicas == 12
        conn.close()
        # a client without a certificate is rejected by client-auth
        bad = GrpcEstimatorConnection(
            "secure", f"127.0.0.1:{port}", root_ca=mtls_certs["ca.crt"],
            timeout_seconds=2.0,
        )
        with pytest.raises(Exception):
            bad.call(
                "MaxAvailableReplicas",
                MaxAvailableReplicasRequest(cluster="secure", resource_request={"cpu": 500}),
            )
        bad.close()
    finally:
        server.stop()


def test_conventional_target():
    assert conventional_target("karmada-scheduler-estimator", "m1", 10352) == (
        "karmada-scheduler-estimator-m1:10352"
    )
    assert conventional_target("est", "m2", 9000, host="127.0.0.1") == "127.0.0.1:9000"


def test_batch_request_matches_single_over_wire():
    """The wire path (single requests) agrees with the in-proc batch kernel."""
    svc = make_service("m1", cpu_free=5000, n_nodes=3)
    server = EstimatorGrpcServer(svc)
    port = server.start()
    try:
        conn = GrpcEstimatorConnection("m1", f"127.0.0.1:{port}")
        reqs = np.array([[1000, 1, 1], [2500, 1, 1], [7000, 1, 1]], np.int64)
        batch = svc.estimator.max_available_replicas(None, reqs)
        for row, expect in zip(reqs, batch):
            resp = conn.call(
                "MaxAvailableReplicas",
                MaxAvailableReplicasRequest(
                    cluster="m1",
                    resource_request={"cpu": int(row[0]), "memory": int(row[1]), "pods": int(row[2])},
                ),
            )
            assert resp.max_replicas == int(expect)
        conn.close()
    finally:
        server.stop()


def test_partial_tls_rejected(mtls_certs):
    """Incomplete TLS material fails loudly — never silent plaintext."""
    svc = make_service("m1", cpu_free=1000)
    with pytest.raises(ValueError):
        EstimatorGrpcServer(svc, server_cert=mtls_certs["server.crt"])
    with pytest.raises(ValueError):
        EstimatorGrpcServer(svc, client_ca=mtls_certs["ca.crt"])
    with pytest.raises(ValueError):
        GrpcEstimatorConnection("m1", "127.0.0.1:1", client_cert=mtls_certs["client.crt"])


def test_bind_failure_raises():
    svc = make_service("m1", cpu_free=1000)
    s1 = EstimatorGrpcServer(svc, address="127.0.0.1:0")
    try:
        with pytest.raises(RuntimeError):
            EstimatorGrpcServer(svc, address=f"127.0.0.1:{s1.port}")
    finally:
        s1.stop()
