"""Estimator tests: resource-model grades, node-level accurate estimation,
min-merge into the scheduler (ref test strategy: estimator server/client unit
tables)."""

import numpy as np
import jax.numpy as jnp

from karmada_tpu.api import (
    AllocatableModeling,
    ResourceModel,
    ResourceModelRange,
    Taint,
)
from karmada_tpu.api.work import NodeClaim, ReplicaRequirements
from karmada_tpu.estimator import (
    AccurateEstimator,
    EstimatorRegistry,
    NodeSnapshot,
    NodeState,
)
from karmada_tpu.models import estimate_by_models, pack_models
from karmada_tpu.scheduler import BindingProblem, ClusterSnapshot, TensorScheduler
from karmada_tpu.utils.builders import dynamic_weight_placement, new_cluster
from karmada_tpu.utils.quantity import parse_resource_list

DIMS = ["cpu", "memory", "pods", "ephemeral-storage"]


def make_model_cluster(name, grades, counts, **kw):
    """grades: list of (cpu_min_milli, mem_min_bytes)."""
    models = [
        ResourceModel(
            grade=g,
            ranges=[
                ResourceModelRange(name="cpu", min=cpu, max=cpu * 2),
                ResourceModelRange(name="memory", min=mem, max=mem * 2),
            ],
        )
        for g, (cpu, mem) in enumerate(grades)
    ]
    cl = new_cluster(name, **kw)
    cl.spec.resource_models = models
    cl.status.resource_summary.allocatable_modelings = [
        AllocatableModeling(grade=g, count=n) for g, n in enumerate(counts)
    ]
    return cl


class TestModelEstimate:
    def test_grade_walk(self):
        # grades: [1C,2C) x [4Gi,8Gi), [2C,4C) x [8Gi,16Gi); counts 3, 2
        cl = make_model_cluster(
            "m", [(1000, 4 << 30), (2000, 8 << 30)], [3, 2]
        )
        pack = pack_models([cl], DIMS)
        req = np.zeros((1, len(DIMS)), np.int64)
        req[0, 0] = 1500  # 1.5C -> grade0 min (1C) not compliant -> grade1
        req[0, 1] = 1 << 30
        got, applicable = estimate_by_models(
            jnp.asarray(pack.min_bounds),
            jnp.asarray(pack.counts),
            jnp.asarray(pack.covered),
            jnp.asarray(req),
        )
        # grade1 per-node: min(2000//1500, 8Gi//1Gi) = 1 -> 2 nodes * 1
        assert int(got[0, 0]) == 2 and bool(applicable[0, 0])

    def test_small_request_uses_all_grades(self):
        cl = make_model_cluster("m", [(1000, 4 << 30), (2000, 8 << 30)], [3, 2])
        pack = pack_models([cl], DIMS)
        req = np.zeros((1, len(DIMS)), np.int64)
        req[0, 0] = 500  # grade0 compliant: 3*(1000//500=2) + 2*(2000//500=4)
        got, _ = estimate_by_models(
            jnp.asarray(pack.min_bounds), jnp.asarray(pack.counts),
            jnp.asarray(pack.covered), jnp.asarray(req),
        )
        assert int(got[0, 0]) == 3 * 2 + 2 * 4

    def test_no_compliant_grade(self):
        cl = make_model_cluster("m", [(1000, 4 << 30)], [5])
        pack = pack_models([cl], DIMS)
        req = np.zeros((1, len(DIMS)), np.int64)
        req[0, 0] = 99_000  # bigger than any grade min
        got, applicable = estimate_by_models(
            jnp.asarray(pack.min_bounds), jnp.asarray(pack.counts),
            jnp.asarray(pack.covered), jnp.asarray(req),
        )
        assert int(got[0, 0]) == 0 and bool(applicable[0, 0])

    def test_uncovered_resource_not_applicable(self):
        cl = make_model_cluster("m", [(1000, 4 << 30)], [5])
        pack = pack_models([cl], DIMS)
        req = np.zeros((1, len(DIMS)), np.int64)
        req[0, 3] = 1 << 30  # ephemeral-storage not in models
        _, applicable = estimate_by_models(
            jnp.asarray(pack.min_bounds), jnp.asarray(pack.counts),
            jnp.asarray(pack.covered), jnp.asarray(req),
        )
        assert not bool(applicable[0, 0])

    def test_scheduler_uses_model_path(self):
        # summary says huge capacity; models say only 2 replicas fit
        cl = make_model_cluster(
            "modeled", [(1000, 4 << 30)], [2], cpu="1000", memory="4000Gi"
        )
        plain = new_cluster("plain", cpu="1000", memory="4000Gi")
        sched = TensorScheduler(ClusterSnapshot([cl, plain]))
        [res] = sched.schedule(
            [
                BindingProblem(
                    key="b",
                    placement=dynamic_weight_placement(),
                    replicas=10,
                    requests=parse_resource_list({"cpu": "1", "memory": "4Gi"}),
                    gvk="apps/v1/Deployment",
                )
            ]
        )
        # modeled cluster capped at 2 by grades, plain takes the rest by weight
        assert res.clusters.get("modeled", 0) <= 3
        assert sum(res.clusters.values()) == 10


class TestAccurateEstimator:
    def _nodes(self):
        alloc = parse_resource_list({"cpu": "8", "memory": "32Gi", "pods": 110})
        return [
            NodeState(
                name=f"n{i}",
                allocatable=dict(alloc),
                requested=parse_resource_list({"cpu": "2", "memory": "8Gi"}),
                labels={"zone": f"z{i % 2}"},
                num_pods=10,
            )
            for i in range(4)
        ]

    def test_node_sum(self):
        est = AccurateEstimator("m1", NodeSnapshot(self._nodes(), DIMS))
        req = np.zeros((1, len(DIMS)), np.int64)
        req[0, 0] = 2000  # 2C -> per node min((8-2)/2=3, pods 100) = 3
        req[0, 2] = 1
        got = est.max_available_replicas(None, req)
        assert got.tolist() == [12]

    def test_node_selector_prefilter(self):
        est = AccurateEstimator("m1", NodeSnapshot(self._nodes(), DIMS))
        reqs = ReplicaRequirements(
            resource_request=parse_resource_list({"cpu": "2"}),
            node_claim=NodeClaim(node_selector={"zone": "z0"}),
        )
        req = np.zeros((1, len(DIMS)), np.int64)
        req[0, 0] = 2000
        got = est.max_available_replicas(reqs, req)
        assert got.tolist() == [6]  # only 2 of 4 nodes match

    def test_node_taint_prefilter(self):
        nodes = self._nodes()
        nodes[0].taints = [Taint(key="gpu", value="true", effect="NoSchedule")]
        est = AccurateEstimator("m1", NodeSnapshot(nodes, DIMS))
        reqs = ReplicaRequirements(node_claim=NodeClaim(node_selector={}))
        req = np.zeros((1, len(DIMS)), np.int64)
        req[0, 0] = 2000
        got = est.max_available_replicas(reqs, req)
        assert got.tolist() == [9]  # tainted node excluded

    def test_registry_min_merges_into_scheduler(self):
        clusters = [new_cluster("m1", cpu="1000"), new_cluster("m2", cpu="1000")]
        snap = ClusterSnapshot(clusters)
        reg = EstimatorRegistry()
        # accurate estimator for m1 says only 3 replicas fit
        tiny = NodeState(
            name="n0",
            allocatable=parse_resource_list({"cpu": "3", "memory": "64Gi", "pods": 50}),
        )
        reg.register(AccurateEstimator("m1", NodeSnapshot([tiny], snap.dims)))
        sched = TensorScheduler(
            snap, extra_estimators=[reg.make_batch_estimator(snap.names)]
        )
        [res] = sched.schedule(
            [
                BindingProblem(
                    key="b",
                    placement=dynamic_weight_placement(),
                    replicas=10,
                    requests=parse_resource_list({"cpu": "1"}),
                    gvk="apps/v1/Deployment",
                )
            ]
        )
        assert res.clusters.get("m1", 0) <= 3
        assert sum(res.clusters.values()) == 10

    def test_batch_estimator_memo_scoped_to_name_order(self):
        # two coexisting batch estimators over the SAME registry but
        # different name orderings: memoized columns are positional, so
        # a memo keyed only by request bytes would hand the second
        # estimator the first one's columns transposed
        clusters = [new_cluster("m1", cpu="1000"), new_cluster("m2", cpu="1000")]
        snap = ClusterSnapshot(clusters)
        reg = EstimatorRegistry()
        for name, cores in (("m1", "3"), ("m2", "8")):
            node = NodeState(
                name=f"{name}-n0",
                allocatable=parse_resource_list(
                    {"cpu": cores, "memory": "64Gi", "pods": 50}
                ),
            )
            reg.register(AccurateEstimator(name, NodeSnapshot([node], snap.dims)))
        fwd = reg.make_batch_estimator(["m1", "m2"])
        rev = reg.make_batch_estimator(["m2", "m1"])
        req = np.zeros((1, len(snap.dims)), np.int64)
        req[0, list(snap.dims).index("cpu")] = 1000
        reps = np.asarray([10])
        assert fwd(req, reps)[0].tolist() == [3, 8]
        assert rev(req, reps)[0].tolist() == [8, 3]
        # and the repeat answers come from each closure's own memo slice
        assert fwd(req, reps)[0].tolist() == [3, 8]
        assert rev(req, reps)[0].tolist() == [8, 3]

    def test_numpy_kernel_mirrors_jit_kernel(self):
        # the small-problem numpy mirror (the estimator server's unary
        # fast path) must be bit-identical to the jit kernel — same floor
        # division, no-requested-dims zeroing, prefilter and int32 clamp
        from karmada_tpu.estimator.accurate import (
            _node_sum_estimate,
            _node_sum_estimate_np,
        )

        rng = np.random.default_rng(11)
        for b, n, r in ((1, 1, 4), (8, 3, 4), (5, 17, 2), (3, 2, 1)):
            avail = rng.integers(-5, 10_000, (n, r)).astype(np.int64)
            ok = rng.random((b, n)) < 0.8
            reqs = rng.integers(0, 7, (b, r)).astype(np.int64) * 100
            reqs[0, :] = 0  # a row with no requested dims answers 0
            jit_out = np.asarray(
                _node_sum_estimate(
                    jnp.asarray(avail), jnp.asarray(ok), jnp.asarray(reqs)
                )
            )
            np_out = _node_sum_estimate_np(avail, ok, reqs)
            assert jit_out.dtype == np_out.dtype
            assert (jit_out == np_out).all()
        # huge availability with a tiny request exercises the int32 clamp
        avail = np.full((2, 1), 2**40, np.int64)
        reqs = np.asarray([[1]], np.int64)
        ok = np.ones((1, 2), bool)
        assert _node_sum_estimate_np(avail, ok, reqs).tolist() == [2**31 - 1]
        assert np.asarray(
            _node_sum_estimate(
                jnp.asarray(avail), jnp.asarray(ok), jnp.asarray(reqs)
            )
        ).tolist() == [2**31 - 1]


class TestModelEstimatorHostMirror:
    def _model_fleet(self, n=20, seed=3):
        from karmada_tpu.api.cluster import (
            AllocatableModeling, ResourceModel, ResourceModelRange,
        )
        from karmada_tpu.utils.builders import synthetic_fleet

        clusters = synthetic_fleet(n, seed=seed)
        rng = np.random.default_rng(seed)
        for cl in clusters:
            if rng.random() < 0.3:
                continue  # some clusters stay model-less (summary path)
            g_n = int(rng.integers(2, 4))
            cl.spec.resource_models = [
                ResourceModel(grade=g, ranges=[
                    ResourceModelRange(
                        name="cpu", min=500 * 2**g, max=500 * 2**(g + 1)
                    ),
                    ResourceModelRange(
                        name="memory", min=(1 << 30) * 2**g,
                        max=(1 << 30) * 2**(g + 1),
                    ),
                ])
                for g in range(g_n)
            ]
            cl.status.resource_summary.allocatable_modelings = [
                AllocatableModeling(grade=g, count=int(rng.integers(1, 30)))
                for g in range(g_n)
            ]
        return clusters

    def test_numpy_mirror_matches_device_kernel(self):
        """estimate_by_models_np must be bit-identical to the jitted
        kernel across randomized model packs and request profiles."""
        from karmada_tpu.models.modeling import estimate_by_models_np

        snap = ClusterSnapshot(self._model_fleet(24, seed=7))
        mp = snap.model_pack
        rng = np.random.default_rng(11)
        reqs = np.stack([
            np.array([int(rng.integers(0, 4000)),
                      int(rng.integers(0, 8 << 30)),
                      int(rng.integers(0, 3)),
                      int(rng.integers(0, 2 << 30))][: len(snap.dims)],
                     dtype=np.int64)
            for _ in range(40)
        ])
        dev_total, dev_app = estimate_by_models(
            jnp.asarray(mp.min_bounds), jnp.asarray(mp.counts),
            jnp.asarray(mp.covered), jnp.asarray(reqs),
        )
        np_total, np_app = estimate_by_models_np(
            np.asarray(mp.min_bounds), np.asarray(mp.counts),
            np.asarray(mp.covered), reqs,
        )
        assert np.array_equal(np.asarray(dev_total), np_total)
        assert np.array_equal(np.asarray(dev_app), np_app)

    def test_model_batches_take_host_fast_path_identically(self):
        """BASELINE config-3 shape (VERDICT r3 item 9): tiny model-bearing
        batches divide on host numpy, bit-identical to the device path."""
        clusters = self._model_fleet(20, seed=3)
        snap = ClusterSnapshot(clusters)
        req = parse_resource_list({"cpu": "250m", "memory": "512Mi"})
        from karmada_tpu.utils.builders import aggregated_placement

        pl = aggregated_placement()
        problems = [
            BindingProblem(key=f"b{i}", placement=pl, replicas=(i % 20) + 1,
                           requests=req, gvk="apps/v1/Deployment")
            for i in range(60)
        ]
        host_eng = TensorScheduler(snap)
        assert host_eng._models_active()
        got = host_eng._schedule_host(
            problems, [host_eng._compiled(p.placement) for p in problems]
        )
        # force the device estimator/divider with an out-of-tree estimator
        # that answers -1 (ignored by the merge): placements must match
        dev_eng = TensorScheduler(
            snap,
            extra_estimators=[
                lambda requests, reps: jnp.full(
                    (requests.shape[0], snap.num_clusters), -1, jnp.int32
                )
            ],
        )
        want = dev_eng._schedule_host(
            problems, [dev_eng._compiled(p.placement) for p in problems]
        )
        for w, g in zip(want, got):
            assert w.success == g.success
            assert dict(w.clusters) == dict(g.clusters), w.key


class TestIncrementalNodeCache:
    def test_event_stream_matches_full_repack(self):
        """NodeCache (incremental AddPod/RemovePod/Upsert/Remove — the
        kube-scheduler cache analogue) must answer identically to a fresh
        NodeSnapshot repack of the surviving nodes after every event."""
        from karmada_tpu.estimator import AccurateEstimator
        from karmada_tpu.estimator.accurate import NodeCache, NodeSnapshot, NodeState

        dims = ["cpu", "memory", "pods"]
        rng = np.random.default_rng(4)

        def mk_node(i):
            return NodeState(
                name=f"n{i}",
                allocatable={"cpu": int(rng.integers(4_000, 64_000)),
                             "memory": int(rng.integers(8, 256)) << 30,
                             "pods": int(rng.integers(30, 110))},
                num_pods=int(rng.integers(0, 10)),
            )

        cache = NodeCache(dims, [mk_node(i) for i in range(12)])
        est_inc = AccurateEstimator("m1", cache)
        live_names = [f"n{i}" for i in range(12)]
        next_id = 12
        reqs = np.stack([
            np.array([int(rng.integers(100, 3000)),
                      int(rng.integers(1, 8)) << 30, 1], np.int64)
            for _ in range(6)
        ])
        for step in range(120):
            ev = rng.random()
            if ev < 0.45 and live_names:  # pod add
                cache.add_pod(str(rng.choice(live_names)),
                              {"cpu": 250, "memory": 512 << 20})
            elif ev < 0.65 and live_names:  # pod remove
                cache.remove_pod(str(rng.choice(live_names)),
                                 {"cpu": 250, "memory": 512 << 20})
            elif ev < 0.8:  # node joins
                node = mk_node(next_id)
                next_id += 1
                cache.upsert_node(node)
                live_names.append(node.name)
            elif ev < 0.92 and len(live_names) > 2:  # node leaves
                gone = live_names.pop(int(rng.integers(len(live_names))))
                cache.remove_node(gone)
            else:  # node capacity update
                if live_names:
                    name = str(rng.choice(live_names))
                    node = cache.nodes[cache._rows[name]]
                    node.allocatable["cpu"] = int(rng.integers(4_000, 64_000))
                    cache.upsert_node(node)
            if step % 10 != 9:
                continue
            # referent: full repack of the live nodes (copied so the
            # referent cannot alias the cache's mutable NodeStates)
            import copy

            ref_snap = NodeSnapshot(
                [copy.deepcopy(n) for n in cache.live_nodes()], dims
            )
            est_ref = AccurateEstimator("m1", ref_snap)
            got = est_inc.max_available_replicas(None, reqs)
            want = est_ref.max_available_replicas(None, reqs)
            assert np.array_equal(got, want), f"step {step}: {got} != {want}"
