"""Per-binary flag surfaces (cmd/*/app/options contract)."""

import pytest

from karmada_tpu.utils.features import FAILOVER, feature_gate
from karmada_tpu.utils.flags import (
    IN_TREE_PLUGINS,
    parse_agent_flags,
    parse_controller_manager_flags,
    parse_descheduler_flags,
    parse_scheduler_flags,
    parse_star_list,
    _duration,
)


class TestStarList:
    def test_star_enables_all(self):
        enabled, disabled = parse_star_list(["*"], IN_TREE_PLUGINS, "plugins")
        assert enabled == set(IN_TREE_PLUGINS) and not disabled

    def test_star_minus_disables_named(self):
        enabled, disabled = parse_star_list(
            ["*,-TaintToleration"], IN_TREE_PLUGINS, "plugins"
        )
        assert disabled == {"TaintToleration"}
        assert "ClusterAffinity" in enabled

    def test_explicit_list_enables_only_those(self):
        enabled, disabled = parse_star_list(
            ["ClusterAffinity,APIEnablement"], IN_TREE_PLUGINS, "plugins"
        )
        assert enabled == {"ClusterAffinity", "APIEnablement"}
        assert "TaintToleration" in disabled

    def test_unknown_name_rejected(self):
        with pytest.raises(ValueError, match="unknown plugins"):
            parse_star_list(["Bogus"], IN_TREE_PLUGINS, "plugins")


class TestSchedulerFlags:
    def test_reference_launch_args_parse(self):
        kwargs = parse_scheduler_flags([
            "--scheduler-name=my-scheduler",
            "--plugins=*,-ClusterLocality",
            "--enable-scheduler-estimator=true",
            "--scheduler-estimator-timeout=5s",
            "--leader-elect=false",
        ])
        assert kwargs["scheduler_name"] == "my-scheduler"
        assert kwargs["disabled_plugins"] == ("ClusterLocality",)
        assert kwargs["enable_scheduler_estimator"] is True
        assert kwargs["scheduler_estimator_timeout_seconds"] == 5.0

    def test_feature_gates_apply(self):
        before = feature_gate.enabled(FAILOVER)
        try:
            parse_scheduler_flags([f"--feature-gates={FAILOVER}=true"])
            assert feature_gate.enabled(FAILOVER)
        finally:
            feature_gate.set(FAILOVER, before)

    def test_flags_drive_engine_plugin_gate(self):
        """The parsed disable list reaches the engine exactly like the
        reference's --plugins wiring (scheduler.go:243-247)."""
        from karmada_tpu.scheduler import (
            BindingProblem, ClusterSnapshot, TensorScheduler,
        )
        from karmada_tpu.utils.builders import new_cluster, duplicated_placement
        from karmada_tpu.api.cluster import Taint

        kwargs = parse_scheduler_flags(["--plugins=*,-TaintToleration"])
        clusters = [
            new_cluster("m1"),
            new_cluster(
                "m2",
                taints=[Taint(key="k", value="v", effect="NoSchedule")],
            ),
        ]
        eng = TensorScheduler(
            ClusterSnapshot(clusters),
            disabled_plugins=kwargs["disabled_plugins"],
        )
        res = eng.schedule([
            BindingProblem(key="b", placement=duplicated_placement(),
                           replicas=1, requests={},
                           gvk="apps/v1/Deployment")
        ])[0]
        # with TaintToleration disabled the tainted cluster is feasible
        assert set(res.clusters) == {"m1", "m2"}


class TestOtherBinaries:
    def test_controller_manager_controllers_grammar(self):
        kwargs = parse_controller_manager_flags(
            ["--controllers=*,-remedy", "--failover-eviction-timeout=3m"]
        )
        assert "remedy" in kwargs["disabled_controllers"]
        assert kwargs["eviction_timeout"] == 180.0

    def test_descheduler_and_agent(self):
        d = parse_descheduler_flags(["--unschedulable-threshold=90s"])
        assert d["unschedulable_threshold"] == 90.0
        a = parse_agent_flags([
            "--cluster-name=member1",
            "--cluster-status-update-frequency=15s",
        ])
        assert a["cluster_name"] == "member1"
        assert a["status_update_frequency"] == 15.0


class TestDurations:
    def test_go_duration_grammar(self):
        assert _duration("500ms") == 0.5
        assert _duration("1h30m") == 5400.0
        assert _duration("3s") == 3.0
        with pytest.raises(ValueError):
            _duration("3parsecs")
