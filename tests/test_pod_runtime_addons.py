"""Pod runtime seam + addons + registration flow.

Covers the reference surfaces:
- estimator server/replica/replica.go:43-77 (unschedulable-pod counting from
  PodScheduled=False/Unschedulable conditions past a threshold)
- pkg/karmadactl/{logs,exec,attach} through clusters/{name}/proxy
  (pkg/registry/cluster/storage/proxy.go:41-102)
- pkg/karmadactl/register + token create (kubeadm-style token -> CSR flow),
  agent-CSR-approving + cert-rotation controllers
- pkg/karmadactl/addons (estimator/descheduler/search/metrics-adapter)
- pkg/servicenameresolutiondetector (coredns-failure detector example)
"""

import pytest

from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.cli import (
    cmd_addons,
    cmd_attach,
    cmd_exec,
    cmd_local_up,
    cmd_logs,
    cmd_register,
    cmd_token_create,
)
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.api.policy import (
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
)
from karmada_tpu.utils.builders import (
    duplicated_placement,
    new_cluster,
    new_deployment,
)
from karmada_tpu.utils.member import MemberCluster


def _policy(name, placement):
    return PropagationPolicy(
        meta=ObjectMeta(name=name, namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=placement,
        ),
    )


class TestUnschedulableCounting:
    def test_counts_pods_past_threshold(self):
        m = MemberCluster("m1")
        m.add_pod("default", "web-1", owner_key="default/web")
        m.add_pod("default", "web-2", owner_key="default/web")
        m.add_pod("default", "web-3", owner_key="default/web")
        m.mark_pod_unschedulable("default", "web-1", since=100.0)
        m.mark_pod_unschedulable("default", "web-2", since=195.0)
        # at t=200: web-1 stuck 100s (counted), web-2 stuck 5s (below the
        # 60s threshold), web-3 scheduled fine
        assert m.count_unschedulable(now=200.0) == {"default/web": 1}
        # at t=300 both count
        assert m.count_unschedulable(now=300.0) == {"default/web": 2}

    def test_manual_override_merges_max(self):
        m = MemberCluster("m1")
        m.add_pod("default", "a-1", owner_key="default/a")
        m.mark_pod_unschedulable("default", "a-1", since=0.0)
        m.unschedulable_replicas["default/a"] = 5
        m.unschedulable_replicas["default/b"] = 2
        counts = m.count_unschedulable(now=1000.0)
        assert counts == {"default/a": 5, "default/b": 2}

    def test_descheduler_uses_pod_conditions(self):
        now = [0.0]
        cp = ControlPlane(enable_descheduler=True, clock=lambda: now[0])
        for name in ("m1", "m2"):
            cp.join_cluster(new_cluster(name))
        dep = new_deployment("web", replicas=4)
        cp.store.apply(dep)
        cp.store.apply(_policy("web-pp", duplicated_placement()))
        cp.settle()
        rb = cp.store.list("ResourceBinding")[0]
        assert {tc.name for tc in rb.spec.clusters} == {"m1", "m2"}
        # two replicas stuck unschedulable on m1 for > threshold
        m1 = cp.members.get("m1")
        m1.add_pod("default", "web-x", owner_key="default/web")
        m1.add_pod("default", "web-y", owner_key="default/web")
        m1.mark_pod_unschedulable("default", "web-x", since=0.0)
        m1.mark_pod_unschedulable("default", "web-y", since=0.0)
        now[0] = 120.0
        cp.settle()
        rb = cp.store.list("ResourceBinding")[0]
        by_cluster = {tc.name: tc.replicas for tc in rb.spec.clusters}
        # duplicated placement re-broadcasts; the descheduler shrank m1
        # then the scheduler restored it (always-reschedule for Duplicated)
        # — the observable effect is the reduction happened
        assert by_cluster["m2"] == 4


class TestPodSubresources:
    def _plane(self):
        cp = cmd_local_up(2)
        m = cp.members.get("member1")
        m.add_pod("default", "web-1", owner_key="default/web")
        m.append_pod_log("default", "web-1", "line1")
        m.append_pod_log("default", "web-1", "line2")
        return cp, m

    def test_logs_via_proxy(self):
        cp, _ = self._plane()
        assert cmd_logs(cp, "member1", "default", "web-1") == ["line1", "line2"]
        assert cmd_logs(cp, "member1", "default", "web-1", tail=1) == ["line2"]
        assert cmd_logs(cp, "member1", "default", "web-1", tail=0) == []
        assert cmd_attach(cp, "member1", "default", "web-1") == ["line1", "line2"]

    def test_exec_default_and_custom_handler(self):
        cp, m = self._plane()
        out = cmd_exec(cp, "member1", "default", "web-1", ["ls", "/"])
        assert out == {"stdout": "ls /", "rc": 0}
        m.exec_handler = lambda pod, cmd: {
            "stdout": f"{pod.meta.name}:{cmd[0]}", "rc": 7,
        }
        out = cmd_exec(cp, "member1", "default", "web-1", ["id"])
        assert out == {"stdout": "web-1:id", "rc": 7}

    def test_missing_pod_and_unknown_cluster(self):
        cp, _ = self._plane()
        with pytest.raises(RuntimeError):
            cmd_logs(cp, "member1", "default", "nope")
        with pytest.raises(RuntimeError):
            cmd_logs(cp, "ghost", "default", "web-1")

    def test_unreachable_member_errors(self):
        cp, m = self._plane()
        m.reachable = False
        with pytest.raises(RuntimeError):
            cmd_logs(cp, "member1", "default", "web-1")


class TestRegistrationFlow:
    def test_token_register_issues_cert(self):
        cp = ControlPlane()
        tok = cmd_token_create(cp)
        cluster = cmd_register(cp, "pull1", token=tok)
        assert cluster.spec.sync_mode == "Pull"
        assert "pull1" in cp.authority.certificates
        assert cp.authority.approved_csrs == ["pull1"]

    def test_bad_token_rejected(self):
        cp = ControlPlane()
        with pytest.raises(PermissionError):
            cmd_register(cp, "pull1", token="aaa.bbb")
        assert cp.store.get("Cluster", "pull1") is None

    def test_rotation_sweep(self):
        now = [0.0]
        cp = ControlPlane(clock=lambda: now[0])
        tok = cmd_token_create(cp)
        cmd_register(cp, "pull1", token=tok)
        first = cp.authority.certificates["pull1"].serial
        cp.settle()
        assert cp.authority.certificates["pull1"].serial == first  # fresh
        # jump past 80% of the cert lifetime -> rotation threshold
        now[0] = cp.authority.CERT_TTL * 0.85
        cp.settle()
        assert cp.authority.certificates["pull1"].serial != first


class TestAddons:
    def test_estimator_toggle_wires_scheduler(self):
        cp = cmd_local_up(2)
        assert cp.scheduler.extra_estimators == []
        cmd_addons(cp, enable=["karmada-scheduler-estimator"])
        assert len(cp.scheduler.extra_estimators) == 1
        assert cp.estimators.get("member1") is not None
        cmd_addons(cp, disable=["karmada-scheduler-estimator"])
        assert cp.scheduler.extra_estimators == []
        assert cp.estimators.get("member1") is None

    def test_estimator_enable_covers_later_joins(self):
        cp = ControlPlane()
        cmd_addons(cp, enable=["karmada-scheduler-estimator"])
        cp.join_cluster(new_cluster("late"))
        assert cp.estimators.get("late") is not None

    def test_metrics_adapter_toggle(self):
        cp = cmd_local_up(1)
        cmd_addons(cp, disable=["karmada-metrics-adapter"])
        assert cp.metrics_adapter is None
        cmd_addons(cp, enable=["karmada-metrics-adapter"])
        assert cp.metrics_adapter is not None

    def test_unknown_addon_rejected(self):
        cp = ControlPlane()
        with pytest.raises(ValueError):
            cmd_addons(cp, enable=["karmada-dashboard"])

    def test_search_toggle_drops_and_rebuilds_cache(self):
        from karmada_tpu.search.registry import (
            ResourceRegistry,
            ResourceRegistrySpec,
        )

        cp = cmd_local_up(1)
        member = cp.members.get("member1")
        member.apply(
            Resource(
                api_version="v1", kind="ConfigMap",
                meta=ObjectMeta(namespace="default", name="cm1"),
            )
        )
        cp.store.apply(
            ResourceRegistry(
                meta=ObjectMeta(name="rr1"),
                spec=ResourceRegistrySpec(
                    resource_selectors=[{"apiVersion": "v1", "kind": "ConfigMap"}]
                ),
            )
        )
        cp.settle()
        assert cp.search.cache.list("v1/ConfigMap")
        cmd_addons(cp, disable=["karmada-search"])
        assert not cp.search.cache.list("v1/ConfigMap")
        assert not cp.search.enabled
        cmd_addons(cp, enable=["karmada-search"])
        cp.settle()
        assert cp.search.enabled
        assert cp.search.cache.list("v1/ConfigMap")


class TestDetectorLifecycle:
    def test_stale_detector_deactivated_on_unjoin(self):
        cp = ControlPlane()
        cp.join_cluster(new_cluster("m1"))
        det1 = cp.add_sn_detector("m1", probe=lambda: False)
        cp.settle()
        cp.unjoin_cluster("m1")
        assert det1.active is False
        # rejoin with a healthy probe: only the new detector writes
        cp.join_cluster(new_cluster("m1"))
        cp.add_sn_detector("m1", probe=lambda: True)
        cp.settle()
        cluster = cp.store.get("Cluster", "m1")
        conds = {c.type: c.status for c in cluster.status.conditions}
        assert conds["ServiceDomainNameResolutionReady"] is True

    def test_replacing_detector_deactivates_previous(self):
        cp = ControlPlane()
        cp.join_cluster(new_cluster("m1"))
        det1 = cp.add_sn_detector("m1", probe=lambda: False)
        det2 = cp.add_sn_detector("m1", probe=lambda: True)
        assert det1.active is False and det2.active is True
        cp.settle()
        cluster = cp.store.get("Cluster", "m1")
        conds = {c.type: c.status for c in cluster.status.conditions}
        assert conds["ServiceDomainNameResolutionReady"] is True


class TestServiceNameResolutionDetector:
    def _dns_service(self):
        return Resource(
            api_version="v1", kind="Service",
            meta=ObjectMeta(namespace="kube-system", name="kube-dns"),
        )

    def test_condition_follows_probe(self):
        cp = ControlPlane()
        cp.join_cluster(new_cluster("m1"))
        member = cp.members.get("m1")
        member.apply(self._dns_service())
        cp.add_sn_detector("m1")
        cp.settle()
        cluster = cp.store.get("Cluster", "m1")
        conds = {c.type: c.status for c in cluster.status.conditions}
        assert conds["ServiceDomainNameResolutionReady"] is True
        # coredns vanishes -> condition flips False
        member.delete("v1/Service", "kube-system", "kube-dns")
        cp.settle()
        cluster = cp.store.get("Cluster", "m1")
        conds = {c.type: c.status for c in cluster.status.conditions}
        assert conds["ServiceDomainNameResolutionReady"] is False

    def test_feeds_remedy_traffic_control(self):
        from karmada_tpu.controllers.remedy import (
            REMEDY_ACTIONS_ANNOTATION,
            DecisionMatch,
            Remedy,
            RemedySpec,
        )

        cp = ControlPlane()
        cp.join_cluster(new_cluster("m1"))
        cp.add_sn_detector("m1")  # no kube-dns -> False
        cp.store.apply(
            Remedy(
                meta=ObjectMeta(name="dns-remedy"),
                spec=RemedySpec(decision_matches=[DecisionMatch()]),
            )
        )
        cp.settle()
        cluster = cp.store.get("Cluster", "m1")
        assert (
            cluster.meta.annotations.get(REMEDY_ACTIONS_ANNOTATION)
            == "TrafficControl"
        )
        # resolution recovers -> remedy action withdrawn
        cp.members.get("m1").apply(self._dns_service())
        cp.settle()
        cluster = cp.store.get("Cluster", "m1")
        assert REMEDY_ACTIONS_ANNOTATION not in cluster.meta.annotations
