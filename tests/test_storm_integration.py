"""Integration torture test: a mixed fleet under churn.

Drives most of the stack in one scenario — mixed strategies and kinds,
overrides, dependencies, a cluster failure with failover + graceful
eviction, descheduler reclaim, a rebalancer storm, and teardown — and
asserts the control plane settles to a consistent state at every stage
(the in-proc analogue of running several reference e2e suites against one
long-lived environment)."""

from karmada_tpu import cli
from karmada_tpu.api import (
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
)
from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.controllers import execution_namespace
from karmada_tpu.controllers.extras import (
    ObjectReferenceSelector,
    WorkloadRebalancer,
    WorkloadRebalancerSpec,
)
from karmada_tpu.utils.builders import (
    duplicated_placement,
    dynamic_weight_placement,
    new_cluster,
    new_deployment,
    static_weight_placement,
)
from karmada_tpu.utils.features import FAILOVER, feature_gate


def policy(name, placement, kind="Deployment", propagate_deps=False):
    return PropagationPolicy(
        meta=ObjectMeta(name=name, namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind=kind,
                                 name=name.removesuffix("-policy"))
            ],
            placement=placement,
            propagate_deps=propagate_deps,
        ),
    )


def binding_totals(cp):
    out = {}
    for rb in cp.store.list("ResourceBinding"):
        out[rb.meta.name] = {tc.name: tc.replicas for tc in rb.spec.clusters}
    return out


def test_fleet_storm_settles_consistently():
    feature_gate.set(FAILOVER, True)
    clock = [10_000.0]
    try:
        cp = cli.cmd_init(clock=lambda: clock[0])
        for i in range(1, 5):
            cli.cmd_join(cp, f"member{i}")
        cp.settle()

        # --- mixed workloads -------------------------------------------
        cp.store.apply(new_deployment("web", replicas=12))
        cp.store.apply(policy("web-policy", dynamic_weight_placement()))
        cp.store.apply(new_deployment("cache", replicas=4))
        cp.store.apply(policy("cache-policy", static_weight_placement(
            {"member1": 3, "member2": 1})))
        cp.store.apply(new_deployment("agent", replicas=2))
        cp.store.apply(policy("agent-policy", duplicated_placement()))
        cp.settle()

        totals = binding_totals(cp)
        assert sum(totals["web-deployment"].values()) == 12
        assert totals["cache-deployment"] == {"member1": 3, "member2": 1}
        assert all(r == 2 for r in totals["agent-deployment"].values())
        assert len(totals["agent-deployment"]) == 4

        # member-side objects exist everywhere the bindings say
        for name, placed in totals.items():
            dep = name.removesuffix("-deployment")
            for cluster in placed:
                assert cp.members.get(cluster).get(
                    "apps/v1/Deployment", "default", dep) is not None, (name, cluster)

        # --- cluster failure: failover + graceful eviction -------------
        victim_load = totals["web-deployment"]
        cp.members.get("member3").reachable = False
        clock[0] += 60
        cp.settle()
        totals = binding_totals(cp)
        assert "member3" not in totals["web-deployment"]
        assert sum(totals["web-deployment"].values()) == 12  # rehomed
        # duplicated bindings drop the dead cluster too
        assert "member3" not in totals["agent-deployment"]

        # --- recovery: the cluster rejoins scheduling ------------------
        cp.members.get("member3").reachable = True
        clock[0] += 60
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/agent-deployment")
        # member statuses are never reported in this harness, so replacement
        # health stays Unknown and the graceful-eviction task is faithfully
        # HELD (capacity is not dropped before the replacement proves out);
        # the ClusterEviction filter keeps member3 out while the task lives
        assert any(t.from_cluster == "member3"
                   for t in rb.spec.graceful_eviction_tasks)
        totals = binding_totals(cp)
        assert "member3" not in totals["agent-deployment"]

        # ... until the eviction timeout elapses, which drains the task and
        # lets the recovered cluster schedule again
        clock[0] += 700  # > the 600s default eviction timeout
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/agent-deployment")
        assert not rb.spec.graceful_eviction_tasks
        totals = binding_totals(cp)
        # duplicated placements re-expand; divided stay steady (no churn)
        assert "member3" in totals["agent-deployment"]
        assert sum(totals["web-deployment"].values()) == 12

        # --- rebalancer storm: every divided binding recomputes --------
        cp.store.apply(WorkloadRebalancer(
            meta=ObjectMeta(name="storm"),
            spec=WorkloadRebalancerSpec(workloads=[
                ObjectReferenceSelector(kind="Deployment", name="web"),
            ]),
        ))
        clock[0] += 5
        cp.settle()
        totals = binding_totals(cp)
        # fresh reassignment may now use member3 again; totals preserved
        assert sum(totals["web-deployment"].values()) == 12
        rebalancer = cp.store.get("WorkloadRebalancer", "storm")
        assert rebalancer.status.observed_workloads[0]["result"] == "Successful"

        # --- full teardown ---------------------------------------------
        cli.cmd_deinit(cp)
        for i in range(1, 5):
            assert cp.store.get("Cluster", f"member{i}") is None
    finally:
        feature_gate.set(FAILOVER, False)


def test_dependencies_follow_moving_workload():
    """propagateDeps + movement: when the parent workload's placement moves
    (fresh rebalance after a new cluster joins), the attached dependency
    bindings must re-shadow the NEW clusters and the dependency must land
    on them (dependencies_distributor.go RequiredBy shadow updates)."""
    clock = [20_000.0]
    cp = cli.cmd_init(clock=lambda: clock[0])
    cli.cmd_join(cp, "member1")
    cp.settle()
    cp.store.apply(Resource(
        api_version="v1", kind="ConfigMap",
        meta=ObjectMeta(name="web-config", namespace="default"),
        spec={"data": {"k": "v"}},
    ))
    dep = new_deployment("web", replicas=2)
    dep.spec["template"]["spec"]["volumes"] = [
        {"name": "cfg", "configMap": {"name": "web-config"}}
    ]
    cp.store.apply(dep)
    pol = policy("web-policy", static_weight_placement({"member1": 1}))
    pol.spec.propagate_deps = True
    cp.store.apply(pol)
    cp.settle()
    assert cp.members.get("member1").get(
        "v1/ConfigMap", "default", "web-config") is not None

    # placement moves to a newly joined cluster
    cli.cmd_join(cp, "member2")
    cp.settle()
    pol.spec.placement = static_weight_placement({"member2": 1})
    cp.store.apply(pol)
    cp.settle()
    assert cp.members.get("member2").get(
        "apps/v1/Deployment", "default", "web") is not None
    # the dependency followed the workload to member2
    assert cp.members.get("member2").get(
        "v1/ConfigMap", "default", "web-config") is not None
    # ... and was withdrawn from the abandoned cluster along with the parent
    assert cp.members.get("member1").get(
        "apps/v1/Deployment", "default", "web") is None
    assert cp.members.get("member1").get(
        "v1/ConfigMap", "default", "web-config") is None
