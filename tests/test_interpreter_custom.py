"""Declarative interpreter customizations + hpa marker/syncer/auth tests."""

from karmada_tpu.api import PropagationPolicy, PropagationSpec, ResourceSelector
from karmada_tpu.api.autoscaling import FederatedHPA, FederatedHPASpec, ScaleTargetRef
from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.controllers.hpa_sync import HPA_TARGET_LABEL
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.interpreter.declarative import (
    CustomizationRules,
    ResourceInterpreterCustomization,
)
from karmada_tpu.utils.builders import (
    duplicated_placement,
    dynamic_weight_placement,
    new_cluster,
    new_deployment,
)


def make_plane(n=2):
    cp = ControlPlane()
    for i in range(1, n + 1):
        cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
    cp.settle()
    return cp


def crd_workload(name="wf1", replicas=6):
    return Resource(
        api_version="example.io/v1",
        kind="Workflow",
        meta=ObjectMeta(name=name, namespace="default"),
        spec={
            "parallelism": {"workers": replicas},
            "resources": {"cpu": "500m"},
            "configRef": "wf-config",
        },
        status={},
    )


def customization():
    return ResourceInterpreterCustomization(
        meta=ObjectMeta(name="workflow-rules"),
        target_api_version="example.io/v1",
        target_kind="Workflow",
        rules=CustomizationRules(
            replica_path="parallelism.workers",
            requests_path="resources",
            status_paths=["phase", "readyWorkers"],
            health=[{"path": "phase", "op": "==", "value": "Running"}],
            status_aggregation={"readyWorkers": "sum"},
            dependencies=[
                {"kind": "ConfigMap", "api_version": "v1", "name_path": "configRef"}
            ],
        ),
    )


class TestDeclarativeCustomization:
    def test_crd_scheduling_via_declared_replicas(self):
        cp = make_plane(2)
        for m in cp.members.names():
            cp.members.get(m).api_enablements.append("example.io/v1/Workflow")
        cp.settle()
        cp.store.apply(customization())
        cp.store.apply(crd_workload(replicas=6))
        cp.store.apply(
            PropagationPolicy(
                meta=ObjectMeta(name="wf", namespace="default"),
                spec=PropagationSpec(
                    resource_selectors=[
                        ResourceSelector(api_version="example.io/v1", kind="Workflow")
                    ],
                    placement=dynamic_weight_placement(),
                ),
            )
        )
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/wf1-workflow")
        assert rb is not None and rb.spec.replicas == 6
        assert rb.spec.replica_requirements.resource_request["cpu"] == 500
        assert sum(tc.replicas for tc in rb.spec.clusters) == 6
        # ReviseReplica wrote the divided count through the declared path
        for tc in rb.spec.clusters:
            obj = cp.members.get(tc.name).get("example.io/v1/Workflow", "default", "wf1")
            assert obj.spec["parallelism"]["workers"] == tc.replicas

    def test_health_and_status_aggregation(self):
        cp = make_plane(2)
        for m in cp.members.names():
            cp.members.get(m).api_enablements.append("example.io/v1/Workflow")
        cp.settle()
        cp.store.apply(customization())
        cp.store.apply(crd_workload(replicas=4))
        cp.store.apply(
            PropagationPolicy(
                meta=ObjectMeta(name="wf", namespace="default"),
                spec=PropagationSpec(
                    resource_selectors=[
                        ResourceSelector(api_version="example.io/v1", kind="Workflow")
                    ],
                    placement=dynamic_weight_placement(),
                ),
            )
        )
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/wf1-workflow")
        for tc in rb.spec.clusters:
            cp.members.get(tc.name).set_workload_status(
                "example.io/v1/Workflow", "default", "wf1",
                {"phase": "Running", "readyWorkers": tc.replicas},
            )
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/wf1-workflow")
        assert all(i.health == "Healthy" for i in rb.status.aggregated_status)
        template = cp.store.get("Resource", "default/wf1")
        assert template.status.get("readyWorkers") == 4

    def test_dependency_declared_path(self):
        cp = make_plane(1)
        cp.store.apply(customization())
        cp.settle()
        deps = cp.interpreter.get_dependencies(crd_workload())
        assert [(d.kind, d.name) for d in deps] == [("ConfigMap", "wf-config")]

    def test_deregistration_on_delete(self):
        cp = make_plane(1)
        cp.store.apply(customization())
        cp.settle()
        assert cp.interpreter.get_replicas(crd_workload())[0] == 6
        cp.store.delete("ResourceInterpreterCustomization", "workflow-rules")
        cp.settle()
        assert cp.interpreter.get_replicas(crd_workload())[0] == 0  # no handler


def make_hpa_sync_plane(n=2):
    cp = ControlPlane(enable_member_hpa_sync=True)
    for i in range(1, n + 1):
        cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
    cp.settle()
    return cp


class TestHpaMarkerAndSyncer:
    def test_marker_labels_target(self):
        cp = make_hpa_sync_plane(1)
        cp.store.apply(new_deployment("web", replicas=2))
        cp.store.apply(
            FederatedHPA(
                meta=ObjectMeta(name="web-hpa", namespace="default"),
                spec=FederatedHPASpec(
                    scale_target_ref=ScaleTargetRef(kind="Deployment", name="web")
                ),
            )
        )
        cp.settle()
        template = cp.store.get("Resource", "default/web")
        assert template.meta.labels[HPA_TARGET_LABEL] == "default/web-hpa"

    def test_replicas_synced_from_members(self):
        cp = make_hpa_sync_plane(2)
        cp.store.apply(new_deployment("web", replicas=4))
        cp.store.apply(
            PropagationPolicy(
                meta=ObjectMeta(name="p", namespace="default"),
                spec=PropagationSpec(
                    resource_selectors=[
                        ResourceSelector(api_version="apps/v1", kind="Deployment")
                    ],
                    placement=duplicated_placement(),
                ),
            )
        )
        cp.store.apply(
            FederatedHPA(
                meta=ObjectMeta(name="web-hpa", namespace="default"),
                spec=FederatedHPASpec(
                    scale_target_ref=ScaleTargetRef(kind="Deployment", name="web")
                ),
            )
        )
        cp.settle()
        # member-side HPAs scaled the deployments up
        for name in ("member1", "member2"):
            obj = cp.members.get(name).get("apps/v1/Deployment", "default", "web")
            obj.spec["replicas"] = 5
            cp.members.get(name).apply(obj)
        cp.settle()
        template = cp.store.get("Resource", "default/web")
        assert template.spec["replicas"] == 10


class TestUnifiedAuth:
    def test_rbac_work_created_per_cluster(self):
        cp = make_plane(2)
        for name in ("member1", "member2"):
            work = cp.store.get("Work", f"karmada-es-{name}/unified-auth")
            assert work is not None
            kinds = [w.kind for w in work.spec.workload]
            assert kinds == ["ClusterRole", "ClusterRoleBinding"]
