"""Concurrency torture for the Store/worker/watch runtime — the -race tier.

The reference runs its whole suite under the Go race detector
(Makefile:119); CPython has no equivalent, so this harness substitutes
adversarial scheduling (tiny switch interval, many writer threads) plus
SETTLE INVARIANTS that any lost update must violate:

- every assigned resource_version is unique (the rv counter is the
  store's linearization point);
- for every surviving key, a watch event carrying its FINAL
  resource_version was delivered (level-triggered controllers converge
  only if the last write's notification is never lost);
- a reconciler driven by watch events converges to exactly the final
  store state for every key (the dirty-bit contract of Worker.enqueue).

The harness must actually detect races: `test_harness_detects_injected_
lost_update` runs the same invariants against a Store whose apply skips
the lock and asserts violations ARE found — a checker that cannot fail
proves nothing.
"""

from __future__ import annotations

import sys
import threading

import pytest

from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.utils import Runtime, Store
from karmada_tpu.utils.store import Event

N_THREADS = 6
N_KEYS = 48
OPS_PER_THREAD = 2500


def _obj(key: str, payload: int) -> Resource:
    ns, _, name = key.partition("/")
    return Resource(
        api_version="v1",
        kind="ConfigMap",
        meta=ObjectMeta(name=name, namespace=ns),
        spec={"payload": payload},
    )


class _Recorder:
    """Thread-safe watch recorder."""

    def __init__(self):
        self.lock = threading.Lock()
        self.events: list[tuple[str, int, int]] = []  # key, rv, payload

    def __call__(self, event: Event) -> None:
        obj = event.obj
        with self.lock:
            self.events.append(
                (event.key, obj.meta.resource_version,
                 obj.spec.get("payload", -1) if event.type != "Deleted" else -1)
            )


def _hammer(store: Store, seed: int, barrier: threading.Barrier) -> list[int]:
    """One writer thread: applies (and occasional deletes) over shared keys;
    returns the rvs it observed being assigned."""
    rng_state = seed * 2654435761 % 2**32
    rvs = []
    barrier.wait()
    for i in range(OPS_PER_THREAD):
        rng_state = (1103515245 * rng_state + 12345) % 2**31
        key = f"ns/k{rng_state % N_KEYS}"
        obj = _obj(key, payload=seed * OPS_PER_THREAD + i)
        applied = store.apply(obj)
        rvs.append(applied.meta.resource_version)
    return rvs


def _run_torture(store: Store):
    recorder = _Recorder()
    store.watch("Resource", recorder)
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)
    try:
        barrier = threading.Barrier(N_THREADS)
        results: list[list[int]] = [None] * N_THREADS  # type: ignore
        threads = []
        for t in range(N_THREADS):
            def run(t=t):
                results[t] = _hammer(store, t + 1, barrier)
            th = threading.Thread(target=run)
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
    finally:
        sys.setswitchinterval(old)
    all_rvs = [rv for rvs in results for rv in rvs]
    violations = []
    if len(set(all_rvs)) != len(all_rvs):
        violations.append(
            f"duplicate resource_versions: {len(all_rvs) - len(set(all_rvs))}"
        )
    # final-notification invariant: for every surviving key, some event
    # carried its final resource_version and payload
    with recorder.lock:
        seen = {(k, rv, p) for k, rv, p in recorder.events}
    for obj in store.list("Resource"):
        key = obj.meta.namespaced_name
        final = (key, obj.meta.resource_version, obj.spec.get("payload", -1))
        if final not in seen:
            violations.append(f"lost final event for {key}: {final}")
    return violations


class TestStoreTorture:
    def test_concurrent_writers_keep_invariants(self):
        violations = _run_torture(Store())
        assert not violations, violations[:5]

    def test_harness_detects_injected_lost_update(self):
        """The same invariants must FAIL against a store whose apply skips
        the lock — otherwise the checker is vacuous."""

        class RacyStore(Store):
            def apply(self, obj):
                kind = obj.KIND if hasattr(obj, "KIND") else obj.kind
                key = obj.meta.namespaced_name
                bucket = self._buckets.setdefault(kind, {})
                existing = bucket.get(key)
                rv = self._rv
                if rv % 7 == 0:
                    import time as _t

                    _t.sleep(0)  # yield: forces interleaving in the window
                self._rv = rv + 1  # classic lost update
                obj.meta.resource_version = self._rv
                if not obj.meta.uid:
                    obj.meta.uid = existing.meta.uid if existing else "u"
                bucket[key] = obj
                self._deliver(
                    Event(
                        "Modified" if existing is not None else "Added",
                        kind, key, obj,
                    )
                )
                return obj

        detected = False
        for _ in range(5):  # adversarial scheduling is probabilistic
            if _run_torture(RacyStore()):
                detected = True
                break
        assert detected, (
            "harness failed to detect the injected lost-update race"
        )


class TestWorkerTorture:
    def test_event_driven_reconciler_converges_under_concurrent_writers(self):
        """Level-triggered convergence: while writer threads mutate the
        store, a cooperative reconciler driven by watch events must end
        with exactly the final store state for every key."""
        store = Store()
        runtime = Runtime()
        last_seen: dict[str, int] = {}

        def reconcile(key):
            obj = store.get("Resource", key)
            if obj is None:
                last_seen.pop(key, None)
            else:
                last_seen[key] = obj.spec.get("payload", -1)
            return "done"

        worker = runtime.new_worker("torture", reconcile)
        store.watch("Resource", lambda e: worker.enqueue(e.key))

        old = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)
        try:
            barrier = threading.Barrier(N_THREADS + 1)
            threads = [
                threading.Thread(target=_hammer, args=(store, t + 1, barrier))
                for t in range(N_THREADS)
            ]
            for th in threads:
                th.start()
            barrier.wait()
            # drain cooperatively WHILE writers run (interleaved reconciles)
            while any(th.is_alive() for th in threads):
                runtime.run_until_settled(10_000)
            for th in threads:
                th.join()
        finally:
            sys.setswitchinterval(old)
        runtime.run_until_settled(10_000_000)
        want = {
            o.meta.namespaced_name: o.spec.get("payload", -1)
            for o in store.list("Resource")
        }
        assert last_seen == want

    def test_checkpoint_under_concurrent_writers_is_coherent(self, tmp_path):
        """Store.checkpoint taken mid-storm must deserialize into a store
        whose objects are internally consistent (the torn-snapshot fix)."""
        store = Store()
        stop = threading.Event()

        def writer(seed):
            i = 0
            while not stop.is_set():
                store.apply(_obj(f"ns/k{(seed * 7 + i) % 8}", payload=i))
                i += 1

        threads = [
            threading.Thread(target=writer, args=(t,)) for t in range(4)
        ]
        old = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)
        try:
            for th in threads:
                th.start()
            for round_i in range(25):
                path = str(tmp_path / f"snap{round_i}.pkl")
                store.checkpoint(path)
                restored = Store()
                n = restored.restore(path)
                assert n == len(restored.list("Resource"))
                for obj in restored.list("Resource"):
                    assert obj.meta.resource_version > 0
                    assert "payload" in obj.spec
        finally:
            stop.set()
            for th in threads:
                th.join()
            sys.setswitchinterval(old)
