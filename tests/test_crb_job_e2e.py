"""Cluster-scoped binding failover + Job completions division e2e."""

from karmada_tpu.api import PropagationSpec, ResourceSelector
from karmada_tpu.api.core import ObjectMeta, Resource
from karmada_tpu.api.policy import ClusterPropagationPolicy, PropagationPolicy
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.utils.builders import (
    dynamic_weight_placement,
    new_cluster,
)
from karmada_tpu.utils.features import FAILOVER, feature_gate


def make_plane(n=3):
    cp = ControlPlane()
    for i in range(1, n + 1):
        member = cp.join_cluster(new_cluster(f"member{i}", cpu="100", memory="200Gi"))
        member.api_enablements.append("rbac.authorization.k8s.io/v1/ClusterRole")
    cp.settle()
    return cp


class TestClusterScopedFailover:
    def test_crb_rehomes_on_cluster_failure(self):
        feature_gate.set(FAILOVER, True)
        try:
            cp = make_plane(3)
            role = Resource(
                api_version="rbac.authorization.k8s.io/v1",
                kind="ClusterRole",
                meta=ObjectMeta(name="ops"),
                spec={"rules": []},
            )
            cp.store.apply(role)
            cp.store.apply(
                ClusterPropagationPolicy(
                    meta=ObjectMeta(name="roles"),
                    spec=PropagationSpec(
                        resource_selectors=[
                            ResourceSelector(
                                api_version="rbac.authorization.k8s.io/v1",
                                kind="ClusterRole",
                            )
                        ],
                        placement=dynamic_weight_placement(),
                    ),
                )
            )
            cp.settle()
            crb = cp.store.get("ClusterResourceBinding", "ops-clusterrole")
            assert crb is not None and crb.spec.clusters
            # non-workload (replicas 0) lands on all clusters; kill one
            placed_before = {tc.name for tc in crb.spec.clusters}
            victim = sorted(placed_before)[0]
            cp.members.get(victim).reachable = False
            cp.settle()
            crb = cp.store.get("ClusterResourceBinding", "ops-clusterrole")
            assert victim not in {tc.name for tc in crb.spec.clusters}
        finally:
            feature_gate.set(FAILOVER, False)


class TestJobCompletions:
    def test_completions_divided_with_replicas(self):
        cp = make_plane(2)
        job = Resource(
            api_version="batch/v1",
            kind="Job",
            meta=ObjectMeta(name="indexer", namespace="default"),
            spec={
                "parallelism": 6,
                "completions": 10,  # non-divisible: exercises the ceil path
                "template": {"spec": {"containers": [
                    {"name": "work",
                     "resources": {"requests": {"cpu": "100m"}}}]}},
            },
        )
        cp.store.apply(job)
        cp.store.apply(
            PropagationPolicy(
                meta=ObjectMeta(name="jobs", namespace="default"),
                spec=PropagationSpec(
                    resource_selectors=[
                        ResourceSelector(api_version="batch/v1", kind="Job")
                    ],
                    placement=dynamic_weight_placement(),
                ),
            )
        )
        cp.settle()
        rb = cp.store.get("ResourceBinding", "default/indexer-job")
        assert rb.spec.replicas == 6  # parallelism is the replica field
        # hand-computed ceil(10 * r / 6) per possible per-cluster share
        expected_completions = {1: 2, 2: 4, 3: 5, 4: 7, 5: 9, 6: 10}
        total_parallelism = 0
        total_completions = 0
        for tc in rb.spec.clusters:
            obj = cp.members.get(tc.name).get("batch/v1/Job", "default", "indexer")
            assert obj is not None
            total_parallelism += obj.spec["parallelism"]
            assert obj.spec["completions"] == expected_completions[tc.replicas]
            total_completions += obj.spec["completions"]
        assert total_parallelism == 6
        assert total_completions >= 10  # ceil split over-provisions on ties
