"""Plane-process active-standby HA e2e (VERDICT r4 next #4).

The reference runs every binary --leader-elect active-standby against the
shared apiserver (cmd/scheduler/app/options/options.go:130-165); here the
deployment shape is: one store-bus process (python -m karmada_tpu.bus, the
apiserver+etcd role), TWO plane replicas (localup serve --connect-bus
--leader-elect) whose controller fleets run over StoreReplica mirrors, and
a pull-mode agent owning the member cluster. SIGKILLing the leader
mid-storm must hand leadership to the warm standby within a lease window,
the standby must finish scheduling the storm, and placements must converge
with every binding observed at its latest generation (the scheduler's
observed-generation guard is what makes a raced duplicate reconcile
idempotent — no double-scheduling).
"""

import json
import os
import signal
import sys
import time

import pytest

from karmada_tpu.api import PropagationPolicy, PropagationSpec, ResourceSelector
from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.bus.service import StoreReplica
from karmada_tpu.localup import scrape_line, spawn_child
from karmada_tpu.utils.builders import dynamic_weight_placement, new_deployment

LEASE = 2.0
RENEW = 1.0


def wait_for(predicate, timeout=30.0, interval=0.05, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture()
def ha_plane():
    procs = {}
    replica = None
    try:
        bus_proc = spawn_child(
            [sys.executable, "-m", "karmada_tpu.bus"]
        )
        procs["bus"] = bus_proc
        bus_port = int(scrape_line(bus_proc, r'"bus": (\d+)', timeout=60))
        target = f"127.0.0.1:{bus_port}"

        for name in ("pull1", "pull2"):
            procs[f"agent-{name}"] = spawn_child(
                [
                    sys.executable, "-m", "karmada_tpu.bus.agent",
                    "--target", target, "--cluster", name,
                    "--max-seconds", "180",
                ]
            )
        for ident in ("pa", "pb"):
            procs[ident] = spawn_child(
                [
                    sys.executable, "-m", "karmada_tpu.localup", "serve",
                    "--connect-bus", target, "--leader-elect",
                    "--identity", ident,
                    "--pull", "pull1", "--pull", "pull2",
                    "--lease-duration", str(LEASE),
                    "--renew-deadline", str(RENEW),
                    "--loop-interval", "0.02",
                ]
            )
        # both replicas booted (identity line printed after replica sync)
        for ident in ("pa", "pb"):
            scrape_line(procs[ident], r'"identity": "(p[ab])"', timeout=120)

        replica = StoreReplica(target)
        replica.start()
        assert replica.wait_synced(30)
        yield procs, replica
    finally:
        if replica is not None:
            replica.close()
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for p in procs.values():
            try:
                p.wait(timeout=5)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


def _policy():
    return PropagationPolicy(
        meta=ObjectMeta(name="ha-policy", namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=dynamic_weight_placement(),
        ),
    )


class TestPlaneHA:
    def test_leader_kill_mid_storm_standby_converges(self, ha_plane):
        procs, replica = ha_plane
        store = replica.store

        def holder():
            lease = store.get("Lease", "karmada-plane")
            return lease.holder_identity if lease is not None else ""

        wait_for(
            lambda: holder() in ("pa", "pb"), timeout=40,
            what="a plane replica to take the lease",
        )
        first = holder()
        standby = "pb" if first == "pa" else "pa"

        # member clusters Ready via the agents' leases
        def clusters_ready():
            ready = 0
            for name in ("pull1", "pull2"):
                cl = store.get("Cluster", name)
                if cl is None:
                    return False
                cond = next(
                    (c for c in cl.status.conditions if c.type == "Ready"),
                    None,
                )
                ready += bool(cond and cond.status)
            return ready == 2

        wait_for(clusters_ready, timeout=60, what="pull clusters Ready")

        # ---- storm phase 1: the elected leader schedules ----------------
        replica.apply(_policy())
        n1 = 40
        for i in range(n1):
            replica.apply(new_deployment(f"app{i}", replicas=4))

        def scheduled(n):
            rbs = [
                rb for rb in store.list("ResourceBinding")
                if rb.meta.namespace == "default"
            ]
            done = [
                rb for rb in rbs
                if rb.spec.clusters
                and sum(tc.replicas for tc in rb.spec.clusters) == 4
                and rb.status.scheduler_observed_generation
                == rb.meta.generation
            ]
            return len(done) >= n

        wait_for(
            lambda: scheduled(n1), timeout=60,
            what=f"{n1} bindings scheduled by {first}",
        )

        # ---- kill the leader mid-storm ----------------------------------
        more = [new_deployment(f"app{n1 + i}", replicas=4) for i in range(n1)]
        for d in more[: n1 // 2]:
            replica.apply(d)
        os.kill(procs[first].pid, signal.SIGKILL)
        for d in more[n1 // 2:]:
            replica.apply(d)

        # standby takes over within a lease window (+ scheduling slack)
        t_kill = time.time()
        wait_for(
            lambda: holder() == standby, timeout=LEASE * 4 + 10,
            what=f"standby {standby} to take the lease",
        )
        takeover = time.time() - t_kill
        # the lease expiry bounds takeover: duration + tick cadence slack
        assert takeover < LEASE * 4 + 5, takeover

        lease = store.get("Lease", "karmada-plane")
        assert lease.lease_transitions >= 1

        # ---- convergence: the standby finishes the storm ----------------
        wait_for(
            lambda: scheduled(2 * n1), timeout=90,
            what=f"all {2 * n1} bindings scheduled after failover",
        )
        # no flapping/double-scheduling: every binding sits at its latest
        # generation with a full assignment, exactly once per cluster
        for rb in store.list("ResourceBinding"):
            if rb.meta.namespace != "default":
                continue
            names = [tc.name for tc in rb.spec.clusters]
            assert len(names) == len(set(names)), names
            assert sum(tc.replicas for tc in rb.spec.clusters) == 4
            assert (
                rb.status.scheduler_observed_generation == rb.meta.generation
            )
