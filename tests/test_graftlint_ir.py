"""graftlint IR tier: tier-1 gate + seeded-mutant fixture corpus.

The gate: every registered kernel entry point abstractly traces across
its bucket grid and the IR001-IR005 invariants hold with ZERO
non-baselined findings. The mutant tests register intentionally-defective
kernels (tests/ir_mutant_kernels.py) as temporary entries and assert each
rule fires and fails the gate — a rule can never silently stop firing.

Everything here runs on the conftest CPU platform; tracing is abstract
(jax.make_jaxpr over ShapeDtypeStructs — no compiles, no data), so the
full grid audits in a few seconds.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.graftlint import ir as graft_ir  # noqa: E402
from tools.graftlint.ir import (  # noqa: E402
    ENTRY_POINTS,
    KernelEntry,
    KernelSpec,
    run_ir,
)

MUTANT_MODULE = "ir_mutant_kernels"
MUTANT_PATH = "tests/ir_mutant_kernels.py"


def mutant_entry(attr: str, in_shapes, *, path=MUTANT_PATH, statics=None,
                 manifest=None) -> KernelEntry:
    spec = KernelSpec("mutant", tuple(in_shapes), dict(statics or {}))
    return KernelEntry(
        name=attr, family="ops", module=MUTANT_MODULE, attr=attr,
        path=path, make_specs=lambda: [spec], manifest_kernel=manifest,
    )


VEC = (((8,), "int32"),)


# -- the tier-1 gate ---------------------------------------------------------


@pytest.fixture(scope="module")
def full_result():
    return run_ir(root=REPO, baseline="auto")


def test_full_grid_zero_findings(full_result):
    assert full_result.checked_files >= 20, "bucket grid shrank"
    assert not full_result.findings, (
        "IR findings on the committed kernels:\n"
        + "\n".join(f.render() for f in full_result.findings)
    )
    assert not full_result.baseline_errors
    assert not full_result.unused_baseline


def test_registry_covers_exports_and_fleet():
    # ops exports <-> IR registry (the docs drift gate's invariant)
    unregistered, stale = graft_ir.ops_registry_drift(REPO)
    assert not unregistered and not stale, (unregistered, stale)
    # every entry builds at least one spec, and the manifest-capable set
    # matches prewarm's kernel list exactly
    from karmada_tpu.scheduler import prewarm

    manifest_capable = set()
    for entry in ENTRY_POINTS.values():
        assert entry.make_specs(), f"{entry.name} has an empty spec grid"
        if entry.manifest_kernel:
            manifest_capable.add(entry.manifest_kernel)
    assert manifest_capable == set(prewarm._KERNELS)
    assert set(prewarm._jit_registry()) == set(prewarm._KERNELS)


# -- seeded mutants: each rule must fire and fail the gate -------------------


MUTANTS = {
    "IR001": mutant_entry("ir001_weak_promotion", VEC),
    "IR002": mutant_entry("ir002_host_callback", VEC),
    "IR003": mutant_entry("ir003_const_capture", VEC),
    "IR005": mutant_entry(
        "ir005_dropped_donation", (((4,), "int32"), ((8,), "int32"))
    ),
    # silently-UN-donated variants: the donated invar HAS a plausible
    # consumer, but a reshape/astype at the kernel boundary leaves no
    # identically-shaped output to alias into — exactly how a refactor
    # quietly doubles the resident's HBM footprint
    "IR005-reshape": mutant_entry(
        "ir005_reshaped_donation", (((8,), "int32"), ((8,), "int32"))
    ),
    "IR005-astype": mutant_entry(
        "ir005_astype_donation", (((8,), "int32"), ((8,), "int32"))
    ),
}


@pytest.mark.parametrize("rule_id", sorted(MUTANTS))
def test_mutant_fires_and_fails_gate(rule_id):
    entry = MUTANTS[rule_id]
    rule = rule_id.split("-")[0]
    result = run_ir(entries={entry.name: entry}, root=REPO, baseline=None)
    assert not result.ok, f"{rule_id} mutant passed the gate"
    hits = [f for f in result.findings if f.rule == rule]
    assert hits, f"{rule_id} did not fire on its mutant"
    assert all(f.path == MUTANT_PATH for f in hits)
    others = [f for f in result.findings if f.rule != rule]
    assert not others, [f.render() for f in others]


def test_sharded_specs_cover_fleet_kernels():
    # the sharded grid contract (ISSUE 9): every mesh-parameterized entry
    # point traces under a >=2-device spec, so IR001-IR005 — including
    # the donation audit over the row-sharded residents — cover the
    # PARTITIONED executables, not just the single-device forms
    for name in ("fleet_solve", "fleet_pass", "fleet_entries"):
        variants = {s.variant: s for s in ENTRY_POINTS[name].make_specs()}
        spec = variants.get("sharded-b2")
        assert spec is not None, f"{name} lost its sharded spec"
        assert spec.statics.get("mesh") == (("b", 2), ("c", 1))


def test_ir001_detail_names_dtype_and_primitive():
    entry = MUTANTS["IR001"]
    result = run_ir(entries={entry.name: entry}, root=REPO, baseline=None)
    details = {f.detail for f in result.findings}
    assert any(d.startswith("float64:") for d in details), details


def test_ir004_trace_drift_fires():
    # a registry spec that no longer matches the kernel signature IS the
    # IR004 finding (the drift that would break prewarm replay)
    entry = mutant_entry("ir002_host_callback", (((8,), "int32"),) * 3)
    result = run_ir(entries={entry.name: entry}, root=REPO, baseline=None)
    assert not result.ok
    assert [f.rule for f in result.findings] == ["IR004"]
    assert result.findings[0].detail.startswith("trace:")


def test_ir004_registry_coverage_drift(monkeypatch):
    from karmada_tpu.scheduler import prewarm

    monkeypatch.setattr(
        prewarm, "_KERNELS", tuple(
            k for k in prewarm._KERNELS if k != "fleet_bits"
        ),
    )
    result = run_ir(root=REPO, baseline=None)
    hits = [
        f for f in result.findings
        if f.rule == "IR004" and f.detail == "coverage:fleet_bits"
    ]
    assert hits and not result.ok
    assert any("prewarm" in f.message for f in hits)


# -- manifest fidelity (IR004 over a live manifest) --------------------------


FLEET_FAMILIES = ["fleet_solve", "fleet_pass", "fleet_entries",
                  "fleet_bits"]


@pytest.fixture(scope="module")
def toy_manifest(tmp_path_factory):
    """A real recorded manifest: one engine, toy shapes, 2 passes."""
    from test_compile_lifecycle import seed_manifest

    path = tmp_path_factory.mktemp("irmanifest") / "manifest.json"
    seed_manifest(path)
    return path


def test_manifest_records_audit_clean(toy_manifest):
    result = run_ir(
        FLEET_FAMILIES, root=REPO, baseline=None,
        manifest=str(toy_manifest),
    )
    assert result.ok, [f.render() for f in result.findings]


def test_manifest_corrupt_record_fires_ir004(toy_manifest, tmp_path):
    data = json.loads(toy_manifest.read_text())
    assert data["records"], "toy manifest recorded nothing"
    data["records"][0]["in_shapes"] = data["records"][0]["in_shapes"][:-1]
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(data))
    result = run_ir(
        FLEET_FAMILIES, root=REPO, baseline=None, manifest=str(bad)
    )
    assert not result.ok
    assert any(
        f.rule == "IR004" and "trace-failed" in f.detail
        for f in result.findings
    )


def test_manifest_unknown_kernel_fires_ir004(toy_manifest):
    # audit with a registry that lacks the recorded families entirely:
    # every record must surface as unknown-kernel, not silently skip
    entry = MUTANTS["IR002"]
    result = run_ir(
        entries={entry.name: entry}, root=REPO, baseline=None,
        manifest=str(toy_manifest),
    )
    assert any(
        f.rule == "IR004" and "unknown-kernel" in f.detail
        for f in result.findings
    )


def test_manifest_missing_or_empty_is_a_finding(tmp_path):
    # an explicitly-audited manifest that is unreadable or holds zero
    # records must FAIL the audit, never report clean — the operator
    # asked to prove prewarm coverage and there is none
    entry = MUTANTS["IR002"]
    absent = run_ir(
        entries={entry.name: entry}, root=REPO, baseline=None,
        manifest=str(tmp_path / "absent.json"),
    )
    assert not absent.ok
    assert any(f.detail == "manifest:unreadable" for f in absent.findings)

    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"version": 1, "records": []}))
    res = run_ir(
        entries={entry.name: entry}, root=REPO, baseline=None,
        manifest=str(empty),
    )
    assert not res.ok
    assert any(f.detail == "manifest:empty" for f in res.findings)


def test_manifest_removed_family_records_surface(tmp_path):
    # the audit parses the manifest RAW: records for a kernel family the
    # build no longer knows (renamed/removed — prewarm's loader would
    # silently drop them) must surface as unknown-kernel findings
    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps({
        "version": 1,
        "records": [{
            "kernel": "fleet_bits_old", "key": None,
            "in_shapes": [[[4], "int32"]], "statics": {},
        }],
    }))
    result = run_ir(
        FLEET_FAMILIES, root=REPO, baseline=None, manifest=str(stale)
    )
    assert not result.ok
    assert any(
        f.rule == "IR004" and "unknown-kernel" in f.detail
        for f in result.findings
    )


def test_manifest_canon_drift_fires_ir004(tmp_path):
    # a record whose serialized form does not survive prewarm's own
    # save/load writers (float dims here) must be flagged even though it
    # traces fine — replay dedup and ledger seeding key on the canon
    entry = mutant_entry(
        "ir002_host_callback", VEC, manifest="toykernel"
    )
    manifest = tmp_path / "drift.json"
    manifest.write_text(json.dumps({
        "version": 1,
        "records": [{
            "kernel": "toykernel", "key": None,
            "in_shapes": [[[8.0], "int32"]], "statics": {},
        }],
    }))
    from karmada_tpu.scheduler import prewarm

    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(prewarm, "_KERNELS", ("toykernel",))
        result = run_ir(
            entries={entry.name: entry}, root=REPO, baseline=None,
            manifest=str(manifest),
        )
    drift = [
        f for f in result.findings
        if f.rule == "IR004" and "canon-drift" in f.detail
    ]
    assert drift, [f.render() for f in result.findings]


# -- suppression + baseline share the AST tier's machinery -------------------


def test_def_line_suppression(tmp_path):
    mod = tmp_path / "ir_suppress_mutant.py"
    mod.write_text(textwrap.dedent(
        """
        import jax

        def suppressed_callback(x):  # graftlint: disable=IR002
            return jax.pure_callback(
                lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x
            )
        """
    ))
    sys.path.insert(0, str(tmp_path))
    try:
        entry = KernelEntry(
            name="suppressed_callback", family="ops",
            module="ir_suppress_mutant", attr="suppressed_callback",
            path="ir_suppress_mutant.py",
            make_specs=lambda: [KernelSpec("m", VEC)],
        )
        result = run_ir(
            entries={entry.name: entry}, root=tmp_path, baseline=None
        )
    finally:
        sys.path.remove(str(tmp_path))
    assert not result.findings
    assert result.suppressed_count == 1


def test_baseline_grandfathers_ir_findings(tmp_path):
    entry = MUTANTS["IR002"]
    raw = run_ir(entries={entry.name: entry}, root=REPO, baseline=None)
    assert raw.findings
    (tmp_path / "bl.json").write_text(json.dumps({
        "version": 1,
        "entries": [
            {"rule": f.rule, "path": f.path, "anchor": f.anchor,
             "detail": f.detail,
             "justification": "fixture: grandfathered for the test"}
            for f in raw.findings
        ],
    }))
    result = run_ir(
        entries={entry.name: entry}, root=tmp_path, baseline="bl.json"
    )
    assert result.ok
    assert len(result.baselined) == len(raw.findings)


# -- parity: the single-sourced accumulator dtypes ---------------------------


def test_acc_dtype_parity():
    from karmada_tpu.ops import dispense
    from karmada_tpu.refimpl import divider_np

    assert np.dtype(dispense.ACC_WIDE) == np.dtype(divider_np.ACC_NP)
    assert np.dtype(dispense.ACC_WIDE) == np.dtype(np.int64)
    assert np.dtype(dispense.ACC_NARROW) == np.dtype(np.int32)
    assert dispense.acc_dtype(True) is dispense.ACC_WIDE
    assert dispense.acc_dtype(False) is dispense.ACC_NARROW


# -- surfaces: module CLI, karmadactl verb, docs drift gate ------------------


def test_module_cli_ir_json():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--ir",
         "merge_estimates", "--format", "json"],
        cwd=REPO, capture_output=True, text=True, timeout=180,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["checked_files"] >= 1


def test_cli_lint_ir_verb(capsys):
    from karmada_tpu import cli

    rc = cli.main(["lint", "--ir", "merge_estimates", "--format", "json"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is True


def test_cli_ir_unknown_family_is_usage_error():
    from karmada_tpu import cli

    rc = cli.main(["lint", "--ir", "no_such_kernel"])
    assert rc == 2


def test_cli_empty_manifest_is_usage_error(capsys):
    # `--manifest "$KARMADA_TPU_TRACE_MANIFEST"` with the var unset must
    # never silently skip the audit the operator asked for
    from karmada_tpu import cli

    rc = cli.main(["lint", "--ir", "--manifest", ""])
    assert rc == 2
    assert "KARMADA_TPU_TRACE_MANIFEST" in capsys.readouterr().err


def test_write_baseline_refuses_partial_scope():
    from tools.graftlint.__main__ import main as graftlint_main

    rc = graftlint_main(["--write-baseline", "--changed-only"])
    assert rc == 2


def test_changed_only_scope(tmp_path):
    from tools.graftlint.__main__ import changed_py_files

    def git(*args):
        subprocess.run(
            ["git", *args], cwd=tmp_path, check=True, capture_output=True,
            env={"GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                 "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t",
                 "HOME": str(tmp_path), "PATH": "/usr/bin:/bin:/usr/local/bin"},
        )

    git("init", "-q")
    (tmp_path / "committed.py").write_text("A = 1\n")
    (tmp_path / "notes.md").write_text("x\n")
    git("add", "-A")
    git("commit", "-q", "-m", "seed")
    (tmp_path / "committed.py").write_text("A = 2\n")  # modified
    (tmp_path / "fresh.py").write_text("B = 1\n")  # untracked
    assert changed_py_files(tmp_path) == ["committed.py", "fresh.py"]


def test_ops_export_drift_fails_docs_regen(monkeypatch):
    sys.path.insert(0, str(REPO / "tools"))
    import docs_from_bench

    docs_from_bench.check_ir_registry()  # clean on the committed tree

    pruned = {
        name: e for name, e in ENTRY_POINTS.items()
        if e.name != "divide_replicas"
    }
    monkeypatch.setattr(graft_ir, "ENTRY_POINTS", pruned)
    with pytest.raises(SystemExit, match="divide_replicas"):
        docs_from_bench.check_ir_registry()
