"""GL001 bad fixture: host control flow + host sync inside a jitted
kernel. Parsed by graftlint only — never imported or executed."""

import os
import time
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("flag",))
def kernel(x, n, flag: bool):
    if n > 0:  # BAD: Python `if` on a traced value
        x = x + 1
    while x.sum() > 0:  # BAD: Python `while` on a traced value
        x = x - 1
    scale = float(x[0])  # BAD: host conversion of a traced value
    print("tracing", flag)  # BAD: trace-time print
    t0 = time.time()  # BAD: clock read baked into the trace
    plat = os.environ.get("KARMADA_TPU_PLATFORM", "")  # BAD: env in trace
    y = x.item()  # BAD: host sync
    return jnp.asarray([scale, t0, float(len(plat)), y])
