"""GL005 good fixture: jax deferred into the function that needs it, no
scheduler imports. Linted with roles {entry, ops}.
Parsed by graftlint only."""

import os
import sys


def run():
    import jax  # OK: deferred — only the verb that needs the backend pays

    return jax.devices(), os, sys
