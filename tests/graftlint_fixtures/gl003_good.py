"""GL003 good fixture: registered reads (direct and through a module
constant), env writes, and non-prefixed keys. Parsed by graftlint only."""

import os

_FLAG = "KARMADA_TPU_PLATFORM"  # registered in utils/flags.py


def read():
    a = os.environ.get(_FLAG, "")  # OK: registered, via constant
    b = os.getenv("KARMADA_TPU_NO_NATIVE")  # OK: registered, direct
    c = os.environ.get("JAX_PLATFORMS")  # OK: not a KARMADA_TPU_* key
    os.environ["KARMADA_TPU_PLATFORM"] = "cpu"  # OK: a write, not a read
    return a, b, c
