"""GL003 bad fixture: unregistered KARMADA_TPU_* env reads — attribute
form, module-constant indirection, and the ``from os import`` aliased
forms. Parsed by graftlint only."""

import os
from os import environ, getenv as _ge

_INDIRECT = "KARMADA_TPU_ALSO_NOT_REGISTERED"


def read():
    a = os.environ.get("KARMADA_TPU_NOT_REGISTERED", "")  # BAD
    b = os.getenv(_INDIRECT)  # BAD: resolved through the constant
    c = os.environ["KARMADA_TPU_NOT_REGISTERED"]  # BAD
    d = _ge("KARMADA_TPU_ALIASED_GETENV")  # BAD: aliased getenv
    e = environ.get("KARMADA_TPU_ALIASED_ENVIRON")  # BAD: aliased environ
    f = environ["KARMADA_TPU_ALIASED_ENVIRON"]  # BAD
    return a, b, c, d, e, f
