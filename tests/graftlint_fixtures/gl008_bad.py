"""GL008 bad fixture: unregistered span names on a tracer receiver."""


class _Tracer:
    def span(self, name, **attrs):
        return name

    def record(self, name, duration, **attrs):
        return name

    def server_span(self, name, ctx, **attrs):
        return name

    def open_manual(self, name, ctx=None, **attrs):
        return name


tracer = _Tracer()
_tracer = tracer


def record_spans(kind: str):
    # BAD: literal name absent from utils.tracing SPAN_NAMES
    tracer.span("rogue.span")
    # BAD: record() with an unregistered literal
    _tracer.record("another.rogue", 0.25)
    # BAD: server_span with an unregistered literal
    tracer.server_span("rogue.serve", None)
    # BAD: dynamic name whose literal prefix matches no `family.*` entry
    tracer.span(f"rogue.{kind}")
    # BAD: dynamic name with no literal head at all
    tracer.record(f"{kind}.tail", 0.1)
