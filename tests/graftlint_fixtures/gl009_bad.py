"""GL009 bad fixture: history series whose sources resolve to nothing —
an unregistered metric family, a span outside the taxonomy, and a source
that follows neither grammar."""

from dataclasses import dataclass


@dataclass(frozen=True)
class HistorySeries:
    name: str
    kind: str
    source: str
    description: str


class _Registry:
    def counter(self, name, help_=""):
        return name


registry = _Registry()

# the only family THIS scan can see
known_total = registry.counter("karmada_tpu_fixture_known_total", "known")

SERIES = {
    # BAD: no scanned registry defines this family
    "ghost": HistorySeries(
        "ghost", "counter", "metric:karmada_tpu_ghost_total", "rotted ref"
    ),
    # BAD: span name outside utils.tracing SPAN_NAMES
    "rogue": HistorySeries(
        "rogue", "gauge", "span:rogue.phase", "unregistered span"
    ),
    # BAD: neither metric:<family> nor span:<name>
    "bogus": HistorySeries(
        name="bogus", kind="gauge", source="buckets.raw",
        description="grammar violation",
    ),
}
