"""GL010 good fixture: registered codes, dynamic reasons, non-reason
keywords and non-emission constructors stay silent."""

import threading


class Condition:
    def __init__(self, type="", status=True, reason="", message=""):
        self.reason = reason


class _Counter:
    def inc(self, n=1, **labels):
        return labels


class ManifestResult:
    def __init__(self, index=0, kernel="", reason="ok"):
        self.reason = reason


unschedulable_total = _Counter()


def emit(ready: bool):
    # registered codes
    Condition(type="Scheduled", status=True, reason="Success")
    Condition(type="Scheduled", status=False, reason="QuotaExceeded")
    unschedulable_total.inc(reason="NoClusterFit")
    # scarcity-plane codes (ISSUE 14): the victim condition, the
    # preemption metric label and the drift-trigger label all resolve
    Condition(
        type="Preempted", status=True, reason="PreemptedByHigherPriority"
    )
    unschedulable_total.inc(reason="RebalanceTriggered")
    # dynamic reason: out of static reach, unchecked (the GL008 rule)
    reason = "ClusterReady" if ready else "ClusterNotReachable"
    Condition(type="Ready", status=ready, reason=reason)
    # a reason kwarg on a NON-emission constructor is not an emission
    ManifestResult(index=1, kernel="k", reason="unreadable")
    # threading.Condition takes no reason and must not be confused
    threading.Condition(threading.Lock())
