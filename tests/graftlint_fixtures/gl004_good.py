"""GL004 good fixture: every mutation under the lock, plus a documented
single-writer suppression. Parsed by graftlint only."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._items = []

    def bump(self):
        with self._lock:
            self._n += 1
            self._items.append(self._n)

    def reset(self):
        with self._lock:  # OK: takes the same lock
            self._n = 0
            self._items.clear()

    # single-writer invariant: only the owner thread calls rewind(),
    # before the worker threads that use bump() are started
    # graftlint: disable=GL004
    def rewind(self):
        self._n = 0
