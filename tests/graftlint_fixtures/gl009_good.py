"""GL009 good fixture: every source resolves — a metric family this scan
defines, taxonomy span names (direct and via a ``*`` family), and a
non-literal source that stays out of static reach."""

from dataclasses import dataclass


@dataclass(frozen=True)
class HistorySeries:
    name: str
    kind: str
    source: str
    description: str


class _Registry:
    def gauge(self, name, help_=""):
        return name


registry = _Registry()

fixture_bytes = registry.gauge("karmada_tpu_fixture_bytes", "a family")

SERIES = {
    "bytes": HistorySeries(
        "bytes", "gauge", "metric:karmada_tpu_fixture_bytes", "resolves"
    ),
    "wall": HistorySeries("wall", "gauge", "span:settle", "taxonomy"),
    "drain": HistorySeries(
        name="drain", kind="counter", source="span:controller.scheduler",
        description="resolves via the controller.* family",
    ),
}


def dynamic(source: str) -> HistorySeries:
    # a plain variable is out of static reach (GL006/GL002 precedent)
    return HistorySeries("dyn", "gauge", source, "unchecked")
