"""GL013 good fixture: every grown container has a cap or an eviction
path, plus a documented bounded-by-construction table. Parsed by
graftlint only (role-forced to the hotpath scope)."""

from collections import deque


class ResultCache:
    CAP = 1024

    def __init__(self):
        self._memo = {}
        self._events = deque(maxlen=256)  # OK: capped at construction
        self._by_kind = {}

    def lookup(self, key, compute):
        if key not in self._memo:
            if len(self._memo) >= self.CAP:
                self._memo.clear()  # OK: eviction path exists
            self._memo[key] = compute(key)
        return self._memo[key]

    def record(self, event):
        self._events.append(event)  # OK: deque(maxlen=...) self-evicts

    # keyed by the static kind enum: the table is bounded by code
    # structure, never by traffic
    def tally(self, kind):
        self._by_kind[kind] = self._by_kind.get(kind, 0) + 1  # graftlint: disable=GL013

    def reset(self):
        self._memo = {}  # OK: reassignment outside __init__ is a reset
