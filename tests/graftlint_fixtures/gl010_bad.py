"""GL010 bad fixture: unregistered reason codes at emission sites."""


class Condition:
    def __init__(self, type="", status=True, reason="", message=""):
        self.reason = reason


class _Counter:
    def inc(self, n=1, **labels):
        return labels


unschedulable_total = _Counter()


def emit():
    # BAD: Condition reason literal absent from utils.reasons REASONS
    Condition(type="Scheduled", status=False, reason="RogueReason")
    # BAD: metric reason label absent from the taxonomy
    unschedulable_total.inc(reason="AnotherRogue")
