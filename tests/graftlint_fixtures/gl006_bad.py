"""GL006 bad fixture: unprefixed + duplicate metric family names."""


class _Registry:
    def counter(self, name, help_=""):
        return name

    def gauge(self, name, help_=""):
        return name

    def histogram(self, name, help_="", buckets=()):
        return name


registry = _Registry()

# BAD: no karmada_tpu_/karmada_scheduler_ prefix
requests_total = registry.counter("requests_total", "bare name")

# BAD: same family registered twice (counter then histogram)
dup_a = registry.counter("karmada_tpu_dup_total", "first registration")
dup_b = registry.histogram("karmada_tpu_dup_total", "second registration")
