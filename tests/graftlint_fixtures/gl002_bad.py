"""GL002 bad fixture: a jitted kernel dispatched with no trace-key
ledger call in any enclosing function. Parsed by graftlint only."""

import jax
import jax.numpy as jnp


@jax.jit
def _toy_kernel(x):
    return x * 2


class Table:
    def schedule(self, x):
        # BAD: a fresh trace here is invisible to new_trace_last_pass
        return _toy_kernel(jnp.asarray(x))
