"""GL005 bad fixture: linted with roles {entry, ops} — module-level jax
in an entry module, scheduler import from the kernel layer.
Parsed by graftlint only."""

import jax  # BAD: cold-start cost on every CLI boot
import jax.numpy as jnp  # BAD

from karmada_tpu.scheduler import fleet  # BAD: ops/ -> scheduler/


def solve(x):
    from ..scheduler import core  # BAD: relative scheduler import too

    return jnp.asarray(x), fleet, core, jax
