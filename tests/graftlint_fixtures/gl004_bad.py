"""GL004 bad fixture: attrs guarded by the lock in one method, mutated
lock-free in another. Parsed by graftlint only."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # OK: construction happens before the object is shared
        self._items = []

    def bump(self):
        with self._lock:
            self._n += 1
            self._items.append(self._n)

    def reset(self):
        self._n = 0  # BAD: lock-free write of a lock-guarded attr
        self._items.clear()  # BAD: lock-free in-place mutation
