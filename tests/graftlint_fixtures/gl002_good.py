"""GL002 good fixture: ledgered dispatch + jit-composed call.
Parsed by graftlint only."""

import jax
import jax.numpy as jnp


@jax.jit
def _toy_kernel(x):
    return x * 2


@jax.jit
def _outer_kernel(x):
    # OK: a kernel called inside another jitted kernel traces as ONE
    # composed program — the outer dispatch site ledgers it
    return _toy_kernel(x) + 1


class Table:
    def __init__(self):
        self._seen = set()

    def _mark_trace(self, *key):
        self._seen.add(key)

    def schedule(self, x):
        self._mark_trace("T", x.shape)  # OK: signature ledgered
        return _outer_kernel(jnp.asarray(x))
