"""GL007 bad fixture: unary stubs and urlopen called without timeouts."""

import urllib.request


class _Chan:
    def unary_unary(self, path, **kw):
        return lambda req, timeout=None: req


channel = _Chan()

# module-level stub binding
score = channel.unary_unary("/svc/Score")


class Client:
    def __init__(self, channel):
        self._sync = channel.unary_unary("/svc/Sync")
        self._score = channel.unary_unary("/svc/Score")
        # a BATCHED stub is still a unary stub — one RPC carrying a
        # whole write set (ISSUE 11 ApplyBatch shape)
        self._apply_batch = channel.unary_unary("/svc/ApplyBatch")

    def call(self, req):
        # BAD: direct stub call with no timeout
        return self._sync(req)

    def call_future(self, req):
        # BAD: future form with no timeout
        return self._score.future(req)

    def call_batch(self, req, md):
        # BAD: batched stub with metadata but no timeout — an unbounded
        # stall here blocks the whole 4096-op write set
        return self._apply_batch(req, metadata=md)

    def call_with_call(self, req):
        # BAD: with_call form with no timeout
        return self._apply_batch.with_call(req)

    def ok(self, req):
        return self._score(req, timeout=3.0)


def module_call(req):
    # BAD: module-level stub called unbounded
    return score(req)


def fetch(url):
    # BAD: urlopen with no timeout
    with urllib.request.urlopen(url) as resp:
        return resp.read()
