"""GL013 bad fixture: hot-path containers that only ever grow. Parsed by
graftlint only (role-forced to the hotpath scope)."""

from collections import deque


class ResultCache:
    def __init__(self):
        self._memo = {}
        self._events = deque()

    def lookup(self, key, compute):
        if key not in self._memo:
            self._memo[key] = compute(key)  # BAD: grows, never evicts
        return self._memo[key]

    def record(self, event):
        self._events.append(event)  # BAD: unbounded deque, no maxlen
