"""GL012 bad fixture: budget objects constructed inside retry loops —
the budget resets every iteration. Parsed by graftlint only."""

from karmada_tpu.utils.backoff import BackoffPolicy, Deadline


def fetch_all(fetch, items):
    results = []
    for item in items:
        deadline = Deadline(5.0)  # BAD: fresh budget per iteration
        results.append(fetch(item, timeout=deadline.remaining()))
    return results


def reconnect(connect, stop):
    while not stop.is_set():
        policy = BackoffPolicy(base=0.1, cap=2.0)  # BAD: ladder resets
        try:
            return connect(policy)
        except ConnectionError:
            continue
