"""GL011 good fixture: every read of a guarded attr under the lock (or
a documented racy-read invariant), plus the __init__ exemption and the
write-side carve-outs that belong to GL004. Parsed by graftlint only."""

import threading


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._by_key = {}
        self._order = []
        self.count = len(self._by_key)  # OK: pre-publication window

    def put(self, key, value):
        with self._lock:
            self._by_key[key] = value
            self._order.append(key)

    def snapshot(self):
        with self._lock:  # OK: snapshot under the lock
            return dict(self._by_key)

    def drop(self, key):
        with self._lock:
            self._by_key.pop(key, None)
            self._order.remove(key)

    # stats() tolerates a torn size: the value feeds a gauge, and the
    # next scrape self-corrects
    def stats(self):
        return len(self._by_key)  # graftlint: disable=GL011
