"""GL008 good fixture: registered names, dynamic families, exempt
receivers."""


class _Tracer:
    def span(self, name, **attrs):
        return name

    def record(self, name, duration, **attrs):
        return name

    def server_span(self, name, ctx, **attrs):
        return name


tracer = _Tracer()


def record_spans(worker: str, phases):
    # registered literals
    tracer.span("settle")
    tracer.record("scheduler.pack", 0.25)
    tracer.server_span("estimator.serve", None)
    # dynamic family: literal prefix resolves `controller.*`
    tracer.span(f"controller.{worker}")
    # a plain variable is out of static reach (GL006/GL002 precedent)
    for name, seconds in phases:
        tracer.record(name, seconds)


class _Api:
    def span(self, label):
        return label


api = _Api()
# not a tracer receiver: arbitrary APIs with a span-shaped method are out
# of scope
unrelated = api.span("not.a.span")
