"""GL001 good fixture: static branches, shape reads, traced selects —
everything the rule must NOT flag. Parsed by graftlint only."""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("flag", "cap"))
def kernel(x, flag: bool, cap: int):
    if flag:  # OK: static argument — branch resolves at trace time
        x = x + 1
    if cap > 4:  # OK: static argument
        x = x * 2
    if x.shape[0] > 2:  # OK: shape is static at trace time
        x = x[:2]
    if len(x) > 1:  # OK: len(traced) == shape[0], static
        x = x + 0
    return jnp.where(x > 0, x, 0)  # OK: traced select, not a host branch


def host_helper(x):
    # OK: not jitted — host code may branch and convert freely
    if x > 3:
        return float(x)
    return 0.0
