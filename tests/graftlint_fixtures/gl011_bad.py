"""GL011 bad fixture: attrs mutated under the lock in one method, READ
lock-free in another. Parsed by graftlint only."""

import threading


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._by_key = {}
        self._order = []

    def put(self, key, value):
        with self._lock:
            self._by_key[key] = value
            self._order.append(key)

    def snapshot(self):
        return dict(self._by_key)  # BAD: lock-free read of a guarded attr

    def newest(self):
        if not self._order:  # BAD: lock-free read of a guarded attr
            return None
        return self._order[-1]
