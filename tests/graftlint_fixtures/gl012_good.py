"""GL012 good fixture: budgets hoisted above their loops, the closure
carve-out, and a documented per-item budget. Parsed by graftlint only."""

from karmada_tpu.utils.backoff import BackoffPolicy, Deadline


def fetch_all(fetch, items):
    deadline = Deadline(5.0)  # OK: ONE budget bounds the whole loop
    results = []
    for item in items:
        results.append(fetch(item, timeout=deadline.remaining()))
    return results


def reconnect(connect, stop):
    policy = BackoffPolicy(base=0.1, cap=2.0)  # OK: hoisted
    while not stop.is_set():
        try:
            return connect(policy)
        except ConnectionError:
            continue


def spawn_workers(submit, items):
    for item in items:
        # OK: the def boundary resets the search — attempt() runs when
        # CALLED, each call legitimately opening its own budget
        def attempt():
            return Deadline(1.0)

        submit(attempt, item)


def probe_each(probe, endpoints):
    results = []
    for ep in endpoints:
        # per-endpoint budget is the CONTRACT here: one slow endpoint
        # must not starve the rest of the sweep
        d = Deadline(1.0)  # graftlint: disable=GL012
        results.append(probe(ep, timeout=d.remaining()))
    return results
