"""GL007 good fixture: every unary call bounded; streams and pass-by-value
stubs exempt."""

import urllib.request
from urllib.request import urlopen


class _Chan:
    def unary_unary(self, path, **kw):
        return lambda req, timeout=None: req

    def unary_stream(self, path, **kw):
        return lambda req: iter(())


channel = _Chan()


class Client:
    def __init__(self, channel):
        self._sync = channel.unary_unary("/svc/Sync")
        self._score = channel.unary_unary("/svc/Score")
        # a batched write stub (ISSUE 11 ApplyBatch): one unary RPC per
        # write SET, bounded like any other unary call
        self._apply_batch = channel.unary_unary("/svc/ApplyBatch")
        # watch streams are deliberately open-ended (bounded by their
        # reconnect loop), not unbounded unary RPCs — the coalesced
        # WatchBatch frame stream is exempt exactly like unary watch
        self._watch = channel.unary_stream("/svc/Watch")
        self._watch_batch = channel.unary_stream("/svc/WatchBatch")

    def call(self, req, deadline):
        return self._sync(req, timeout=deadline)

    def call_future(self, req):
        return self._score.future(req, timeout=2.5)

    def call_batch(self, req, md, deadline):
        # one Deadline budget for the whole batch, not per op
        return self._apply_batch(req, timeout=deadline, metadata=md)

    def call_with_call(self, req):
        return self._apply_batch.with_call(req, timeout=2.5)

    def watch(self, req):
        return self._watch(req)

    def watch_batch(self, req):
        return self._watch_batch(req)

    def resilient(self, req):
        # stub passed by VALUE into a wrapper that owns the deadline —
        # the wrapper's own call carries timeout=
        return _retry(self._score, req)


def _retry(stub, req):
    return stub(req, timeout=1.0)


def fetch(url):
    with urllib.request.urlopen(url, timeout=5.0) as resp:
        return resp.read()


def fetch2(url):
    with urlopen(url, timeout=5.0) as resp:
        return resp.read()
