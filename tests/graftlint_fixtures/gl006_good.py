"""GL006 good fixture: prefixed, unique family names; non-registry
receivers with a ``counter``-shaped method stay exempt."""

import collections


class _Registry:
    def counter(self, name, help_=""):
        return name

    def gauge(self, name, help_=""):
        return name

    def histogram(self, name, help_="", buckets=()):
        return name


registry = _Registry()

ok_counter = registry.counter("karmada_tpu_fixture_ok_total", "prefixed")
ok_gauge = registry.gauge("karmada_scheduler_fixture_depth", "prefixed")
ok_hist = registry.histogram("karmada_tpu_fixture_seconds", "prefixed")

# not a registry receiver: collections.Counter / arbitrary APIs with a
# same-named method are out of scope
retries = collections.Counter()


class _Api:
    def counter(self, label):
        return label


api = _Api()
unrelated = api.counter("not_a_metric_family")
