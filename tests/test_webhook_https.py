"""Admission chain as an out-of-process HTTP(S) webhook (VERDICT r3 #10).

Mirrors the interpreter webhook's transport tier: the same AdmissionChain
that hooks the Store in-proc is served behind TLS, and a Store wired with
``RemoteAdmission`` round-trips every write through it — mutations come
back over the wire, denials raise, unreachable webhooks fail closed (or
open with failurePolicy=Ignore semantics).
Ref: cmd/webhook/app/webhook.go:161-183.
"""

import subprocess

import pytest

from karmada_tpu.api.cluster import Cluster, ClusterSpec
from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.api.policy import (
    PropagationPolicy,
    PropagationSpec,
    ResourceSelector,
)
from karmada_tpu.utils import Store
from karmada_tpu.webhook.chain import PERMANENT_ID_ANNOTATION
from karmada_tpu.webhook.server import (
    AdmissionDenied,
    AdmissionWebhookServer,
    RemoteAdmission,
)


def make_policy(name="pp"):
    return PropagationPolicy(
        meta=ObjectMeta(name=name, namespace="default"),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ]
        ),
    )


@pytest.fixture(scope="module")
def tls_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("admission-pki")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(d / "srv.key"), "-out", str(d / "srv.crt"),
         "-days", "1", "-subj", "/CN=localhost",
         "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost"],
        check=True, capture_output=True,
    )
    return d


@pytest.fixture()
def https_store(tls_files):
    server = AdmissionWebhookServer(
        certfile=str(tls_files / "srv.crt"),
        keyfile=str(tls_files / "srv.key"),
    )
    url = server.start()
    remote = RemoteAdmission(
        url, ca_bundle=(tls_files / "srv.crt").read_bytes()
    )
    store = Store(admission=remote.admit, delete_admission=remote.admit_delete)
    yield store, server
    server.stop()


class TestAdmissionOverHttps:
    def test_mutation_round_trips(self, https_store):
        store, _ = https_store
        policy = make_policy()
        assert PERMANENT_ID_ANNOTATION not in policy.meta.annotations
        store.apply(policy)
        # the webhook PROCESS side ran the mutator; the annotation came back
        # over the wire and was folded into the caller's object
        assert PERMANENT_ID_ANNOTATION in policy.meta.annotations
        stored = store.get("PropagationPolicy", "default/pp")
        assert PERMANENT_ID_ANNOTATION in stored.meta.annotations

    def test_validation_denial_raises(self, https_store):
        store, _ = https_store
        bad = Cluster(
            meta=ObjectMeta(name="Bad_Name!"),
            spec=ClusterSpec(sync_mode="Push"),
        )
        with pytest.raises(ValueError):
            store.apply(bad)
        assert store.get("Cluster", "Bad_Name!") is None

    def test_unreachable_webhook_fails_closed_and_open(self, tls_files):
        closed = RemoteAdmission("https://127.0.0.1:1/admit")
        store = Store(admission=closed.admit)
        with pytest.raises(AdmissionDenied):
            store.apply(make_policy())
        opened = RemoteAdmission("https://127.0.0.1:1/admit", fail_open=True)
        store2 = Store(admission=opened.admit)
        store2.apply(make_policy())  # failurePolicy=Ignore semantics
        assert store2.get("PropagationPolicy", "default/pp") is not None
