"""Out-of-process pull-mode agent over the store bus (VERDICT r3 item 3).

The analogue of the reference's kind-based pull-mode e2e
(hack/local-up-karmada.sh member3 + cmd/agent): the control plane runs in
THIS process with a StoreBusServer; the agent runs as a REAL subprocess
(python -m karmada_tpu.bus.agent) holding its own member-cluster state,
mirroring the plane over the gRPC watch stream and writing Work status +
Lease renewals back through the bus. Killing the subprocess must degrade
the cluster via lease staleness and fail the workload over to a surviving
push member — the full failure chain crossing a real process boundary.
"""

import subprocess
import sys
import time

import pytest

from karmada_tpu.api.core import ObjectMeta
from karmada_tpu.api import PropagationPolicy, PropagationSpec, ResourceSelector
from karmada_tpu.bus.service import StoreBusServer
from karmada_tpu.controllers import execution_namespace
from karmada_tpu.controlplane import ControlPlane
from karmada_tpu.utils.builders import (
    dynamic_weight_placement,
    new_cluster,
    new_deployment,
)
from karmada_tpu.utils.features import FAILOVER, feature_gate


def nginx_policy(placement, name="nginx-policy", ns="default"):
    return PropagationPolicy(
        meta=ObjectMeta(name=name, namespace=ns),
        spec=PropagationSpec(
            resource_selectors=[
                ResourceSelector(api_version="apps/v1", kind="Deployment")
            ],
            placement=placement,
        ),
    )


def settle_until(cp, predicate, timeout=20.0, interval=0.05):
    """Drive the plane's reconcilers while polling for a condition the
    out-of-process agent must produce (its writes arrive via bus events)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        cp.settle()
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def plane_and_agent():
    # offset-able clock: advancing it simulates lease staleness without
    # waiting out the real 120s grace period
    offset = [0.0]
    cp = ControlPlane(clock=lambda: time.time() + offset[0])
    bus = StoreBusServer(cp.store)
    port = bus.start()
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "karmada_tpu.bus.agent",
            "--target", f"127.0.0.1:{port}",
            "--cluster", "pull1",
            "--max-seconds", "120",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        yield cp, offset, proc
    finally:
        proc.kill()
        proc.wait(timeout=5)
        bus.stop()


class TestAgentOverBus:
    def test_pull_propagation_status_and_failover(self, plane_and_agent):
        cp, offset, proc = plane_and_agent
        feature_gate.set(FAILOVER, True)
        try:
            # pull member whose agent lives in the subprocess + a local
            # push member to fail over to
            pull = new_cluster("pull1", cpu="100", memory="200Gi")
            pull.spec.sync_mode = "Pull"
            cp.join_cluster(pull, remote_agent=True)
            cp.join_cluster(new_cluster("member2", cpu="100", memory="200Gi"))
            cp.settle()

            # the agent's lease arrives over the bus -> Pull cluster Ready
            def pull_ready():
                cluster = cp.store.get("Cluster", "pull1")
                ready = next(
                    (c for c in cluster.status.conditions if c.type == "Ready"),
                    None,
                )
                return ready is not None and bool(ready.status)

            assert settle_until(cp, pull_ready), (
                "pull cluster never became Ready from the subprocess lease; "
                f"agent output: {proc.stdout}"
            )

            # propagate a workload across both members
            cp.store.apply(new_deployment("ha-app", replicas=6))
            cp.store.apply(nginx_policy(dynamic_weight_placement()))
            cp.settle()
            rb = cp.store.get("ResourceBinding", "default/ha-app-deployment")
            placed = {tc.name: tc.replicas for tc in rb.spec.clusters}
            assert sum(placed.values()) == 6
            assert "pull1" in placed, placed

            # the subprocess agent applies the Work and reflects status
            # (Applied + Healthy once its simulated kubelet reports ready)
            work_key = f"{execution_namespace('pull1')}/default.ha-app-deployment"

            def work_applied_healthy():
                work = cp.store.get("Work", work_key)
                if work is None:
                    return False
                applied = any(
                    c.type == "Applied" and c.status
                    for c in work.status.conditions
                )
                healthy = any(
                    ms.health == "Healthy"
                    for ms in work.status.manifest_statuses
                )
                return applied and healthy

            assert settle_until(cp, work_applied_healthy), (
                "subprocess agent never reflected Applied/Healthy status"
            )

            # aggregated status reaches the binding
            def aggregated():
                rb2 = cp.store.get(
                    "ResourceBinding", "default/ha-app-deployment"
                )
                return any(
                    i.cluster_name == "pull1" for i in rb2.status.aggregated_status
                )

            assert settle_until(cp, aggregated)

            # kill the agent process: lease goes stale past grace ->
            # NotReady -> taint -> eviction -> replicas rehome to member2
            proc.kill()
            proc.wait(timeout=5)
            offset[0] += 200.0  # > LEASE_GRACE_SECONDS

            def failed_over():
                rb2 = cp.store.get(
                    "ResourceBinding", "default/ha-app-deployment"
                )
                after = {tc.name: tc.replicas for tc in rb2.spec.clusters}
                return "pull1" not in after and sum(after.values()) == 6

            assert settle_until(cp, failed_over, timeout=10.0), (
                "binding never failed over after the agent process died"
            )
            cluster = cp.store.get("Cluster", "pull1")
            ready = next(
                c for c in cluster.status.conditions if c.type == "Ready"
            )
            assert not ready.status and ready.reason == "AgentLeaseExpired"
        finally:
            feature_gate.set(FAILOVER, False)

    def test_agent_write_round_trips_through_primary_admission(
        self, plane_and_agent
    ):
        """The agent's writes are primary-committed: its Lease carries a
        primary resource_version and is visible to plane controllers."""
        cp, _offset, proc = plane_and_agent
        pull = new_cluster("pull1", cpu="10", memory="20Gi")
        pull.spec.sync_mode = "Pull"
        cp.join_cluster(pull, remote_agent=True)

        def lease_present():
            lease = cp.store.get("Lease", "pull1")
            return lease is not None and lease.meta.resource_version > 0

        assert settle_until(cp, lease_present), (
            f"no lease from subprocess; agent output head: "
            f"{proc.stdout}"
        )


class TestAgentLeaderElection:
    """HA pull agents: N replicas per member, one Lease holder syncs
    (cmd/agent --leader-elect over client-go leaderelection; here the CAS
    elector of utils/leaderelect.py through the bus facade)."""

    def test_two_agents_one_leader_and_failover(self):
        cp = ControlPlane()
        bus = StoreBusServer(cp.store)
        port = bus.start()

        def spawn(ident):
            return subprocess.Popen(
                [
                    sys.executable, "-m", "karmada_tpu.bus.agent",
                    "--target", f"127.0.0.1:{port}",
                    "--cluster", "pull1",
                    "--max-seconds", "120",
                    "--leader-elect",
                    "--leader-elect-identity", ident,
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )

        a, b = spawn("agent-a"), spawn("agent-b")
        try:
            pull = new_cluster("pull1", cpu="100", memory="200Gi")
            pull.spec.sync_mode = "Pull"
            cp.join_cluster(pull, remote_agent=True)
            cp.settle()

            lock_key = "karmada-agent-pull1"

            def lease_held():
                lease = cp.store.get("Lease", lock_key)
                return lease is not None and lease.holder_identity in (
                    "agent-a", "agent-b",
                )

            assert settle_until(cp, lease_held, timeout=20), (
                "no agent acquired the leader lease"
            )
            leader = cp.store.get("Lease", lock_key).holder_identity

            # the LEADER syncs: workload propagates and reports Applied
            cp.store.apply(new_deployment("ha-le-app", replicas=3))
            cp.store.apply(nginx_policy(dynamic_weight_placement()))
            work_key = (
                f"{execution_namespace('pull1')}/default.ha-le-app-deployment"
            )

            def applied(key):
                def check():
                    work = cp.store.get("Work", key)
                    return work is not None and any(
                        c.type == "Applied" and c.status
                        for c in work.status.conditions
                    )
                return check

            assert settle_until(cp, applied(work_key), timeout=30), (
                "leader agent never applied the Work"
            )

            # kill the leader: the standby must take the lease over after
            # expiry (lease_duration 2s at the default 0.5s tick)
            victim, survivor_id = (
                (a, "agent-b") if leader == "agent-a" else (b, "agent-a")
            )
            victim.kill()
            victim.wait(timeout=5)

            def taken_over():
                lease = cp.store.get("Lease", lock_key)
                return (
                    lease is not None
                    and lease.holder_identity == survivor_id
                    and lease.lease_transitions >= 1
                )

            assert settle_until(cp, taken_over, timeout=25), (
                f"standby {survivor_id} never took the lease over"
            )

            # the NEW leader drains the backlog and syncs fresh work
            cp.store.apply(new_deployment("ha-le-app2", replicas=2))
            work_key2 = (
                f"{execution_namespace('pull1')}/default.ha-le-app2-deployment"
            )
            assert settle_until(cp, applied(work_key2), timeout=30), (
                "surviving agent never applied post-failover Work"
            )
        finally:
            for p in (a, b):
                p.kill()
                p.wait(timeout=5)
            bus.stop()
