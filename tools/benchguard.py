"""benchguard — the perf-regression guard over the committed bench
trajectory (ISSUE 12 c).

The repo's BENCH_*.json records are its perf memory; until now nothing
compared a fresh record against them. This tool does, with per-metric
DIRECTIONAL noise bands:

- every record family (``metric`` field prefix) declares the metrics it
  guards in ``SPECS`` — each with a direction (``lower`` = a time, fresh
  must not grow; ``higher`` = a throughput/ratio, fresh must not shrink)
  and a multiplicative band sized to the rig noise that family has
  actually exhibited (the quota bench measured the shared CI rig itself
  swinging ~2x, so wall-clock bands are generous; coverage ratios are
  rig-robust and band tight).
- the baseline is resolved from the COMMITTED records: every
  ``BENCH_*.json`` in the repo root whose ``metric`` matches the fresh
  record's, excluding the fresh file itself; the newest (highest ``_rNN``
  in the filename, then mtime) wins.
- a breach is ``fresh >= band x worse than baseline`` (>=, so an exact
  synthetic 2x slowdown against a 2.0 band FIRES); an improvement past
  the band the other way is reported as ``improved``, never an error.
- a guarded metric MISSING from the fresh record is a LOUD error (exit
  nonzero), never a silent pass — a record that stopped carrying a
  series is itself a regression of the measurement layer. A metric the
  (older) baseline record predates is reported ``baseline-missing`` and
  passes: there is nothing to regress against, but it is printed, not
  swallowed.

Surfaces: ``python -m tools.benchguard RECORD.json`` and
``bench.py --check RECORD.json`` (the same code path) print the verdict
table and exit nonzero on any regression or missing metric.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from dataclasses import dataclass
from pathlib import Path
from typing import Optional, Sequence

ROOT = Path(__file__).resolve().parent.parent


@dataclass(frozen=True)
class GuardMetric:
    """One guarded series of a record family."""

    name: str
    #: "lower" = smaller is better (seconds); "higher" = bigger is
    #: better (throughput, coverage, speedup ratios)
    direction: str
    #: multiplicative noise band: breach when the fresh value is >= band
    #: times WORSE than baseline in the guarded direction
    band: float
    #: required=True: absent from the FRESH record = loud error.
    #: required=False: the metric is conditional (e.g. stitched columns
    #: exist only when the 4-process phase ran) — absence is reported as
    #: ``absent`` and passes.
    required: bool = True


#: the committed trajectory's guard specs, keyed by ``metric`` prefix
#: (longest prefix wins). Bands are per-metric and directional — sized
#: to observed rig noise, not wishful tightness: BENCH history shows the
#: shared rig swinging up to ~2x on wall clocks (PR 8/11 notes), while
#: coverage and identity ratios barely move.
SPECS: dict[str, tuple[GuardMetric, ...]] = {
    "observability_wave": (
        GuardMetric("value", "lower", 2.0),
        GuardMetric("coverage_vs_wall", "higher", 1.25),
        GuardMetric("bindings_s", "higher", 2.0),
        GuardMetric("stitched_wall_s", "lower", 3.0, required=False),
        GuardMetric(
            "stitched_coverage_vs_wall", "higher", 1.35, required=False
        ),
        GuardMetric("stitched_bindings_s", "higher", 3.0, required=False),
        GuardMetric("bus_unary_vs_batched", "higher", 3.0, required=False),
        # ISSUE 13: armed-vs-disarmed explain overhead ratio — a value
        # of 1.0 means free; the band allows shared-rig swing but fires
        # if provenance capture ever becomes a structural storm cost.
        # required=False: the tier exists only from BENCH_OBS_r04 on.
        GuardMetric("explain_overhead_x", "lower", 2.0, required=False),
    ),
    "p50_engine_schedule": (
        GuardMetric("value", "lower", 2.0),
        GuardMetric("scale1m_steady_p50", "lower", 2.0, required=False),
        GuardMetric("scale1m_churn_p50", "lower", 2.0, required=False),
        # ISSUE 20: the incremental (dirty-row) solve contract — churn
        # cost proportional to churn size. The 1% tier is the headline
        # and REQUIRED: a default record that stopped carrying it means
        # the delta path (or its measurement) silently died. Older
        # records predate the series, so the first guarded run reports
        # baseline-missing and passes; from then on the band fires if a
        # 1M-plane 1%-churn pass ever drifts back toward full-solve
        # cost. 0.1%/10% ride along unrequired (diagnostic envelope).
        GuardMetric("scale1m_churn1pct_p50", "lower", 2.0),
        GuardMetric("scale1m_churn0p1pct_p50", "lower", 2.0, required=False),
        GuardMetric("scale1m_churn10pct_p50", "lower", 2.0, required=False),
        GuardMetric("churn_p50", "lower", 2.0, required=False),
        GuardMetric(
            "whole_plane_bindings_s", "higher", 2.0, required=False
        ),
        # vs_python_oracle is deliberately unguarded: the committed
        # trajectory itself shows it swinging >20x between records (the
        # oracle's own timing is the denominator) — a band wide enough
        # to absorb that guards nothing
    ),
    "chaos_storm": (
        GuardMetric("value", "lower", 2.5),
    ),
    "quota_surge": (
        GuardMetric("value", "lower", 2.5),
    ),
    "preempt_storm": (
        # scarcity-storm time-to-stable (the surge settle wall)
        GuardMetric("value", "lower", 2.5),
        # disarmed-vs-armed engine.schedule ratio: 1.0 means arming is
        # free; fires if the scarcity plane ever becomes a structural
        # steady-storm cost (the explain_overhead_x discipline)
        GuardMetric("preempt_overhead_x", "lower", 2.0),
        # the bounded-disruption drift round's wall
        GuardMetric("drift_round_s", "lower", 2.5, required=False),
    ),
    "estimator512_wire": (
        GuardMetric("value", "lower", 2.5),
    ),
    "multichip_scaling": (
        GuardMetric("value", "lower", 2.5),
    ),
    "cold_start_first_wave": (
        GuardMetric("value", "lower", 2.0),
        # restored-boot first wave over warm wave: the tier's criterion
        GuardMetric("vs_baseline", "lower", 1.75, required=False),
    ),
}

#: verdicts that fail the guard
FAILING = ("regression", "missing")


def load_record(path: Path) -> dict:
    d = json.loads(path.read_text())
    # the driver's BENCH_r{N}.json wrapper nests the record under
    # "parsed" (docs_from_bench handles the same shape)
    return d["parsed"] if "parsed" in d else d


def spec_for(metric: str) -> Optional[tuple]:
    best = None
    for prefix, metrics in SPECS.items():
        if metric.startswith(prefix):
            if best is None or len(prefix) > len(best[0]):
                best = (prefix, metrics)
    return best


def _record_rank(path: Path) -> tuple:
    m = re.search(r"_r(\d+)\.json$", path.name)
    return (int(m.group(1)) if m else -1, path.stat().st_mtime)


def _trajectory_paths(root: Path) -> list[Path]:
    """The COMMITTED trajectory: git-tracked BENCH_*.json when ``root``
    is a git checkout — an uncommitted local record must never become
    the baseline, or repeated local runs re-baseline on each other and
    a creeping regression never fires. Outside a git checkout (fixture
    dirs, exported trees) every on-disk record counts."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "-C", str(root), "ls-files", "BENCH_*.json"],
            capture_output=True, text=True, timeout=10,
        )
        if out.returncode == 0:
            names = [
                ln.strip() for ln in out.stdout.splitlines() if ln.strip()
            ]
            return [root / n for n in names if (root / n).exists()]
    except Exception:  # noqa: BLE001 — no git: fall through to glob
        pass
    return sorted(root.glob("BENCH_*.json"))


def find_baseline(
    metric: str, *, root: Path = ROOT, exclude: Optional[Path] = None
) -> tuple[Path, dict]:
    """The committed record the fresh one regresses against: same
    ``metric``, newest first; the fresh file itself never baselines
    itself. Loudly refuses when the trajectory has no matching record —
    a guard with nothing to compare must say so, not pass."""
    exclude = exclude.resolve() if exclude is not None else None
    candidates: list[tuple[Path, dict]] = []
    for path in _trajectory_paths(root):
        if exclude is not None and path.resolve() == exclude:
            continue
        try:
            rec = load_record(path)
        except (OSError, ValueError):
            continue
        if rec.get("metric") == metric:
            candidates.append((path, rec))
    if not candidates:
        raise SystemExit(
            f"benchguard: no committed BENCH_*.json in {root} carries "
            f"metric {metric!r} — record a baseline first (the guard "
            "never passes by default)"
        )
    candidates.sort(key=lambda pr: _record_rank(pr[0]))
    return candidates[-1]


def compare(
    fresh: dict, baseline: dict, metrics: Sequence[GuardMetric]
) -> list[dict]:
    """Per-metric verdicts, every guarded metric accounted for —
    ``missing`` (loud failure), ``baseline-missing``/``absent``
    (reported passes), ``regression``, ``improved`` or ``ok``."""
    out: list[dict] = []
    for gm in metrics:
        fv = fresh.get(gm.name)
        bv = baseline.get(gm.name)
        row = {
            "metric": gm.name,
            "direction": gm.direction,
            "band": gm.band,
            "fresh": fv,
            "baseline": bv,
            "ratio": None,
        }
        if not isinstance(fv, (int, float)) or isinstance(fv, bool):
            row["verdict"] = "missing" if gm.required else "absent"
            out.append(row)
            continue
        if not isinstance(bv, (int, float)) or isinstance(bv, bool):
            row["verdict"] = "baseline-missing"
            out.append(row)
            continue
        # worseness ratio: >1 means the fresh record is worse in the
        # guarded direction, whichever direction that is
        if gm.direction == "lower":
            ratio = (fv / bv) if bv else (float("inf") if fv else 1.0)
        else:
            ratio = (bv / fv) if fv else (float("inf") if bv else 1.0)
        row["ratio"] = round(ratio, 4) if ratio != float("inf") else None
        if ratio >= gm.band:
            row["verdict"] = "regression"
        elif ratio <= 1.0 / gm.band:
            row["verdict"] = "improved"
        else:
            row["verdict"] = "ok"
        out.append(row)
    return out


def render_verdicts(
    verdicts: list[dict], *, fresh_name: str, baseline_name: str
) -> str:
    lines = [
        f"benchguard: {fresh_name} vs {baseline_name}",
        f"{'metric':<28} {'dir':<6} {'fresh':>12} {'baseline':>12} "
        f"{'worse x':>8} {'band':>6}  verdict",
    ]

    def fmt(v) -> str:
        if v is None:
            return "-"
        return f"{v:.4g}" if isinstance(v, float) else str(v)

    for row in verdicts:
        lines.append(
            f"{row['metric']:<28} {row['direction']:<6} "
            f"{fmt(row['fresh']):>12} {fmt(row['baseline']):>12} "
            f"{fmt(row['ratio']):>8} {row['band']:>6}  {row['verdict']}"
        )
    failing = [v for v in verdicts if v["verdict"] in FAILING]
    lines.append(
        f"verdict: {'REGRESSION' if failing else 'pass'} "
        f"({len(failing)} failing / {len(verdicts)} guarded)"
    )
    return "\n".join(lines)


def check_record(
    record_path: str | Path,
    *,
    root: Path = ROOT,
    specs: Optional[dict] = None,
) -> tuple[int, dict]:
    """The whole guard for one fresh record: resolve the spec and the
    committed baseline, compare, and answer (exit_code, report). The
    report carries the verdict rows + rendered table; exit 1 on any
    regression or missing metric."""
    record_path = Path(record_path)
    fresh = load_record(record_path)
    metric = fresh.get("metric")
    if not metric:
        raise SystemExit(
            f"benchguard: {record_path} carries no 'metric' field"
        )
    table = spec_for(metric) if specs is None else (
        next(
            (
                (p, m) for p, m in sorted(
                    specs.items(), key=lambda kv: -len(kv[0])
                )
                if metric.startswith(p)
            ),
            None,
        )
    )
    if table is None:
        raise SystemExit(
            f"benchguard: no guard spec for metric family {metric!r} — "
            "add one to tools/benchguard.py SPECS (the guard never "
            "passes a family it does not know)"
        )
    prefix, metrics = table
    baseline_path, baseline = find_baseline(
        metric, root=root, exclude=record_path
    )
    verdicts = compare(fresh, baseline, metrics)
    failing = [v for v in verdicts if v["verdict"] in FAILING]
    report = {
        "metric": metric,
        "family": prefix,
        "fresh": str(record_path),
        "baseline": str(baseline_path),
        "verdicts": verdicts,
        "failing": len(failing),
        "ok": not failing,
        "table": render_verdicts(
            verdicts,
            fresh_name=record_path.name,
            baseline_name=baseline_path.name,
        ),
    }
    return (1 if failing else 0), report


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="benchguard")
    parser.add_argument("record", help="fresh bench record (JSON)")
    parser.add_argument(
        "--root", default=str(ROOT),
        help="repo root holding the committed BENCH_*.json trajectory",
    )
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    args = parser.parse_args(argv)
    code, report = check_record(args.record, root=Path(args.root))
    if args.format == "json":
        print(json.dumps(
            {k: v for k, v in report.items() if k != "table"}, indent=2
        ))
    else:
        print(report["table"])
    return code


if __name__ == "__main__":
    sys.exit(main())
