"""graftlint-dep: abstract row-dependence certification over kernel jaxprs.

ROADMAP item 2 (the incremental dirty-row solve) rests on one property:
per-row kernel outputs depend only on that row's inputs plus replicated
state, so untouched rows can be replayed instead of re-solved. This tier
makes that property machine-checked. For every entry point in the IR
tier's ``ENTRY_POINTS`` registry it runs an abstract interpretation over
the jaxpr the IR tier already traces, propagating which batch-axis rows
of which inputs each value depends on — through element-wise ops,
per-row gathers, reshapes and nested jits — and flagging the cross-row
couplers (sorts, cumulative scans, global reductions, row-axis
contractions, data-dependent scatters).

The per-value lattice (``RowDep.kind``):

- ``repl``    — no dependence on any row of any row-arg (replicated
  state, constants, iota).
- ``row``     — element at row *i* depends only on row *i + off* of the
  row-args (``off`` 0 for the aligned case; a non-zero static offset is
  a PROVEN delta-safety violation at an output).
- ``mixed``   — row-dependent but alignment is lost (data-dependent row
  selection, windowed scans, row-axis concatenation). Not a proof in
  either direction: a ``mixed`` output neither certifies independence
  nor convicts coupling.
- ``coupled`` — PROVEN cross-row information flow (a sort/cumsum/global
  reduction along the row axis, a row-axis contraction, a data-dependent
  scatter). ``reasons`` names the couplers.

Findings only ever come from PROOFS (IR006 fires on a contradicted
declaration, never on ``mixed``), so unknown primitives degrade to
``mixed`` — conservative, sound both directions.

Two rule families consume the analysis (deprules.py): IR006
row-independence certification against the explicit ``row_coupled``
declarations every registered kernel must carry, and IR007 replicated-
scan discipline over the sharded spec variants (the PR 9 CPU-SPMD
miscompile class: a cross-row coupler consuming operands that were not
re-replicated).

Run it:

    python -m tools.graftlint --dep                  # full registry
    python -m tools.graftlint --dep divide_replicas  # one family
    python -m tools.graftlint --all                  # AST + IR + dep
    python -m tools.graftlint.dep                    # debug verdict dump

Like the IR tier, tracing is abstract (``jax.make_jaxpr`` over
``ShapeDtypeStruct``s, no compiles) and the analysis itself is pure
Python over the jaxpr — the full grid runs in seconds and is a tier-1
gate (tests/test_graftlint_dep.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from . import deprules  # noqa: F401 — registers the IR006/IR007 analyzers
from .core import DEP_RULES, apply_baseline, default_config

# --------------------------------------------------------------------------
# the lattice
# --------------------------------------------------------------------------

_ORDER = {"repl": 0, "row": 1, "mixed": 2, "coupled": 3}


@dataclass(frozen=True)
class RowDep:
    """Abstract row-dependence of one jaxpr value (see module docstring).

    ``plane`` carries the flat input positions of declared plane-state
    args the value depends on (any kind — the first_fit_group cohort
    channel); ``repl_ok`` is the IR007 mark: True while every row-
    dependent ancestor has been re-replicated (or never sharded)."""

    kind: str = "repl"
    axis: int = -1
    off: object = 0
    reasons: frozenset = frozenset()
    plane: frozenset = frozenset()
    repl_ok: bool = True

    @property
    def row_dependent(self) -> bool:
        return self.kind != "repl"


REPL = RowDep()


def row(axis: int, off: object = 0, *, plane=frozenset(), ok=True) -> RowDep:
    return RowDep("row", axis, off, plane=frozenset(plane), repl_ok=ok)


def mixed(src: RowDep = REPL, *more: RowDep) -> RowDep:
    """Row-dependent with alignment lost; keeps coupling + plane/mark."""
    states = (src,) + more
    if any(s.kind == "coupled" for s in states):
        return join(*states)
    return RowDep(
        "mixed",
        reasons=frozenset().union(*(s.reasons for s in states)),
        plane=frozenset().union(*(s.plane for s in states)),
        repl_ok=all(s.repl_ok for s in states),
    )


def coupled(reason: str, *srcs: RowDep) -> RowDep:
    return RowDep(
        "coupled",
        reasons=frozenset({reason}).union(*(s.reasons for s in srcs)),
        plane=frozenset().union(*(s.plane for s in srcs)),
        repl_ok=all(s.repl_ok for s in srcs) if srcs else True,
    )


def _offs_compat(a: object, b: object) -> Optional[bool]:
    """True = provably equal, False = provably different (both static
    ints), None = cannot tell (at least one symbolic token)."""
    if a == b:
        return True
    if isinstance(a, int) and isinstance(b, int):
        return False
    return None


def join(*states: RowDep, combine: bool = False) -> RowDep:
    """Least upper bound. ``combine=True`` is the element-wise dataflow
    product: two row-aligned operands with provably DIFFERENT static
    offsets couple neighbouring rows (the ``a[1:] - a[:-1]`` class),
    which a pure control-flow merge (select branches) does not."""
    states = [s for s in states if s is not None]
    if not states:
        return REPL
    plane = frozenset().union(*(s.plane for s in states))
    reasons = frozenset().union(*(s.reasons for s in states))
    ok = all(s.repl_ok for s in states)
    top = max(states, key=lambda s: _ORDER[s.kind])
    if top.kind == "coupled":
        return RowDep("coupled", reasons=reasons, plane=plane, repl_ok=ok)
    rows = [s for s in states if s.kind == "row"]
    if top.kind == "row":
        axes = {s.axis for s in rows}
        if len(axes) == 1:
            offs = {s.off for s in rows}
            if len(offs) == 1:
                return RowDep("row", rows[0].axis, rows[0].off,
                              reasons=reasons, plane=plane, repl_ok=ok)
            compat = None
            for s in rows[1:]:
                compat = _offs_compat(rows[0].off, s.off)
                if compat is False:
                    break
            if compat is False and combine:
                return RowDep("coupled",
                              reasons=reasons | {"shifted-combine"},
                              plane=plane, repl_ok=ok)
        return RowDep("mixed", reasons=reasons, plane=plane, repl_ok=ok)
    if top.kind == "mixed":
        return RowDep("mixed", reasons=reasons, plane=plane, repl_ok=ok)
    return RowDep("repl", plane=plane, repl_ok=ok) if (plane or not ok) \
        else REPL


def _shift_off(off: object, delta: int) -> object:
    if delta == 0:
        return off
    if isinstance(off, int):
        return off + delta
    return ("add", off, delta)


# --------------------------------------------------------------------------
# coupler events (IR007 inputs)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class CouplerEvent:
    """One cross-row coupler the analysis walked through: ``proven``
    marks a definite row-axis coupler (vs a coupler-class op over a
    ``mixed`` value that MIGHT span rows); ``replicated_ok`` is False
    when a row-sharded, never-re-replicated value feeds it (the PR 9
    miscompile precondition IR007 fires on)."""

    prim: str
    reason: str
    proven: bool
    replicated_ok: bool


# --------------------------------------------------------------------------
# the abstract interpreter
# --------------------------------------------------------------------------

_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "max", "min", "and", "or", "xor",
    "not", "neg", "sign", "abs", "eq", "ne", "ge", "gt", "le", "lt",
    "select_n", "convert_element_type", "shift_left",
    "shift_right_arithmetic", "shift_right_logical", "clamp", "pow",
    "integer_pow", "exp", "log", "sqrt", "rsqrt", "floor", "ceil",
    "round", "logistic", "tanh", "erf", "erf_inv", "is_finite",
    "nextafter", "copy", "stop_gradient", "real", "imag",
    "population_count", "clz", "le_to", "lt_to", "square", "atan2",
    "expm1", "log1p", "rev_dummy",
})

_CUMULATIVE = frozenset({
    "cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp",
})

_REDUCES = frozenset({
    "reduce_sum", "reduce_prod", "reduce_max", "reduce_min", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin",
})

_SCATTERS = frozenset({
    "scatter", "scatter-add", "scatter-mul", "scatter-min", "scatter-max",
})


def _aval(v):
    return getattr(v, "aval", None)


def _shape(v) -> tuple:
    aval = _aval(v)
    return tuple(getattr(aval, "shape", ()) or ())


def _is_literal(v) -> bool:
    return not hasattr(v, "count") and hasattr(v, "val")


class _Analyzer:
    """One jaxpr walk. ``env`` maps jaxpr Vars to RowDep states; ``vn``
    value-numbers scalar index computations so two eqns computing the
    same start offset (``i * chunk`` twice) share one symbolic token."""

    def __init__(self, events: list, sharded: bool, depth: int = 0):
        self.events = events
        self.sharded = sharded
        self.depth = depth
        self.env: dict = {}
        self.vn: dict = {}
        self._vn_next = 0

    # -- environment -------------------------------------------------------

    def read(self, v) -> RowDep:
        if _is_literal(v):
            return REPL
        return self.env.get(v, REPL)

    def write(self, v, state: RowDep) -> None:
        if not _shape(v) and state.kind == "row":
            # a scalar has no row axis: a row-state reduced to rank 0
            # means one row was selected data-dependently
            state = mixed(state)
        self.env[v] = state

    def token(self, v) -> object:
        """Value number of a (scalar) var: literals by value, vars by a
        structural hash of the producing eqn so CSE-equivalent index
        arithmetic compares equal."""
        if _is_literal(v):
            val = v.val
            try:
                return int(val)
            except (TypeError, ValueError):
                return ("lit", repr(val))
        if v in self.vn:
            return self.vn[v]
        self._vn_next += 1
        tok = ("var", self.depth, self._vn_next)
        self.vn[v] = tok
        return tok

    def _number_eqn(self, eqn) -> None:
        """Forward value numbering: outvars of structurally identical
        eqns over identically-numbered operands share a token."""
        try:
            params = tuple(sorted(
                (k, repr(val)) for k, val in eqn.params.items()
                if not hasattr(val, "jaxpr")
                and not isinstance(val, (tuple, list))
            ))
        except Exception:  # noqa: BLE001 — numbering is best-effort
            return
        key = (eqn.primitive.name, params,
               tuple(self.token(v) for v in eqn.invars))
        for i, ov in enumerate(eqn.outvars):
            self.vn[ov] = ("eqn", key, i)

    def event(self, prim: str, reason: str, proven: bool, *srcs: RowDep):
        ok = all(s.repl_ok or not s.row_dependent for s in srcs)
        self.events.append(CouplerEvent(prim, reason, proven, ok))

    # -- the walk ----------------------------------------------------------

    def run(self, jaxpr, in_states: list) -> list:
        for cv in jaxpr.constvars:
            self.env[cv] = REPL
        for v, s in zip(jaxpr.invars, in_states):
            self.env[v] = s
        for eqn in jaxpr.eqns:
            self._number_eqn(eqn)
            self.eqn(eqn)
        return [self.read(v) for v in jaxpr.outvars]

    def sub(self, closed, in_states: list) -> list:
        """Recurse into a closed subjaxpr sharing events + numbering
        scope (tokens are depth-tagged, so inner vars never alias)."""
        inner = _Analyzer(self.events, self.sharded, self.depth + 1)
        inner.vn = self.vn
        inner._vn_next = self._vn_next
        out = inner.run(closed.jaxpr, in_states)
        self._vn_next = inner._vn_next
        return out

    def eqn(self, eqn) -> None:
        name = eqn.primitive.name
        handler = getattr(self, "_p_" + name.replace("-", "_"), None)
        states = [self.read(v) for v in eqn.invars]
        if handler is not None:
            handler(eqn, states)
        elif name in _ELEMENTWISE:
            self._write_all(eqn, join(*states, combine=True))
        elif name in _CUMULATIVE:
            self._cumulative(eqn, states)
        elif name in _REDUCES:
            self._reduce(eqn, states)
        elif name in _SCATTERS:
            self._scatter(eqn, states)
        else:
            # unknown primitive: recurse into any subjaxpr params, else
            # degrade row-dependent inputs to mixed (sound: proofs never
            # come from unknowns)
            subs = [val for val in eqn.params.values()
                    if hasattr(val, "jaxpr")]
            if len(subs) == 1 and len(subs[0].jaxpr.invars) == len(states):
                out = self.sub(subs[0], states)
                for v, s in zip(eqn.outvars, out):
                    self.write(v, s)
                return
            self._write_all(eqn, self._conservative(states))

    def _write_all(self, eqn, state: RowDep) -> None:
        for v in eqn.outvars:
            self.write(v, state)

    @staticmethod
    def _conservative(states: list) -> RowDep:
        st = join(*states)
        return mixed(st) if st.kind == "row" else st

    # -- structural primitives ---------------------------------------------

    def _p_iota(self, eqn, states):
        self._write_all(eqn, REPL)

    def _p_broadcast_in_dim(self, eqn, states):
        st = states[0]
        if st.kind == "row":
            bd = tuple(eqn.params.get("broadcast_dimensions", ()))
            if st.axis < len(bd):
                st = RowDep("row", bd[st.axis], st.off, st.reasons,
                            st.plane, st.repl_ok)
            else:
                st = mixed(st)
        self._write_all(eqn, st)

    def _p_reshape(self, eqn, states):
        st = states[0]
        if eqn.params.get("dimensions") is not None:
            st = mixed(st) if st.kind == "row" else st
        elif st.kind == "row":
            old = _shape(eqn.invars[0])
            new = _shape(eqn.outvars[0])
            st = self._remap_reshape(st, old, new)
        self._write_all(eqn, st)

    @staticmethod
    def _remap_reshape(st: RowDep, old: tuple, new: tuple) -> RowDep:
        """The row axis survives a reshape iff an output axis has the
        same extent at the same leading-stride position (prefix products
        match) — merging the row axis with a neighbour loses it."""
        if st.axis >= len(old):
            return mixed(st)
        prefix = 1
        for d in old[:st.axis]:
            prefix *= d
        extent = old[st.axis]
        acc = 1
        for i, d in enumerate(new):
            if acc == prefix and d == extent:
                # the dims after must also multiply out (always true
                # when total sizes agree, which reshape guarantees)
                return RowDep("row", i, st.off, st.reasons, st.plane,
                              st.repl_ok)
            acc *= d
            if acc > prefix:
                break
        return mixed(st)

    def _p_squeeze(self, eqn, states):
        st = states[0]
        if st.kind == "row":
            dims = sorted(eqn.params.get("dimensions", ()))
            if st.axis in dims:
                st = mixed(st)  # size-1 row axis squeezed away
            else:
                shift = sum(1 for d in dims if d < st.axis)
                st = RowDep("row", st.axis - shift, st.off, st.reasons,
                            st.plane, st.repl_ok)
        self._write_all(eqn, st)

    def _p_expand_dims(self, eqn, states):
        st = states[0]
        if st.kind == "row":
            dims = sorted(eqn.params.get("dimensions", ()))
            ax = st.axis
            for d in dims:
                if d <= ax:
                    ax += 1
            st = RowDep("row", ax, st.off, st.reasons, st.plane,
                        st.repl_ok)
        self._write_all(eqn, st)

    def _p_transpose(self, eqn, states):
        st = states[0]
        if st.kind == "row":
            perm = tuple(eqn.params.get("permutation", ()))
            if st.axis in perm:
                st = RowDep("row", perm.index(st.axis), st.off,
                            st.reasons, st.plane, st.repl_ok)
            else:
                st = mixed(st)
        self._write_all(eqn, st)

    def _p_slice(self, eqn, states):
        st = states[0]
        if st.kind == "row":
            starts = tuple(eqn.params.get("start_indices", ()))
            strides = eqn.params.get("strides") or (1,) * len(starts)
            if st.axis < len(starts):
                if strides[st.axis] != 1:
                    st = mixed(st)
                elif starts[st.axis]:
                    st = RowDep("row", st.axis,
                                _shift_off(st.off, int(starts[st.axis])),
                                st.reasons, st.plane, st.repl_ok)
        self._write_all(eqn, st)

    def _p_pad(self, eqn, states):
        st = join(states[0], states[1] if len(states) > 1 else REPL)
        base = states[0]
        if base.kind == "row":
            cfg = tuple(eqn.params.get("padding_config", ()))
            if base.axis < len(cfg):
                lo, _hi, interior = cfg[base.axis]
                if interior:
                    st = mixed(base)
                elif lo:
                    st = RowDep("row", base.axis,
                                _shift_off(base.off, -int(lo)),
                                base.reasons, base.plane, base.repl_ok)
                else:
                    st = base
            else:
                st = base
        self._write_all(eqn, st)

    def _p_concatenate(self, eqn, states):
        dim = eqn.params.get("dimension", 0)
        st = join(*states)
        if any(s.kind == "row" and s.axis == dim for s in states):
            st = mixed(*states)  # rows re-indexed by the stacking
        self._write_all(eqn, st)

    def _p_rev(self, eqn, states):
        st = states[0]
        if st.kind == "row" and st.axis in tuple(
            eqn.params.get("dimensions", ())
        ):
            st = mixed(st)
        self._write_all(eqn, st)

    # -- couplers ----------------------------------------------------------

    def _cumulative(self, eqn, states):
        axis = eqn.params.get("axis", 0)
        st = states[0]
        name = eqn.primitive.name
        if st.kind == "row" and st.axis == axis:
            self.event(name, f"{name}[axis={axis}]", True, st)
            self._write_all(eqn, coupled(name, st))
        elif st.kind == "mixed":
            self.event(name, f"{name}[axis={axis}] over mixed", False, st)
            self._write_all(eqn, st)
        else:
            self._write_all(eqn, st)

    def _reduce(self, eqn, states):
        axes = tuple(eqn.params.get("axes", ()))
        st = states[0]
        name = eqn.primitive.name
        if st.kind == "row":
            if st.axis in axes:
                self.event(name, f"{name}[axes={axes}]", True, st)
                self._write_all(eqn, coupled(name, st))
            else:
                shift = sum(1 for a in axes if a < st.axis)
                self._write_all(eqn, RowDep(
                    "row", st.axis - shift, st.off, st.reasons, st.plane,
                    st.repl_ok,
                ))
        else:
            self._write_all(eqn, st)

    def _p_sort(self, eqn, states):
        dim = eqn.params.get("dimension", -1)
        st = join(*states)
        rowish = [s for s in states if s.kind == "row" and s.axis == dim]
        if rowish:
            self.event("sort", f"sort[dimension={dim}]", True, *states)
            st = coupled("sort", *states)
        elif st.kind == "mixed":
            self.event("sort", f"sort[dimension={dim}] over mixed",
                       False, *states)
        self._write_all(eqn, st)

    def _p_top_k(self, eqn, states):
        st = states[0]
        last = len(_shape(eqn.invars[0])) - 1
        if st.kind == "row" and st.axis == last:
            self.event("top_k", "top_k over the row axis", True, st)
            st = coupled("top_k", st)
        elif st.kind == "mixed":
            self.event("top_k", "top_k over mixed", False, st)
        self._write_all(eqn, st)

    def _p_dot_general(self, eqn, states):
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lc, rc, lb, rb = tuple(lc), tuple(rc), tuple(lb), tuple(rb)
        lhs, rhs = states[0], states[1]
        for st, contract in ((lhs, lc), (rhs, rc)):
            if st.kind == "row" and st.axis in contract:
                self.event("dot_general", "contraction over the row axis",
                           True, lhs, rhs)
                self._write_all(eqn, coupled("dot_general", lhs, rhs))
                return
        # output layout: batch dims, then lhs free dims, then rhs free
        # dims. A row axis on exactly one side's batch/free dims keeps
        # alignment; row axes on BOTH sides is an outer product of rows
        # our single-axis state cannot represent — degrade to mixed.
        lhs_free = [d for d in range(len(_shape(eqn.invars[0])))
                    if d not in lc and d not in lb]
        rhs_free = [d for d in range(len(_shape(eqn.invars[1])))
                    if d not in rc and d not in rb]
        out = []
        for st, batch, free, base in (
            (lhs, lb, lhs_free, len(lb)),
            (rhs, rb, rhs_free, len(lb) + len(lhs_free)),
        ):
            if st.kind != "row":
                out.append(st)
            elif st.axis in batch:
                out.append(RowDep("row", batch.index(st.axis), st.off,
                                  st.reasons, st.plane, st.repl_ok))
            elif st.axis in free:
                out.append(RowDep("row", base + free.index(st.axis),
                                  st.off, st.reasons, st.plane,
                                  st.repl_ok))
            else:
                out.append(mixed(st))
        if all(s.kind == "row" for s in out) and \
                out[0].axis != out[1].axis:
            out = [mixed(*out)]
        self._write_all(eqn, join(*out, combine=True))

    def _p_gather(self, eqn, states):
        operand, indices = states[0], states[1]
        dn = eqn.params.get("dimension_numbers")
        out_rank = len(_shape(eqn.outvars[0]))
        offset_dims = tuple(getattr(dn, "offset_dims", ()))
        start_map = tuple(getattr(dn, "start_index_map", ()))
        op_batch = tuple(getattr(dn, "operand_batching_dims", ()))
        collapsed = tuple(getattr(dn, "collapsed_slice_dims", ()))
        slice_sizes = tuple(eqn.params.get("slice_sizes", ()))
        batch_out = [d for d in range(out_rank) if d not in offset_dims]

        def idx_out_state(idx_st: RowDep) -> RowDep:
            # indices row axis -> the matching output batch dim (index
            # axes map to output batch dims in order, minus the trailing
            # index-vector axis)
            if idx_st.kind != "row":
                return idx_st if idx_st.kind != "repl" else REPL
            if idx_st.axis < len(batch_out):
                return RowDep("row", batch_out[idx_st.axis], idx_st.off,
                              idx_st.reasons, idx_st.plane,
                              idx_st.repl_ok)
            return mixed(idx_st)

        if operand.kind == "repl":
            self._write_all(eqn, join(idx_out_state(indices), RowDep(
                "repl", plane=operand.plane, repl_ok=operand.repl_ok,
            )))
            return
        if operand.kind == "coupled" or indices.kind == "coupled":
            self._write_all(eqn, join(operand, indices))
            return
        if operand.kind == "row":
            ax = operand.axis
            if ax in op_batch:
                # per-row gather (the vmap form): operand row axis is a
                # batching dim — row identity carried by the indices'
                # own batching axis; output stays row-aligned when the
                # indices are row-aligned or replicated
                ib = idx_out_state(indices)
                pos = op_batch.index(ax)
                tgt = batch_out[pos] if pos < len(batch_out) else None
                base = RowDep("row", tgt, operand.off, operand.reasons,
                              operand.plane, operand.repl_ok) \
                    if tgt is not None else mixed(operand)
                self._write_all(eqn, join(base, ib))
                return
            if ax in start_map:
                # gathering ACROSS rows: data-dependent row selection
                self._write_all(eqn, mixed(operand, indices))
                return
            if ax not in collapsed and ax < len(slice_sizes) and \
                    slice_sizes[ax] == _shape(eqn.invars[0])[ax]:
                # full slice along the row axis: row axis maps into the
                # offset dims (its rank among non-collapsed slice dims)
                kept = [d for d in range(len(slice_sizes))
                        if d not in collapsed and d not in op_batch]
                if ax in kept and kept.index(ax) < len(offset_dims):
                    tgt = offset_dims[kept.index(ax)]
                    self._write_all(eqn, join(
                        RowDep("row", tgt, operand.off, operand.reasons,
                               operand.plane, operand.repl_ok),
                        idx_out_state(indices),
                    ))
                    return
            self._write_all(eqn, mixed(operand, indices))
            return
        self._write_all(eqn, mixed(operand, indices))

    def _scatter(self, eqn, states):
        operand, indices, updates = states[0], states[1], states[2]
        name = eqn.primitive.name
        dn = eqn.params.get("dimension_numbers")
        addressed = tuple(
            getattr(dn, "scatter_dims_to_operand_dims", ())
        )
        if indices.row_dependent and operand.kind == "row" and \
                operand.axis in addressed:
            # data-dependent placement INTO the row axis of existing
            # row state: changing one row of the index input moves
            # another row's data — proven cross-row flow (scatter_rows)
            self.event(name, "data-dependent scatter into the row axis",
                       True, *states)
            self._write_all(eqn, coupled("scatter", *states))
            return
        if indices.row_dependent:
            # data-dependent placement into a fresh/replicated buffer:
            # usually per-row via an iota index component, but the
            # component structure is lost in the fused index array —
            # alignment unprovable either way
            self._write_all(eqn, mixed(operand, indices, updates))
            return
        self._write_all(eqn, self._conservative(states))

    # -- dynamic slicing ---------------------------------------------------

    def _p_dynamic_slice(self, eqn, states):
        operand = states[0]
        starts = eqn.invars[1:]
        start_states = states[1:]
        st = operand
        if operand.kind == "row":
            shape = _shape(eqn.invars[0])
            sizes = tuple(eqn.params.get("slice_sizes",
                                         _shape(eqn.outvars[0])))
            ax = operand.axis
            sv = starts[ax] if ax < len(starts) else None
            tok = self.token(sv) if sv is not None else 0
            if tok == 0 and ax < len(sizes) and sizes[ax] == shape[ax]:
                pass  # identity along the row axis
            else:
                st = RowDep("row", ax, _shift_off(operand.off, 0)
                            if tok == 0 else ("dyn", tok, operand.off),
                            operand.reasons, operand.plane,
                            operand.repl_ok)
        taint = join(*start_states) if start_states else REPL
        if taint.row_dependent:
            st = mixed(st, taint)
        else:
            st = join(st, taint) if taint.plane or not taint.repl_ok \
                else st
        self._write_all(eqn, st)

    def _p_dynamic_update_slice(self, eqn, states):
        operand, update = states[0], states[1]
        start_states = states[2:]
        starts = eqn.invars[2:]
        taint = join(*start_states) if start_states else REPL
        same_shape = _shape(eqn.invars[0]) == _shape(eqn.invars[1])
        all_zero = all(
            self.token(s) == 0 for s in starts
        ) if starts else True
        if taint.row_dependent:
            st = mixed(operand, update, taint)
        elif same_shape and all_zero:
            st = join(operand, update, combine=True)
        else:
            st = self._conservative([operand, update, taint])
        self._write_all(eqn, st)

    # -- sharding / control flow -------------------------------------------

    def _p_sharding_constraint(self, eqn, states):
        st = states[0]
        sharding = eqn.params.get("sharding")
        fully_repl = bool(getattr(sharding, "is_fully_replicated", False))
        self._write_all(eqn, RowDep(
            st.kind, st.axis, st.off, st.reasons, st.plane, fully_repl,
        ))

    def _p_pjit(self, eqn, states):
        closed = eqn.params.get("jaxpr")
        if closed is None:
            self._write_all(eqn, self._conservative(states))
            return
        out = self.sub(closed, states)
        for v, s in zip(eqn.outvars, out):
            self.write(v, s)

    _p_closed_call = _p_pjit
    _p_core_call = _p_pjit
    _p_remat = _p_pjit

    def _p_custom_jvp_call(self, eqn, states):
        closed = eqn.params.get("call_jaxpr") or eqn.params.get("jaxpr")
        if closed is None or not hasattr(closed, "jaxpr"):
            self._write_all(eqn, self._conservative(states))
            return
        out = self.sub(closed, states)
        for v, s in zip(eqn.outvars, out):
            self.write(v, s)

    _p_custom_vjp_call = _p_custom_jvp_call
    _p_custom_vjp_call_jaxpr = _p_custom_jvp_call

    def _p_cond(self, eqn, states):
        branches = eqn.params.get("branches", ())
        idx_state, op_states = states[0], states[1:]
        outs = None
        for br in branches:
            bout = self.sub(br, list(op_states))
            outs = bout if outs is None else [
                join(a, b) for a, b in zip(outs, bout)
            ]
        if outs is None:
            self._write_all(eqn, self._conservative(states))
            return
        for v, s in zip(eqn.outvars, outs):
            self.write(v, join(s, idx_state) if idx_state.row_dependent
                       or idx_state.plane or not idx_state.repl_ok else s)

    def _p_while(self, eqn, states):
        body = eqn.params.get("body_jaxpr")
        cond = eqn.params.get("cond_jaxpr")
        cn = eqn.params.get("cond_nconsts", 0)
        bn = eqn.params.get("body_nconsts", 0)
        if body is None:
            self._write_all(eqn, self._conservative(states))
            return
        cconsts = states[:cn]
        bconsts = states[cn:cn + bn]
        carry = list(states[cn + bn:])
        # the join is monotone in every dimension (kind climbs, offset
        # divergence climbs to mixed, plane/reasons grow, repl_ok only
        # drops), so the fixpoint terminates; the cap is defensive
        for _ in range(32):
            out = self.sub(body, bconsts + carry)
            nxt = [join(a, b) for a, b in zip(carry, out)]
            if nxt == carry:
                break
            carry = nxt
        else:
            carry = [mixed(s) if s.kind == "row" else s for s in carry]
        cond_taint = join(*(cconsts or [REPL]))
        for v, s in zip(eqn.outvars, carry):
            self.write(v, mixed(s, cond_taint)
                       if cond_taint.row_dependent else s)

    def _p_scan(self, eqn, states):
        closed = eqn.params.get("jaxpr")
        n_consts = eqn.params.get("num_consts", 0)
        n_carry = eqn.params.get("num_carry", 0)
        if closed is None:
            self._write_all(eqn, self._conservative(states))
            return
        consts = states[:n_consts]
        carry = list(states[n_consts:n_consts + n_carry])
        xs = states[n_consts + n_carry:]
        # per-iteration slices of the xs: scanning over a row axis feeds
        # one row per step — inside the body that value is row-blind,
        # but any flow into the carry is a sequential cross-row
        # accumulation (the prefix-scan pattern), which we prove by
        # tainting the body-level x states and watching the carry.
        xs_body = []
        scanned_rows = False
        for s in xs:
            if s.kind == "row" and s.axis == 0:
                scanned_rows = True
                xs_body.append(RowDep("row", -2, s.off, s.reasons,
                                      s.plane, s.repl_ok))
            elif s.kind == "row":
                xs_body.append(RowDep("row", s.axis - 1, s.off, s.reasons,
                                      s.plane, s.repl_ok))
            else:
                xs_body.append(s)
        for _ in range(32):  # monotone join: terminates (see _p_while)
            out = self.sub(closed, consts + carry + xs_body)
            carry_out = out[:n_carry]
            nxt = [join(a, b) for a, b in zip(carry, carry_out)]
            if nxt == carry:
                break
            carry = nxt
        out = self.sub(closed, consts + carry + xs_body)
        carry_out, ys = out[:n_carry], out[n_carry:]
        if scanned_rows:
            # row data flowing into the carry = proven sequential
            # coupling across rows
            carry_final = []
            for s in carry_out:
                if s.row_dependent:
                    self.event("scan", "row data accumulated through the "
                               "scan carry", True, s)
                    carry_final.append(coupled("scan-carry", s))
                else:
                    carry_final.append(s)
            ys_final = []
            for s in ys:
                if s.kind == "row" and s.axis == -2:
                    # purely per-iteration output of a row scan: stacked
                    # back along the leading axis, row-aligned
                    ys_final.append(RowDep("row", 0, s.off, s.reasons,
                                           s.plane, s.repl_ok))
                elif s.row_dependent:
                    ys_final.append(mixed(s))
                else:
                    ys_final.append(s)
        else:
            # a non-row scan (fori_loop-style iteration): a FIXPOINT-
            # stable row carry is provably aligned at every step, so it
            # passes through; ys gain a leading iteration axis, shifting
            # a body-level row axis by one
            carry_final = list(carry_out)
            ys_final = [
                RowDep("row", s.axis + 1, s.off, s.reasons, s.plane,
                       s.repl_ok) if s.kind == "row" else s
                for s in ys
            ]
        for v, s in zip(eqn.outvars, carry_final + ys_final):
            self.write(v, s)


# --------------------------------------------------------------------------
# per-trace analysis + driver
# --------------------------------------------------------------------------


@dataclass
class DepAnalysis:
    """The dep tier's per-trace result the IR006/IR007 rules consume."""

    traced: object  # ir.TracedKernel
    out_states: list
    events: list
    sharded: bool
    error: Optional[str] = None

    @property
    def verdict(self) -> str:
        """'independent' (proven), 'coupled' (proven), or 'unproven'."""
        if self.error:
            return "unproven"
        kinds = {s.kind for s in self.out_states}
        if "coupled" in kinds:
            return "coupled"
        for s in self.out_states:
            if s.kind == "row" and isinstance(s.off, int) and s.off != 0:
                return "coupled"  # statically row-shifted output
        if kinds <= {"repl", "row"}:
            return "independent"
        return "unproven"

    @property
    def coupler_reasons(self) -> tuple:
        out = frozenset()
        for s in self.out_states:
            out |= s.reasons
        return tuple(sorted(out))

    @property
    def plane_deps(self) -> frozenset:
        return frozenset().union(*(s.plane for s in self.out_states)) \
            if self.out_states else frozenset()


def analyze_trace(traced) -> DepAnalysis:
    """Run the abstract interpretation over one TracedKernel."""
    entry = traced.entry
    sharded = traced.spec.statics.get("mesh") is not None
    events: list = []
    closed = traced.closed_jaxpr
    n_in = len(closed.jaxpr.invars)
    row_args = set(getattr(entry, "row_args", ()) or ())
    plane_args = set(getattr(entry, "plane_args", ()) or ())
    in_states = []
    for i in range(n_in):
        plane = frozenset({i}) if i in plane_args else frozenset()
        if i in row_args:
            in_states.append(RowDep("row", 0, 0, plane=plane,
                                    repl_ok=not sharded))
        else:
            in_states.append(RowDep("repl", plane=plane))
    try:
        out = _Analyzer(events, sharded).run(closed.jaxpr, in_states)
    except Exception as exc:  # noqa: BLE001 — an analyzer crash must
        # degrade to 'unproven', never abort the whole run
        return DepAnalysis(traced, [], events, sharded,
                           error=f"analysis failed: {exc!r}")
    return DepAnalysis(traced, out, events, sharded)


class DepContext:
    """Cross-rule state of one dep run (the IRContext analogue)."""

    def __init__(self, config, entries: dict, full_run: bool):
        self.config = config
        self.entries = entries
        self.full_run = full_run
        self.analyses: list = []  # DepAnalysis, trace order
        self.trace_failures: list = []  # (entry, spec, err)
        self._modinfos: dict = {}
        self._def_lines: dict = {}

    def by_entry(self) -> dict:
        out: dict = {}
        for a in self.analyses:
            out.setdefault(a.traced.entry.name, []).append(a)
        return out


def declared_row_coupled(entry) -> dict:
    """Every declaration surface for one entry: the registry field, the
    live function attribute, and (manifest kernels only) the prewarm
    name->row_coupled dict. Missing surfaces map to None."""
    from .ir import resolve_kernel

    out = {"registry": getattr(entry, "row_coupled", None)}
    try:
        fn = resolve_kernel(entry)
        out["kernel"] = getattr(fn, "row_coupled", None)
    except Exception as exc:  # noqa: BLE001 — surfaced by IR004 already
        out["kernel"] = None
        out["kernel_error"] = repr(exc)
    if entry.manifest_kernel:
        from karmada_tpu.scheduler import prewarm

        kernels = prewarm._KERNELS
        out["prewarm"] = (
            kernels.get(entry.manifest_kernel)
            if isinstance(kernels, dict) else None
        )
    return out


def run_dep(
    families=None,
    *,
    root=None,
    baseline="auto",
    entries: Optional[dict] = None,
):
    """One-call API behind ``--dep`` and the tier-1 gate — mirrors
    ``ir.run_ir``: ``families`` filters by entry name, ``entries``
    substitutes the registry wholesale (the seeded-mutant fixtures)."""
    from .ir import ENTRY_POINTS, IRContext, trace_spec

    config = default_config(root)
    registry = dict(entries) if entries is not None else dict(ENTRY_POINTS)
    full_run = entries is None and not families
    if families:
        unknown = sorted(set(families) - set(registry))
        if unknown:
            raise KeyError(
                f"unknown kernel families {unknown}; known: "
                f"{sorted(registry)}"
            )
        registry = {name: registry[name] for name in families}

    ctx = DepContext(config, registry, full_run)
    # reuse the IR tier's def-line/suppression machinery via a throwaway
    # IRContext (same config, same parsed-module cache semantics)
    irctx = IRContext(config, registry)
    ctx._ir = irctx
    for entry in registry.values():
        line = irctx.entry_line(entry)
        for spec in entry.make_specs():
            try:
                traced = trace_spec(entry, spec, line)
            except Exception as exc:  # noqa: BLE001 — IR004 territory;
                # the dep tier reports it as an unprovable entry
                ctx.trace_failures.append((entry, spec, repr(exc)))
                continue
            ctx.analyses.append(analyze_trace(traced))

    raw: list = []
    suppressed = 0
    seen: set = set()
    for r in DEP_RULES.values():
        found: list = []
        for a in ctx.analyses:
            found.extend(r.check(a, ctx))
        found.extend(r.finalize(ctx))
        for f in found:
            key = (f.identity, f.line)
            if key in seen:
                continue
            seen.add(key)
            mod = irctx.modinfo(f.path)
            if mod is not None and mod.suppressed(
                f.rule, f.line, f.anchor_line
            ):
                suppressed += 1
            else:
                raw.append(f)

    baseline_path = None
    if baseline == "auto":
        baseline_path = config.root / config.baseline_path
    elif baseline:
        baseline_path = config.root / baseline
    checked = len(ctx.analyses) + len(ctx.trace_failures)
    return apply_baseline(
        raw, baseline=baseline_path, checked_files=checked,
        suppressed=suppressed,
    )


# --------------------------------------------------------------------------
# the delta-safe registry surface (docs table + the future dirty-row solve)
# --------------------------------------------------------------------------


def delta_safe_registry(root=None) -> list:
    """Per-entry certification summary, the single source the generated
    DEVELOPMENT.md table renders from and the incremental solve will
    assert at arm time: ``delta_safe`` is True only for kernels DECLARED
    row-independent whose every spec variant the analyzer PROVES
    independent."""
    from .ir import ENTRY_POINTS, IRContext, trace_spec

    config = default_config(root)
    irctx = IRContext(config, dict(ENTRY_POINTS))
    rows = []
    for entry in ENTRY_POINTS.values():
        verdicts = []
        plane = frozenset()
        for spec in entry.make_specs():
            try:
                traced = trace_spec(entry, spec, irctx.entry_line(entry))
            except Exception:  # noqa: BLE001 — IR004's finding, not ours
                verdicts.append("unproven")
                continue
            a = analyze_trace(traced)
            verdicts.append(a.verdict)
            plane |= a.plane_deps
        if "coupled" in verdicts:
            verdict = "coupled"
        elif verdicts and all(v == "independent" for v in verdicts):
            verdict = "independent"
        else:
            verdict = "unproven"
        declared = getattr(entry, "row_coupled", None)
        rows.append({
            "name": entry.name,
            "family": entry.family,
            "row_coupled": declared,
            "verdict": verdict,
            "plane_coupled": bool(plane),
            "delta_safe": declared is False and verdict == "independent",
        })
    return rows


def render_delta_safe_table(root=None) -> str:
    rows = delta_safe_registry(root)
    out = [
        "| kernel | family | `row_coupled` | analyzer verdict | "
        "`delta_safe` |",
        "|---|---|---|---|---|",
    ]
    for r in rows:
        verdict = r["verdict"]
        if r["plane_coupled"]:
            verdict += " (plane-state input)"
        out.append(
            f"| `{r['name']}` | {r['family']} | `{r['row_coupled']}` | "
            f"{verdict} | {'yes' if r['delta_safe'] else 'no'} |"
        )
    return "\n".join(out)


def _debug_main() -> int:  # pragma: no cover — developer surface
    import sys

    from .ir import ENTRY_POINTS, IRContext, trace_spec

    config = default_config(None)
    irctx = IRContext(config, dict(ENTRY_POINTS))
    names = sys.argv[1:] or list(ENTRY_POINTS)
    for name in names:
        entry = ENTRY_POINTS[name]
        for spec in entry.make_specs():
            try:
                traced = trace_spec(entry, spec, irctx.entry_line(entry))
            except Exception as exc:  # noqa: BLE001
                print(f"{name}[{spec.variant}]: TRACE FAIL {exc!r}")
                continue
            a = analyze_trace(traced)
            outs = ",".join(s.kind for s in a.out_states)
            evs = "; ".join(
                f"{e.prim}:{e.reason}{'' if e.replicated_ok else ' !repl'}"
                for e in a.events
            )
            print(f"{name}[{spec.variant}]: {a.verdict} outs=[{outs}] "
                  f"plane={sorted(a.plane_deps)} "
                  f"reasons={a.coupler_reasons} "
                  f"{('events: ' + evs) if evs else ''} "
                  f"{('ERROR ' + a.error) if a.error else ''}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(_debug_main())
