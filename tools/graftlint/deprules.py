"""Dep-tier rules: row-independence certification over the dep tier's
abstract dependence analyses (see dep.py for the lattice).

IR006 — every registered kernel carries an explicit ``row_coupled``
declaration on every surface (registry entry, live function attribute,
prewarm manifest dict for manifest kernels), the surfaces agree, and the
analyzer's PROOF never contradicts the declaration: a declared-
independent kernel with a proven cross-row coupler (or a statically
row-shifted output) is a finding, and so is a declared-coupled kernel
the analyzer proves fully independent (the coupling the declaration
documents no longer exists — either the declaration or the kernel
regressed). ``unproven`` verdicts contradict nothing.

IR007 — replicated-scan discipline: in a SHARDED spec variant, every
cross-row coupler must consume operands that were re-replicated (a
``with_sharding_constraint`` to a fully-replicated sharding) since the
row-sharded inputs. A row-sharded value flowing into a sort/cumsum/
global reduction is the PR 9 CPU-SPMD prefix-scan miscompile shape —
promoted here from code-comment convention to checked rule.

Both rules anchor findings at the kernel def (the IR-tier convention)
and honour ``# graftlint: disable=IR006`` pragmas and the shared
baseline.
"""

from __future__ import annotations

from typing import Iterator

from .core import Finding, Rule, rule


class DepRule(Rule):
    kind = "dep"
    id = "DEP000"

    def check(self, analysis, ctx) -> Iterator[Finding]:  # type: ignore[override]
        return iter(())

    def finalize(self, ctx) -> Iterator[Finding]:  # type: ignore[override]
        return iter(())


def _entry_finding(entry, line: int, rule_id: str, message: str,
                   detail: str) -> Finding:
    return Finding(
        rule=rule_id, path=entry.path, line=line, col=1, message=message,
        anchor=entry.attr, detail=detail, anchor_line=line,
    )


def _join_verdicts(analyses) -> str:
    verdicts = [a.verdict for a in analyses]
    if "coupled" in verdicts:
        return "coupled"
    if verdicts and all(v == "independent" for v in verdicts):
        return "independent"
    return "unproven"


# -- IR006 — row-independence certification ---------------------------------


@rule
class RowIndependenceCertification(DepRule):
    id = "IR006"
    title = "row_coupled declarations present, agreeing, and proven"

    def finalize(self, ctx) -> Iterator[Finding]:
        from .dep import declared_row_coupled

        by_entry = ctx.by_entry()
        failed = {e.name for e, _s, _err in ctx.trace_failures}
        for name, entry in ctx.entries.items():
            line = ctx._ir.entry_line(entry)
            decl = declared_row_coupled(entry)
            registry = decl.get("registry")
            kernel = decl.get("kernel")
            prewarm = decl.get("prewarm", registry)

            if registry is None:
                if ctx.full_run:
                    yield _entry_finding(
                        entry, line, self.id,
                        f"{name}: no `row_coupled` declaration on the "
                        "ENTRY_POINTS registry entry — every registered "
                        "kernel must declare whether its outputs couple "
                        "batch rows (the delta-safety contract the "
                        "incremental dirty-row solve asserts at arm "
                        "time); set row_coupled=True|False on the "
                        "KernelEntry",
                        "missing-declaration",
                    )
                continue
            mismatched = [
                (surface, val)
                for surface, val in (("kernel attribute", kernel),
                                     ("prewarm._KERNELS", prewarm))
                if val is not None and bool(val) != bool(registry)
            ]
            for surface, val in mismatched:
                yield _entry_finding(
                    entry, line, self.id,
                    f"{name}: `row_coupled` disagrees across declaration "
                    f"surfaces — registry says {registry} but the "
                    f"{surface} says {val}; the three surfaces "
                    "(ENTRY_POINTS, the jitted function's row_coupled "
                    "attribute, prewarm._KERNELS) must state one truth",
                    f"surface-mismatch:{surface}",
                )
            if kernel is None and "kernel_error" not in decl and \
                    ctx.full_run:
                yield _entry_finding(
                    entry, line, self.id,
                    f"{name}: the jitted kernel carries no `row_coupled` "
                    "attribute — declare it at the def site "
                    f"(`{entry.attr}.row_coupled = {bool(registry)}`) so "
                    "the property is visible where the kernel body is "
                    "edited, not only in the lint registry",
                    "missing-kernel-attribute",
                )

            analyses = by_entry.get(name, ())
            if not analyses or name in failed:
                continue  # unprovable (trace failures are IR004's beat)
            verdict = _join_verdicts(analyses)
            if registry is False and verdict == "coupled":
                reasons = sorted(
                    {r for a in analyses for r in a.coupler_reasons}
                ) or ["row-shifted-output"]
                yield _entry_finding(
                    entry, line, self.id,
                    f"{name}: declared row_coupled=False but the jaxpr "
                    "PROVES cross-row information flow "
                    f"({', '.join(reasons)}) — a delta replay of this "
                    "kernel would silently produce stale rows; either "
                    "remove the coupler or declare row_coupled=True",
                    f"declared-independent-but-coupled:"
                    f"{','.join(reasons)}",
                )
            elif registry is True and verdict == "independent":
                plane = set()
                for a in analyses:
                    plane |= a.plane_deps
                declared_plane = set(
                    getattr(entry, "plane_args", ()) or ()
                )
                if declared_plane and plane & declared_plane:
                    continue  # coupled via the declared plane channel
                yield _entry_finding(
                    entry, line, self.id,
                    f"{name}: declared row_coupled=True but every spec "
                    "variant analyzes fully row-independent"
                    + (" with no dependence on the declared plane-state "
                       f"args {sorted(declared_plane)}"
                       if declared_plane else "")
                    + " — the coupling the declaration documents no "
                    "longer exists; flip the declaration to False (and "
                    "gain delta_safe) or restore the intended coupling",
                    "declared-coupled-but-independent",
                )


# -- IR007 — replicated-scan discipline -------------------------------------

#: the miscompile class: order/prefix-sensitive couplers the CPU SPMD
#: partitioner evaluates per shard (PR 9's global prefix-scan bug).
#: Scatters/contractions/gathers are partitioned with collectives and
#: cross shards legitimately, so they are not IR007's business.
_SCAN_CLASS = ("sort", "top_k", "cum", "reduce_", "argmax", "argmin",
               "scan")


def _is_scan_class(prim: str) -> bool:
    return any(prim.startswith(p) for p in _SCAN_CLASS)


@rule
class ReplicatedScanDiscipline(DepRule):
    id = "IR007"
    title = "row-axis scans/sorts in sharded variants consume replicated operands"

    def check(self, analysis, ctx) -> Iterator[Finding]:
        if not analysis.sharded:
            return
        seen: set = set()
        for ev in analysis.events:
            # only PROVEN row-axis couplers convict: a coupler-class op
            # over a 'mixed' value may be per-row (a sort along the wire
            # axis of a selection) — unproven, no finding
            if ev.replicated_ok or not ev.proven:
                continue
            if not _is_scan_class(ev.prim):
                continue
            key = (ev.prim, ev.reason)
            if key in seen:
                continue
            seen.add(key)
            traced = analysis.traced
            yield traced.finding(
                self.id,
                f"{traced.label}: cross-row coupler `{ev.reason}` "
                "consumes a row-sharded operand that was never "
                "re-replicated — on the CPU SPMD partitioner a global "
                "prefix-scan/sort over a row-sharded value is miscompiled "
                "per shard (the PR 9 bug class); wrap the operands in "
                "lax.with_sharding_constraint(x, NamedSharding(mesh, "
                "P())) before the coupler",
                f"unreplicated-coupler:{ev.prim}:{ev.reason}",
            )
