"""graftlint-IR: jaxpr-level kernel auditor.

The AST tier (core.py/rules.py) guards Python-source invariants; the class
of bugs that actually burns TPU time — silent float64/weak-type promotion,
host transfers hidden inside a kernel, large arrays closed over into a
trace so every snapshot recompiles, prewarm-manifest entries drifting from
what the kernels really trace to — only exists in the lowered IR,
invisible to any AST pass. This tier discovers every exported kernel entry
point (the ops/ dispense/divide/estimate/masks families and the scheduler
fleet kernels), abstractly traces each via ``jax.make_jaxpr`` under
``JAX_PLATFORMS=cpu`` across a representative bucket grid (the same
cap/row buckets the prewarm trace manifest records), and machine-checks
the IR001-IR005 invariants (irrules.py) over the resulting jaxprs.

Run it:

    python -m tools.graftlint --ir                    # full registry
    python -m tools.graftlint --ir divide_replicas    # one family
    python -m tools.graftlint --ir --manifest PATH    # + manifest audit
    karmadactl-tpu lint --ir                          # same, CLI verb

Tracing is ABSTRACT: ``make_jaxpr`` over ``ShapeDtypeStruct``s never
compiles or executes anything, so the whole grid audits in seconds on any
backend. Findings share the AST tier's machinery end to end — inline
``# graftlint: disable=IR00X`` pragmas on the kernel's ``def`` line,
justified entries in ``graftlint_baseline.json``, ``--format json``.

This module imports jax ONLY inside the tracing functions: importing it
(for the registry listing, the docs drift gate, ``--list-rules``) stays
dependency-free like the rest of the package.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Optional

from . import irrules  # noqa: F401 — registers the IR00x analyzers
from .core import (
    IR_RULES,
    Config,
    Finding,
    LintResult,
    ModuleInfo,
    apply_baseline,
    default_config,
)

# --------------------------------------------------------------------------
# entry-point registry
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class KernelSpec:
    """One abstract trace of one entry point: positional input
    shapes/dtypes (manifest ``in_shapes`` form: dtype as string) plus the
    static kwargs. ``group`` optionally regroups the flat struct list
    into the kernel's pytree signature (tuple-valued args)."""

    variant: str
    in_shapes: tuple  # ((shape tuple, dtype str), ...)
    statics: dict = field(default_factory=dict)
    group: Optional[Callable] = None


@dataclass(frozen=True)
class KernelEntry:
    """One exported kernel family: where it lives, how prewarm knows it,
    and how to build its representative spec grid. ``make_specs`` is a
    thunk so the registry itself imports nothing heavy — bucket constants
    (K_PREV, cap rounding) are read LIVE from the engine at trace time,
    never mirrored."""

    name: str
    family: str  # "ops" | "masks" | "scheduler"
    module: str
    attr: str
    path: str  # repo-relative source file (findings anchor here)
    make_specs: Callable[[], list]
    manifest_kernel: Optional[str] = None  # name in the prewarm manifest
    #: delta-safety declaration: do the kernel's outputs couple batch
    #: rows? Mandatory (IR006 fails a missing one) and PROVEN against
    #: the jaxpr by the dep tier — see tools/graftlint/dep.py
    row_coupled: Optional[bool] = None
    #: flat in_shapes positions whose leading axis is the batch-row axis
    row_args: tuple = ()
    #: positions carrying plane-wide state (cross-row by construction —
    #: the first_fit_group avail channel); a declared-coupled kernel may
    #: verify via proven dependence on these instead of a row coupler
    plane_args: tuple = ()
    #: repo-relative modules (beyond ``path``) whose change must
    #: re-trace this entry under ``--changed-only`` — the spec builders'
    #: and kernel bodies' import graph, kept explicit
    spec_deps: tuple = ()


# -- spec builders: the representative bucket grid --------------------------
#
# Dimensions are deliberately SMALL (abstract tracing cost is shape-
# independent, so nothing is gained by production extents) but bucket-
# SHAPED: pow2 caps, the engine's floor quanta, both wide/narrow and
# fast/sorted divide variants, byte and word wires — the statics axes are
# what mint distinct traces in production, so they are what the grid must
# cover.

_B, _C, _R, _U, _G, _P = 8, 16, 3, 4, 2, 3


def _fast_tuples(c: int) -> tuple:
    """(with_idx, no_idx) packed-dispense static tuples valid for ``c``
    clusters — the same (w_bits, l_bits, k_top, div_f32, with_idx) shape
    scheduler.core.kernel_variant emits."""
    i_bits = max(1, (c - 1).bit_length())
    l_bits = 8
    return (
        (31 - l_bits - i_bits, l_bits, 8, True, True),
        (31 - l_bits, l_bits, 8, False, False),
    )


def _specs_divide() -> list:
    fast_idx, fast_noidx = _fast_tuples(_C)
    row = (
        ((_B,), "int32"), ((_B,), "int32"), ((_B, _C), "bool"),
        ((_B, _C), "int32"), ((_B, _C), "int32"), ((_B, _C), "int32"),
        ((_B,), "bool"),
    )
    return [
        KernelSpec("wide-sorted", row,
                   {"has_aggregated": True, "wide": True, "fast": None}),
        KernelSpec("narrow-fast", row,
                   {"has_aggregated": True, "wide": False,
                    "fast": fast_idx}),
        KernelSpec("narrow-fast-noidx", row,
                   {"has_aggregated": False, "wide": False,
                    "fast": fast_noidx}),
    ]


def _specs_take_by_weight() -> list:
    vec = (((), "int32"), ((_C,), "int32"), ((_C,), "int32"),
           ((_C,), "int32"))
    return [
        KernelSpec("wide", vec, {"wide": True}),
        KernelSpec("narrow", vec, {"wide": False}),
    ]


def _specs_take_by_weight_fast() -> list:
    fast_idx, fast_noidx = _fast_tuples(_C)
    vec = (((), "int32"), ((_C,), "int32"), ((_C,), "int32"),
           ((_C,), "int32"))

    def statics(fast, sites):
        w_bits, l_bits, k_top, div_f32, with_idx = fast
        return {"w_bits": w_bits, "l_bits": l_bits, "k_top": k_top,
                "div_f32": div_f32, "with_idx": with_idx,
                "return_sites": sites}

    return [
        KernelSpec("packed-idx", vec, statics(fast_idx, False)),
        KernelSpec("packed-idx-sites", vec, statics(fast_idx, True)),
        KernelSpec("packed-noidx", vec, statics(fast_noidx, False)),
    ]


def _specs_take_by_weight_batch() -> list:
    batch = (((_B,), "int32"), ((_B, _C), "int32"), ((_B, _C), "int32"),
             ((_B, _C), "int32"))
    return [
        KernelSpec("wide", batch, {"wide": True}),
        KernelSpec("narrow", batch, {"wide": False}),
    ]


def _specs_general_estimate() -> list:
    return [KernelSpec(
        "base", (((_C, _R), "int64"), ((_B, _R), "int64")),
    )]


def _specs_general_estimate_interned() -> list:
    return [KernelSpec(
        "base",
        (((_C, _R), "int64"), ((_U, _R), "int64"), ((_B,), "int32")),
    )]


def _specs_gather_profile_rows() -> list:
    return [KernelSpec("base", (((_U, _C), "int32"), ((_B,), "int32")))]


def _group_merge(structs):
    return structs[0], tuple(structs[1:])


def _specs_merge_estimates() -> list:
    return [KernelSpec(
        "two-estimators",
        (((_B,), "int32"), ((_B, _C), "int32"), ((_B, _C), "int32")),
        group=_group_merge,
    )]


def _specs_quota_admit() -> list:
    # B-pow2 wave rows x pow2 namespace rows — the engine's admission
    # padding shape (scheduler.core._quota_admission)
    return [
        KernelSpec(
            "base",
            (((_B,), "int32"), ((_B, _R), "int64"), ((_U, _R), "int64")),
        ),
        KernelSpec(
            "wide-wave",
            (
                ((4 * _B,), "int32"),
                ((4 * _B, _R), "int64"),
                ((2 * _U, _R), "int64"),
            ),
        ),
    ]


def _specs_quota_cluster_caps() -> list:
    return [
        KernelSpec(
            "base",
            (
                ((_U, _C, _R), "int64"),
                ((_B,), "int32"),
                ((_B, _R), "int64"),
            ),
        ),
    ]


def _specs_explain_pass() -> list:
    # the engine's capture padding shape: pow2 binding rows x the
    # snapshot's cluster columns, k clamped to C (ops.explain.topk_width)
    row = (
        ((_B, _C), "bool"), ((_B, _C), "bool"), ((_B, _C), "bool"),
        ((_B, _C), "bool"), ((_B, _C), "int32"), ((_B, _C), "int32"),
        ((_B,), "bool"), ((_B,), "bool"), ((_B,), "int32"),
        ((_B, _C), "int32"), ((_B, _C), "int32"), ((_B, _C), "bool"),
    )
    return [
        KernelSpec("base", row, {"k": 4, "mesh": None, "shard_c": False}),
        KernelSpec("wide-wave", tuple(
            ((4 * _B,) + s[0][1:], s[1]) for s in row
        ), {"k": 8, "mesh": None, "shard_c": False}),
        # sharded grid: the provenance dispatch under a 2-device ("b")
        # mesh — IR001-IR005 run over the PARTITIONED jaxpr, the fleet
        # kernels' contract (ISSUE 9 / test_sharded_specs_cover_*)
        KernelSpec("sharded-b2", row,
                   {"k": 4, "mesh": _MESH2, "shard_c": False}),
    ]


def _specs_preempt_select() -> list:
    # the engine's preemption padding shape: pow2 combined demander+
    # victim rows x cluster columns x resource dims
    # (scheduler.core._preempt_pass)
    row = (
        ((_B,), "int32"), ((_B, _R), "int64"), ((_B, _R), "int64"),
        ((_B,), "bool"), ((_B,), "int32"), ((_B, _C), "int32"),
        ((_B, _R), "int64"),
    )
    return [
        KernelSpec("base", row, {"mesh": None}),
        KernelSpec("wide-wave", tuple(
            ((4 * _B,) + s[0][1:], s[1]) for s in row
        ), {"mesh": None}),
        # sharded grid: the victim selection under a 2-device ("b")
        # mesh — IR001-IR005 run over the PARTITIONED jaxpr (the global
        # sort/cumsum replication guard is audited, not assumed)
        KernelSpec("sharded-b2", row, {"mesh": _MESH2}),
    ]


def _specs_masks_contains_all() -> list:
    return [KernelSpec(
        "base", (((_C, 2), "uint32"), ((2,), "uint32")),
    )]


def _specs_masks_intersects() -> list:
    return [KernelSpec(
        "base", (((_C, 2), "uint32"), ((2,), "uint32")),
    )]


# -- fleet kernels: shapes mirror FleetTable's device layout ----------------


def _fleet_dims() -> dict:
    from karmada_tpu.scheduler.fleet import K_PREV

    c = _C
    return {
        "c": c, "w8": (c + 7) // 8, "cap": 256, "chunk": 256,
        "n_pad": 256, "k_prev": K_PREV,
    }


def _fleet_tables(d: dict) -> list:
    return [
        ((_U, 2 * d["w8"]), "uint8"),  # cp_bits
        ((_U, d["c"]), "int32"),  # cp_static
        ((_G, d["w8"]), "uint8"),  # gvk_bits
        ((_P, d["c"]), "int32"),  # prof_table
        ((d["c"],), "bool"),  # incomplete_en
    ]


def _fleet_state(d: dict) -> list:
    cap = d["cap"]
    return (
        [((cap,), "int32")] * 5  # cp_idx gvk_idx prof_idx replicas strategy
        + [((cap,), "bool")]  # fresh
        + [((cap, d["k_prev"]), "int32")] * 2  # prev_sites prev_counts
    )


def _specs_fleet_solve() -> list:
    from karmada_tpu.scheduler.fleet import _cap_round

    d = _fleet_dims()
    fast_idx, _ = _fast_tuples(d["c"])
    k_out = k_res = 8
    e_cap = _cap_round(1)

    def spec(variant, **statics):
        base = dict(
            chunk=d["chunk"], n_chunks=1, k_out=k_out, k_res=k_res,
            e_cap=e_cap, wide=True, fast=None, has_aggregated=True,
            all_rows=True, mesh=None, shard_c=False, pack21=True,
        )
        base.update(statics)
        shapes = tuple(
            _fleet_tables(d) + [((d["n_pad"],), "int32")] + _fleet_state(d)
            + [((d["cap"], base["k_res"]), "int32")]
        )
        return KernelSpec(variant, shapes, base)

    return [
        spec("wide-allrows"),
        spec("narrow-fast-partial", wide=False, fast=fast_idx,
             all_rows=False, pack21=False),
        spec("next-e-bucket", e_cap=_cap_round(e_cap + 1)),
        # sharded grid: the same program under a 2-device ("b") mesh —
        # trace_spec materializes the shape into a live Mesh, so IR001-
        # IR005 (incl. the donation audit over the row-sharded resident)
        # run over the PARTITIONED executable's jaxpr, not just the
        # single-device form
        spec("sharded-b2", mesh=_MESH2),
    ]


#: canonical 2-device mesh shape for the sharded spec variants (the
#: serialized form the trace manifest also records; trace_spec builds the
#: live mesh over the forced host devices at trace time)
_MESH2 = (("b", 2), ("c", 1))


def _specs_fleet_pass() -> list:
    from karmada_tpu.scheduler.fleet import D_FLOOR

    d = _fleet_dims()
    fast_idx, _ = _fast_tuples(d["c"])

    def spec(variant, **statics):
        base = dict(
            chunk=d["chunk"], n_chunks=1, wide=True, fast=None,
            has_aggregated=True, all_rows=True, m_cap=d["n_pad"],
            d_cap=0, mesh=None, shard_c=False,
        )
        base.update(statics)
        shapes = tuple(
            _fleet_tables(d) + [((d["n_pad"],), "int32")] + _fleet_state(d)
            + [((d["cap"], d["c"]), "uint8"), ((d["cap"],), "int32")]
        )
        return KernelSpec(variant, shapes, base)

    return [
        spec("wide-allrows"),
        spec("narrow-fast-delta", wide=False, fast=fast_idx,
             d_cap=D_FLOOR, all_rows=False),
        # sharded grid under a 2-device mesh (see _specs_fleet_solve):
        # proves the donated dense residents still alias when partitioned
        spec("sharded-b2", mesh=_MESH2),
    ]


def _specs_fleet_entries() -> list:
    from karmada_tpu.scheduler.fleet import _cap_round

    d = _fleet_dims()
    shapes = (
        ((d["cap"], d["c"]), "uint8"), ((2048,), "int32"),
    )
    base = dict(chunk=256, n_chunks=8, k_out=8, e_cap=_cap_round(1))
    return [
        KernelSpec("byte-pack21", shapes,
                   {**base, "byte_wire": True, "pack21": True}),
        KernelSpec("word-wire", shapes,
                   {**base, "byte_wire": False, "pack21": False}),
        # sharded grid: phase B over a row-sharded dense resident (the
        # mesh engines' form — gathers cross shards, scans replicate)
        KernelSpec("sharded-b2", shapes,
                   {**base, "byte_wire": True, "pack21": True,
                    "mesh": _MESH2}),
    ]


def _specs_fleet_bits() -> list:
    d = _fleet_dims()
    shapes = tuple(
        _fleet_tables(d) + [((d["n_pad"],), "int32")] + _fleet_state(d)
    )
    return [KernelSpec("base", shapes, {"chunk": d["chunk"], "n_chunks": 1})]


def _specs_gather_meta() -> list:
    d = _fleet_dims()
    return [KernelSpec(
        "base", (((d["cap"],), "int32"), ((d["n_pad"],), "int32")),
    )]


def _group_scatter(structs):
    return tuple(structs[0:8]), structs[8], tuple(structs[9:17])


def _specs_scatter_rows() -> list:
    d = _fleet_dims()
    state = _fleet_state(d)
    rows = 16
    vals = [((rows,) + tuple(s[0][1:]), s[1]) for s in state]
    return [KernelSpec(
        "base",
        tuple(state + [((rows,), "int64")] + vals),
        group=_group_scatter,
    )]


def _specs_first_fit_group() -> list:
    t = 3
    return [KernelSpec(
        "base",
        (
            ((_B, t, _C), "bool"), ((_B,), "int32"), ((_B, _C), "int64"),
            ((_B,), "int64"), ((_B, _C), "int64"), ((_B,), "bool"),
            ((_B,), "bool"),
        ),
    )]


#: fleet.py's full ops-module import surface (divide pulls dispense;
#: fleet composes every family) — the --changed-only re-trace closure
_FLEET_DEPS = (
    "karmada_tpu/ops/divide.py", "karmada_tpu/ops/dispense.py",
    "karmada_tpu/ops/estimate.py", "karmada_tpu/ops/explain.py",
    "karmada_tpu/ops/preempt.py", "karmada_tpu/ops/quota.py",
)


def _entry(name, family, module, attr, path, make_specs, manifest=None,
           row_coupled=None, row_args=(), plane_args=(), spec_deps=()):
    return KernelEntry(
        name=name, family=family, module=module, attr=attr, path=path,
        make_specs=make_specs, manifest_kernel=manifest,
        row_coupled=row_coupled, row_args=tuple(row_args),
        plane_args=tuple(plane_args), spec_deps=tuple(spec_deps),
    )


#: THE registry: every exported kernel entry point, AST-light (spec
#: builders import the engine lazily). The docs drift gate
#: (tools/docs_from_bench.py check_ir_registry) fails loudly when an
#: ops/ export is missing here; IR004 fails when a fleet kernel is
#: missing from any of FLEET_KERNELS / prewarm._KERNELS / this table.
ENTRY_POINTS: dict = {
    e.name: e
    for e in (
        # ops/ — the dispense/divide/estimate/masks families. Every
        # entry declares ``row_coupled`` (the delta-safety contract,
        # IR006-checked) and which flat input positions carry the batch
        # row axis; the unbatched dispense kernels have no row axis at
        # all, so their independence is trivial (row_args=()).
        _entry("divide_replicas", "ops", "karmada_tpu.ops.divide",
               "divide_replicas", "karmada_tpu/ops/divide.py",
               _specs_divide, row_coupled=False,
               row_args=(0, 1, 2, 3, 4, 5, 6),
               spec_deps=("karmada_tpu/ops/dispense.py",)),
        _entry("take_by_weight", "ops", "karmada_tpu.ops.dispense",
               "take_by_weight", "karmada_tpu/ops/dispense.py",
               _specs_take_by_weight, row_coupled=False),
        _entry("take_by_weight_fast", "ops", "karmada_tpu.ops.dispense",
               "take_by_weight_fast", "karmada_tpu/ops/dispense.py",
               _specs_take_by_weight_fast, row_coupled=False),
        _entry("take_by_weight_batch", "ops", "karmada_tpu.ops.dispense",
               "take_by_weight_batch", "karmada_tpu/ops/dispense.py",
               _specs_take_by_weight_batch, row_coupled=False,
               row_args=(0, 1, 2, 3)),
        _entry("general_estimate", "ops", "karmada_tpu.ops.estimate",
               "general_estimate", "karmada_tpu/ops/estimate.py",
               _specs_general_estimate, row_coupled=False,
               row_args=(1,)),
        _entry("general_estimate_interned", "ops",
               "karmada_tpu.ops.estimate", "general_estimate_interned",
               "karmada_tpu/ops/estimate.py",
               _specs_general_estimate_interned, row_coupled=False,
               row_args=(2,)),
        _entry("gather_profile_rows", "ops", "karmada_tpu.ops.estimate",
               "gather_profile_rows", "karmada_tpu/ops/estimate.py",
               _specs_gather_profile_rows, row_coupled=False,
               row_args=(1,)),
        _entry("merge_estimates", "ops", "karmada_tpu.ops.estimate",
               "merge_estimates", "karmada_tpu/ops/estimate.py",
               _specs_merge_estimates, row_coupled=False,
               row_args=(0, 1, 2)),
        # quota family: dispatched engine-side (TensorScheduler) but
        # manifest-recorded like the fleet solve family, so prewarm can
        # replay admission traces at boot (IR004 keeps the three
        # registries — FLEET_KERNELS / prewarm._KERNELS / here — equal)
        _entry("quota_admit", "ops", "karmada_tpu.ops.quota",
               "quota_admit", "karmada_tpu/ops/quota.py",
               _specs_quota_admit, manifest="quota_admit",
               row_coupled=True, row_args=(0, 1), plane_args=(2,)),
        _entry("quota_cluster_caps", "ops", "karmada_tpu.ops.quota",
               "quota_cluster_caps", "karmada_tpu/ops/quota.py",
               _specs_quota_cluster_caps, manifest="quota_cluster_caps",
               row_coupled=False, row_args=(1, 2)),
        # provenance family: the armed-only per-pass explain dispatch
        # (engine-side like the quota kernels, manifest-recorded, with a
        # sharded-b2 variant so the partitioned form is audited too)
        _entry("explain_pass", "ops", "karmada_tpu.ops.explain",
               "explain_pass", "karmada_tpu/ops/explain.py",
               _specs_explain_pass, manifest="explain_pass",
               row_coupled=False,
               row_args=(0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11)),
        # scarcity family: the armed-only plane-wide victim selection
        # (engine-side like quota/explain, manifest-recorded, with a
        # sharded-b2 variant auditing the partitioned jaxpr)
        _entry("preempt_select", "ops", "karmada_tpu.ops.preempt",
               "preempt_select", "karmada_tpu/ops/preempt.py",
               _specs_preempt_select, manifest="preempt_select",
               row_coupled=True, row_args=(0, 1, 2, 3, 4, 5, 6),
               spec_deps=("karmada_tpu/ops/quota.py",)),
        _entry("masks.contains_all", "masks", "karmada_tpu.ops.masks",
               "contains_all", "karmada_tpu/ops/masks.py",
               _specs_masks_contains_all, row_coupled=False,
               row_args=(0,)),
        _entry("masks.intersects", "masks", "karmada_tpu.ops.masks",
               "intersects", "karmada_tpu/ops/masks.py",
               _specs_masks_intersects, row_coupled=False,
               row_args=(0,)),
        # cohort selection: row-wise over B but coupled THROUGH the
        # plane-merged availability input (plane_args) — a declared-
        # coupled kernel IR006 verifies via the plane channel
        _entry("masks.first_fit_group", "masks", "karmada_tpu.ops.masks",
               "first_fit_group", "karmada_tpu/ops/masks.py",
               _specs_first_fit_group, row_coupled=True,
               row_args=(0, 1, 3, 4, 5, 6), plane_args=(2,)),
        # scheduler fleet kernels (manifest-recorded solve family + the
        # ledger-only utility kernels). The row space is the resident
        # cap axis; the solve/pass/entries kernels compact globally
        # (declared coupled), bits/meta are per-row but scan-windowed,
        # so the analyzer returns 'unproven' — declared honestly, not
        # delta_safe (see DEVELOPMENT.md, delta-safe kernel contract).
        _entry("fleet_solve", "scheduler", "karmada_tpu.scheduler.fleet",
               "_fleet_solve", "karmada_tpu/scheduler/fleet.py",
               _specs_fleet_solve, manifest="fleet_solve",
               row_coupled=True,
               row_args=(6, 7, 8, 9, 10, 11, 12, 13, 14),
               spec_deps=_FLEET_DEPS),
        _entry("fleet_pass", "scheduler", "karmada_tpu.scheduler.fleet",
               "_fleet_pass", "karmada_tpu/scheduler/fleet.py",
               _specs_fleet_pass, manifest="fleet_pass",
               row_coupled=True,
               row_args=(6, 7, 8, 9, 10, 11, 12, 13, 14, 15),
               spec_deps=_FLEET_DEPS),
        _entry("fleet_entries", "scheduler", "karmada_tpu.scheduler.fleet",
               "_fleet_entries", "karmada_tpu/scheduler/fleet.py",
               _specs_fleet_entries, manifest="fleet_entries",
               row_coupled=True, row_args=(0,), spec_deps=_FLEET_DEPS),
        _entry("fleet_bits", "scheduler", "karmada_tpu.scheduler.fleet",
               "_fleet_bits", "karmada_tpu/scheduler/fleet.py",
               _specs_fleet_bits, manifest="fleet_bits",
               row_coupled=False,
               row_args=(6, 7, 8, 9, 10, 11, 12, 13),
               spec_deps=_FLEET_DEPS),
        _entry("gather_meta", "scheduler", "karmada_tpu.scheduler.fleet",
               "_gather_meta", "karmada_tpu/scheduler/fleet.py",
               _specs_gather_meta, row_coupled=False, row_args=(0,),
               spec_deps=_FLEET_DEPS),
        _entry("scatter_rows", "scheduler", "karmada_tpu.scheduler.fleet",
               "_scatter_rows", "karmada_tpu/scheduler/fleet.py",
               _specs_scatter_rows, row_coupled=True,
               row_args=tuple(range(17)), spec_deps=_FLEET_DEPS),
    )
}


def entries_for_changed(paths, registry: Optional[dict] = None) -> dict:
    """The ``--changed-only`` scope for the IR/dep tiers: entries whose
    source file or declared ``spec_deps`` intersect the changed set.
    Like GL003's precedent, full-scope-only negatives (registry
    coverage, manifest presence) stay off scoped runs — run_ir/run_dep
    see ``entries is not None`` and drop them."""
    changed = {str(p).replace("\\", "/") for p in paths}
    registry = ENTRY_POINTS if registry is None else registry
    return {
        name: e
        for name, e in registry.items()
        if e.path in changed or set(e.spec_deps) & changed
    }


def exported_ops_kernels(root: Path) -> set:
    """Kernel function names ``karmada_tpu/ops/__init__.py`` re-exports
    (pure AST: lowercase ``from .submodule import name`` bindings —
    constants are UPPER and result types CamelCase by repo convention).
    The docs drift gate compares this against the registry."""
    tree = ast.parse(
        (Path(root) / "karmada_tpu" / "ops" / "__init__.py").read_text()
    )
    out: set = set()
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.ImportFrom)
            and node.level == 1
            and node.module
        ):
            continue
        for a in node.names:
            name = a.asname or a.name
            if name.islower() and not name.startswith("_"):
                out.add(name)
    return out


def ops_registry_drift(root: Optional[Path] = None) -> tuple:
    """(exported-but-unregistered, registered-but-unexported) kernel
    names — both must be empty; tools/docs_from_bench.py fails loudly on
    either (the same drift-guard pattern as the env-flag table)."""
    config = default_config(root)
    exported = exported_ops_kernels(config.root)
    registered = {
        e.name for e in ENTRY_POINTS.values() if e.family == "ops"
    }
    return sorted(exported - registered), sorted(registered - exported)


# --------------------------------------------------------------------------
# tracing
# --------------------------------------------------------------------------


def _import_jax():
    # the auditor must never grab a TPU: default to CPU before the first
    # jax import (a caller that already imported jax keeps its platform).
    # The sharded entry-point specs trace under a >=2-device mesh, so the
    # forced-host-device flag is ensured BEFORE the first backend init —
    # a caller that already initialized a 1-device backend surfaces the
    # mesh-build failure as an IR004 trace failure (loud, not skipped).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # inline (NOT parallel.mesh.ensure_host_devices): importing any
    # karmada_tpu module pulls jax, and XLA_FLAGS is captured at jax
    # IMPORT — the flag must be in the env before that first import
    import re as _re

    flags = os.environ.get("XLA_FLAGS", "")
    m = _re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if not m or int(m.group(1)) < 2:
        opt = "--xla_force_host_platform_device_count=2"
        flags = flags.replace(m.group(0), opt) if m else f"{flags} {opt}"
        os.environ["XLA_FLAGS"] = flags.strip()
    import jax

    return jax


@dataclass
class TracedKernel:
    """One abstract trace: the jaxpr plus the finding anchor."""

    entry: KernelEntry
    spec: KernelSpec
    closed_jaxpr: object
    line: int = 1

    @property
    def label(self) -> str:
        return f"{self.entry.name}[{self.spec.variant}]"

    def finding(self, rule_id: str, message: str, detail: str) -> Finding:
        return Finding(
            rule=rule_id, path=self.entry.path, line=self.line, col=1,
            message=message, anchor=self.entry.attr, detail=detail,
            anchor_line=self.line,
        )


def resolve_kernel(entry: KernelEntry):
    import importlib

    return getattr(importlib.import_module(entry.module), entry.attr)


def trace_spec(entry: KernelEntry, spec: KernelSpec, line: int = 1):
    """Abstractly trace one spec: no compile, no execution, no data."""
    jax = _import_jax()
    import numpy as np

    fn = resolve_kernel(entry)
    structs = [
        jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))
        for shape, dtype in spec.in_shapes
    ]
    args = spec.group(structs) if spec.group else tuple(structs)
    statics = dict(spec.statics)
    # a sharded spec (registry variant or meshed manifest record) carries
    # its mesh as the canonical SHAPE — build the live Mesh over this
    # process's devices the same way prewarm replay does, so the audited
    # jaxpr is the partitioned program the serving path dispatches
    from karmada_tpu.parallel.mesh import materialize_mesh_statics

    statics = materialize_mesh_statics(statics)
    closed = jax.make_jaxpr(lambda *a: fn(*a, **statics))(*args)
    return TracedKernel(
        entry=entry, spec=spec, closed_jaxpr=closed, line=line,
    )


# --------------------------------------------------------------------------
# manifest fidelity (IR004 inputs)
# --------------------------------------------------------------------------


@dataclass
class ManifestResult:
    index: int
    kernel: str
    error: Optional[str] = None
    reason: str = "ok"
    traced: Optional[TracedKernel] = None


def spec_from_record(record: dict, variant: str) -> KernelSpec:
    """A manifest record IS a kernel spec: same in_shapes form, statics
    through prewarm's own JSON inverse (so tuple restoration cannot
    diverge from what replay() would execute)."""
    from karmada_tpu.scheduler.prewarm import _statics_from_json

    return KernelSpec(
        variant=variant,
        in_shapes=tuple(
            (tuple(int(d) for d in shape), dtype)
            for shape, dtype in record["in_shapes"]
        ),
        statics=_statics_from_json(record["statics"]),
    )


def record_canon(record: dict, spec: KernelSpec) -> tuple:
    """(original canon, canon of the spec re-serialized through prewarm's
    own writers) — byte-identical means the save/load/replay cycle is
    lossless for this record."""
    import numpy as np

    from karmada_tpu.scheduler.prewarm import _canon, _listify

    rebuilt = {
        "kernel": record["kernel"],
        "in_shapes": [
            [list(shape), str(np.dtype(dtype))]
            for shape, dtype in spec.in_shapes
        ],
        "statics": {k: _listify(v) for k, v in spec.statics.items()},
    }
    return _canon(record), _canon(rebuilt)


def check_manifest(path: str, ctx: "IRContext") -> None:
    """Audit one trace manifest: every record must resolve to a known
    kernel family, re-trace under its recorded shapes/statics, and
    round-trip to a byte-identical content signature. Successfully traced
    records join the IR001/2/3/5 audit set.

    The file is parsed RAW, not through ``prewarm.TraceManifest`` — the
    loader silently drops unreadable files and records whose kernel is
    missing from ``_KERNELS``, which is exactly the drift this audit
    exists to catch (a renamed fleet kernel would make every old record
    vanish and the audit report clean). An explicitly-audited manifest
    that is unreadable or empty is itself a finding: the operator asked
    to prove coverage, and there is none."""
    import json

    by_manifest = {
        e.manifest_kernel: e
        for e in ctx.entries.values()
        if e.manifest_kernel
    }
    try:
        rel = Path(path).resolve().relative_to(
            ctx.config.root.resolve()
        ).as_posix()
    except ValueError:
        rel = Path(path).as_posix()
    ctx.manifest_rel = rel
    try:
        data = json.loads(Path(path).read_text())
        records = data.get("records", [])
        if not isinstance(records, list):
            raise ValueError("'records' is not a list")
    except (OSError, ValueError) as exc:
        ctx.manifest_results.append(ManifestResult(
            index=-1, kernel="<manifest>",
            error=f"manifest unreadable ({exc})", reason="unreadable",
        ))
        return
    if not records:
        ctx.manifest_results.append(ManifestResult(
            index=-1, kernel="<manifest>",
            error=("manifest holds zero records — prewarm would cover "
                   "nothing; re-record it (run a warm pass with recording "
                   "on) or drop --manifest"),
            reason="empty",
        ))
        return
    for i, record in enumerate(records):
        kernel = (
            record.get("kernel", "?") if isinstance(record, dict) else "?"
        )
        res = ManifestResult(index=i, kernel=str(kernel))
        ctx.manifest_results.append(res)
        if not isinstance(record, dict) or not all(
            k in record for k in ("kernel", "in_shapes", "statics")
        ):
            res.error = (
                "malformed record (kernel/in_shapes/statics required)"
            )
            res.reason = "malformed"
            continue
        entry = by_manifest.get(kernel)
        if entry is None:
            res.error = (
                "unknown kernel family (not in the IR entry-point registry)"
            )
            res.reason = "unknown-kernel"
            continue
        try:
            spec = spec_from_record(record, f"manifest[{i}]")
            res.traced = trace_spec(entry, spec, ctx.entry_line(entry))
        except Exception as exc:  # noqa: BLE001 — each record is audited
            # independently; one stale record must not mask the rest
            res.error = f"re-trace failed ({exc!r})"
            res.reason = "trace-failed"
            continue
        original, rebuilt = record_canon(record, spec)
        if original != rebuilt:
            res.error = (
                "recorded signature does not round-trip byte-identically "
                f"({original} != {rebuilt})"
            )
            res.reason = "canon-drift"
            continue
        ctx.traced.append(res.traced)


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------


class IRContext:
    """Cross-rule state of one IR run (the IR analogue of LintContext)."""

    def __init__(self, config: Config, entries: dict):
        self.config = config
        self.entries = entries
        self.traced: list = []
        self.trace_failures: list = []  # (entry, spec, err-str)
        self.registry_coverage: Optional[dict] = None
        self.manifest_rel: str = ""
        self.manifest_results: list = []
        self.const_bytes_threshold = irrules.CONST_BYTES_THRESHOLD
        self._def_lines: dict = {}  # path -> {funcname: lineno}
        self._modinfos: dict = {}  # path -> Optional[ModuleInfo]

    def entry_line(self, entry: KernelEntry) -> int:
        lines = self._def_lines.get(entry.path)
        if lines is None:
            lines = {}
            source = self.config.root / entry.path
            if source.exists():
                for node in ast.walk(ast.parse(source.read_text())):
                    if isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        lines.setdefault(node.name, node.lineno)
            self._def_lines[entry.path] = lines
        return lines.get(entry.attr, 1)

    def modinfo(self, rel: str) -> Optional[ModuleInfo]:
        """Parsed module for suppression lookup (None for paths outside
        the tree, e.g. a manifest file)."""
        if rel not in self._modinfos:
            source = self.config.root / rel
            info = None
            if source.exists() and source.suffix == ".py":
                info = ModuleInfo.parse(source, rel, set())
            self._modinfos[rel] = info
        return self._modinfos[rel]


def _registry_coverage(entries: dict) -> dict:
    """The three surfaces a fleet kernel must be registered on (IR004)."""
    from karmada_tpu.scheduler import fleet, prewarm

    return {
        "fleet": set(fleet.FLEET_KERNELS),
        "prewarm": set(prewarm._KERNELS),
        "ir": {
            e.manifest_kernel
            for e in entries.values()
            if e.manifest_kernel
        },
    }


def run_ir(
    families=None,
    *,
    root=None,
    baseline="auto",
    manifest: Optional[str] = None,
    entries: Optional[dict] = None,
    const_bytes_threshold: Optional[int] = None,
) -> LintResult:
    """One-call API behind ``--ir`` and the tier-1 gate. ``families``
    filters the registry by entry name (None = everything); ``entries``
    substitutes the registry wholesale (the seeded-mutant fixtures);
    ``manifest`` additionally audits a trace-manifest file (IR004)."""
    config = default_config(root)
    registry = dict(entries) if entries is not None else dict(ENTRY_POINTS)
    full_run = entries is None and not families
    if families:
        unknown = sorted(set(families) - set(registry))
        if unknown:
            raise KeyError(
                f"unknown kernel families {unknown}; known: "
                f"{sorted(registry)}"
            )
        registry = {name: registry[name] for name in families}

    ctx = IRContext(config, registry)
    if const_bytes_threshold is not None:
        ctx.const_bytes_threshold = const_bytes_threshold
    for entry in registry.values():
        line = ctx.entry_line(entry)
        for spec in entry.make_specs():
            try:
                ctx.traced.append(trace_spec(entry, spec, line))
            except Exception as exc:  # noqa: BLE001 — a spec that fails
                # to trace is ITSELF the IR004 finding, never an abort
                ctx.trace_failures.append((entry, spec, repr(exc)))
    if full_run:
        ctx.registry_coverage = _registry_coverage(registry)
    if manifest:
        check_manifest(manifest, ctx)

    raw: list = []
    suppressed = 0
    seen: set = set()
    for r in IR_RULES.values():
        found: list = []
        for t in ctx.traced:
            found.extend(r.check(t, ctx))
        found.extend(r.finalize(ctx))
        for f in found:
            key = (f.identity, f.line)
            if key in seen:  # variants of one entry repeat one defect
                continue
            seen.add(key)
            mod = ctx.modinfo(f.path)
            if mod is not None and mod.suppressed(
                f.rule, f.line, f.anchor_line
            ):
                suppressed += 1
            else:
                raw.append(f)

    baseline_path = None
    if baseline == "auto":
        baseline_path = config.root / config.baseline_path
    elif baseline:
        baseline_path = config.root / baseline
    checked = len(ctx.traced) + len(ctx.trace_failures)
    return apply_baseline(
        raw, baseline=baseline_path, checked_files=checked,
        suppressed=suppressed,
    )
