"""CLI: ``python -m tools.graftlint [paths ...]`` (see package docstring).

Two tiers behind one surface: the default AST tier (GL00x, pure-ast,
sub-second — pre-commit material with ``--changed-only``) and the IR tier
(``--ir``: IR00x, abstractly traces every registered kernel entry point
under JAX_PLATFORMS=cpu and audits the jaxprs — run it before a rollout
and in tier-1, see tests/test_graftlint_ir.py).

Exit codes: 0 clean (baselined findings allowed), 1 findings or a
baseline entry without justification, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys

from . import DEFAULT_TARGETS, RULES, default_config, run
from .core import IR_RULES, write_baseline


def changed_py_files(root) -> list:
    """Repo-relative .py files with uncommitted changes (staged, unstaged
    and untracked) — the pre-commit scope for ``--changed-only``."""
    def _git(*args):
        proc = subprocess.run(
            ["git", *args], cwd=root, capture_output=True, text=True,
        )
        if proc.returncode != 0:
            raise RuntimeError(proc.stderr.strip() or "git failed")
        return [line for line in proc.stdout.splitlines() if line.strip()]

    names = set(_git("diff", "--name-only", "HEAD", "--"))
    names |= set(_git("ls-files", "--others", "--exclude-standard"))
    return sorted(
        n for n in names
        if n.endswith(".py") and (root / n).exists()
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="trace-safety & concurrency analyzer (AST tier) and "
        "jaxpr-level kernel auditor (--ir)",
    )
    p.add_argument("paths", nargs="*", default=[],
                   help="files/directories to lint (default: karmada_tpu "
                   "tools); with --ir, kernel family names to audit "
                   "(default: the full entry-point registry)")
    p.add_argument("--paths", dest="extra_paths", action="append",
                   default=[], metavar="PATH",
                   help="additional lint targets (repeatable; same as the "
                   "positionals — scripting convenience)")
    p.add_argument("--changed-only", action="store_true",
                   help="AST tier: lint only .py files with uncommitted "
                   "git changes (staged+unstaged+untracked) — the "
                   "pre-commit mode, runs in well under a second")
    p.add_argument("--ir", action="store_true",
                   help="run the IR tier instead: abstractly trace every "
                   "registered kernel entry point (jax.make_jaxpr on CPU, "
                   "no compiles) and audit the jaxprs (IR001-IR005)")
    p.add_argument("--manifest", default=None, metavar="PATH",
                   help="IR tier: additionally audit a prewarm trace "
                   "manifest — every record must re-trace to its recorded "
                   "signature (IR004)")
    p.add_argument("--root", default=None,
                   help="repo root (default: this checkout)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined findings too")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to graftlint_baseline.json "
                   "with EMPTY justifications (the linter refuses them "
                   "until each is justified); always runs BOTH tiers — "
                   "the baseline file is shared")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rid, r in sorted({**RULES, **IR_RULES}.items()):
            print(f"{rid}  {r.title}")
        return 0

    paths = list(args.paths) + list(args.extra_paths)
    config = default_config(args.root)

    if args.manifest is not None and not args.manifest:
        # an empty path is almost always `--manifest "$UNSET_VAR"`: the
        # operator asked for a manifest audit and would get a silent skip
        print("error: --manifest requires a non-empty path (is "
              "KARMADA_TPU_TRACE_MANIFEST set?)", file=sys.stderr)
        return 2

    if args.changed_only:
        if args.ir:
            print("error: --changed-only is an AST-tier mode (the IR tier "
                  "audits traced kernels, not files)", file=sys.stderr)
            return 2
        if args.write_baseline:
            print("error: --write-baseline needs the FULL lint scope — a "
                  "baseline regenerated from only the changed files would "
                  "delete every justified entry outside them",
                  file=sys.stderr)
            return 2
        try:
            paths = changed_py_files(config.root)
        except RuntimeError as exc:
            print(f"error: --changed-only needs a git checkout: {exc}",
                  file=sys.stderr)
            return 2
        if not paths:
            print("0 changed python files: nothing to lint")
            return 0

    if args.write_baseline:
        # baseline=None: the new baseline must hold EVERY current finding
        # (a baselined run would drop — and thereby delete — entries that
        # still match); write_baseline carries existing justifications
        # over. BOTH tiers always run here — the baseline file is shared,
        # so an AST-only regeneration would delete the IR tier's entries.
        raw = run(paths or DEFAULT_TARGETS, root=args.root, baseline=None)
        findings = list(raw.findings)
        from .ir import run_ir

        findings += run_ir(
            root=args.root, baseline=None, manifest=args.manifest
        ).findings
        path = config.root / config.baseline_path
        n = write_baseline(path, findings)
        print(f"wrote {n} entries to {path} — add a justification to each "
              "new entry (empty justifications are rejected)")
        return 0

    if args.ir:
        from .ir import run_ir

        try:
            result = run_ir(
                paths or None,
                root=args.root,
                baseline=None if args.no_baseline else "auto",
                manifest=args.manifest,
            )
        except KeyError as exc:
            print(f"error: {exc.args[0]}", file=sys.stderr)
            return 2
    else:
        result = run(
            paths or DEFAULT_TARGETS,
            root=args.root,
            baseline=None if args.no_baseline else "auto",
            # an explicit path list (or the git-changed set) is a partial
            # scan: whole-tree negative checks must not fire from it
            full_scope=not paths,
        )
    if args.format == "json":
        print(json.dumps(result.to_json(), indent=2))
    else:
        print(result.render_text())
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
