"""CLI: ``python -m tools.graftlint [paths ...]`` (see package docstring).

Exit codes: 0 clean (baselined findings allowed), 1 findings or a
baseline entry without justification, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import DEFAULT_TARGETS, RULES, default_config, run
from .core import write_baseline


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based trace-safety & concurrency analyzer",
    )
    p.add_argument("paths", nargs="*", default=list(DEFAULT_TARGETS),
                   help="files/directories to lint (default: karmada_tpu "
                   "tools)")
    p.add_argument("--root", default=None,
                   help="repo root (default: this checkout)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined findings too")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to graftlint_baseline.json "
                   "with EMPTY justifications (the linter refuses them "
                   "until each is justified)")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rid, r in sorted(RULES.items()):
            print(f"{rid}  {r.title}")
        return 0

    if args.write_baseline:
        # baseline=None: the new baseline must hold EVERY current finding
        # (a baselined run would drop — and thereby delete — entries that
        # still match); write_baseline carries existing justifications over
        raw = run(args.paths or DEFAULT_TARGETS, root=args.root,
                  baseline=None)
        config = default_config(args.root)
        path = config.root / config.baseline_path
        n = write_baseline(path, raw.findings)
        print(f"wrote {n} entries to {path} — add a justification to each "
              "new entry (empty justifications are rejected)")
        return 0

    result = run(
        args.paths or DEFAULT_TARGETS,
        root=args.root,
        baseline=None if args.no_baseline else "auto",
    )
    if args.format == "json":
        print(json.dumps(result.to_json(), indent=2))
    else:
        print(result.render_text())
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
