"""CLI: ``python -m tools.graftlint [paths ...]`` (see package docstring).

Three tiers behind one surface: the default AST tier (GL00x, pure-ast,
sub-second — pre-commit material with ``--changed-only``), the IR tier
(``--ir``: IR00x, abstractly traces every registered kernel entry point
under JAX_PLATFORMS=cpu and audits the jaxprs) and the dep tier
(``--dep``: IR006/IR007, row-dependence certification over the same
jaxprs — the delta-safety contract). ``--all`` runs every tier in one
invocation with a merged exit code and per-tier timing — the CI/rollout
gate shape (see docs/DEVELOPMENT.md).

``--changed-only`` scopes every tier: the AST tier lints only the
changed files; the IR/dep tiers audit only the registry entries whose
kernel source or declared ``spec_deps`` intersect the changed set
(full-scope-only negatives like registry coverage stay off scoped runs).

Exit codes: 0 clean (baselined findings allowed), 1 findings or a
baseline entry without justification, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time

from . import DEFAULT_TARGETS, RULES, default_config, run
from .core import DEP_RULES, IR_RULES, write_baseline


def changed_py_files(root) -> list:
    """Repo-relative .py files with uncommitted changes (staged, unstaged
    and untracked) — the pre-commit scope for ``--changed-only``."""
    def _git(*args):
        proc = subprocess.run(
            ["git", *args], cwd=root, capture_output=True, text=True,
        )
        if proc.returncode != 0:
            raise RuntimeError(proc.stderr.strip() or "git failed")
        return [line for line in proc.stdout.splitlines() if line.strip()]

    names = set(_git("diff", "--name-only", "HEAD", "--"))
    names |= set(_git("ls-files", "--others", "--exclude-standard"))
    return sorted(
        n for n in names
        if n.endswith(".py") and (root / n).exists()
        # the fixture corpus is deliberately-bad code: linted only by
        # the fixture tests (with forced roles), never by the scoped gate
        and "graftlint_fixtures" not in n.split("/")
    )


def _run_tier(tier: str, args, paths, changed, config):
    """One tier's LintResult. ``changed`` is None (full scope) or the
    changed-file list driving every tier's scoping."""
    baseline = None if args.no_baseline else "auto"
    if tier == "ast":
        targets = changed if changed is not None else (
            paths or DEFAULT_TARGETS
        )
        return run(
            targets, root=args.root, baseline=baseline,
            # an explicit path list (or the git-changed set) is a partial
            # scan: whole-tree negative checks must not fire from it
            full_scope=not paths and changed is None,
        )
    from .ir import entries_for_changed

    entries = None
    families = paths or None
    if changed is not None:
        entries = entries_for_changed(changed)
        families = None
    if tier == "ir":
        from .ir import run_ir

        return run_ir(
            families, root=args.root, baseline=baseline,
            manifest=args.manifest, entries=entries,
        )
    from .dep import run_dep

    return run_dep(
        families, root=args.root, baseline=baseline, entries=entries,
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="trace-safety & concurrency analyzer (AST tier), "
        "jaxpr-level kernel auditor (--ir) and row-dependence certifier "
        "(--dep); --all runs every tier",
    )
    p.add_argument("paths", nargs="*", default=[],
                   help="files/directories to lint (default: karmada_tpu "
                   "tools); with --ir/--dep, kernel family names to audit "
                   "(default: the full entry-point registry)")
    p.add_argument("--paths", dest="extra_paths", action="append",
                   default=[], metavar="PATH",
                   help="additional lint targets (repeatable; same as the "
                   "positionals — scripting convenience)")
    p.add_argument("--changed-only", action="store_true",
                   help="scope every tier to uncommitted git changes "
                   "(staged+unstaged+untracked): AST lints only those "
                   "files; IR/dep audit only the registry entries whose "
                   "kernel source or spec_deps intersect them — the "
                   "pre-commit mode")
    p.add_argument("--ir", action="store_true",
                   help="run the IR tier instead: abstractly trace every "
                   "registered kernel entry point (jax.make_jaxpr on CPU, "
                   "no compiles) and audit the jaxprs (IR001-IR005)")
    p.add_argument("--dep", action="store_true",
                   help="run the dep tier instead: abstract row-dependence "
                   "propagation over the same jaxprs — certify every "
                   "kernel's row_coupled declaration and the replicated-"
                   "scan discipline (IR006/IR007)")
    p.add_argument("--all", dest="all_tiers", action="store_true",
                   help="run AST + IR + dep tiers in one invocation: "
                   "merged exit code, per-tier timing, `tier` field on "
                   "every JSON finding — the CI/rollout gate shape")
    p.add_argument("--manifest", default=None, metavar="PATH",
                   help="IR tier: additionally audit a prewarm trace "
                   "manifest — every record must re-trace to its recorded "
                   "signature (IR004)")
    p.add_argument("--root", default=None,
                   help="repo root (default: this checkout)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined findings too")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to graftlint_baseline.json "
                   "with EMPTY justifications (the linter refuses them "
                   "until each is justified); always runs ALL tiers — "
                   "the baseline file is shared")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rid, r in sorted({**RULES, **IR_RULES, **DEP_RULES}.items()):
            print(f"{rid}  {r.title}")
        return 0

    if args.ir + args.dep + args.all_tiers > 1:
        print("error: --ir, --dep and --all are mutually exclusive",
              file=sys.stderr)
        return 2

    paths = list(args.paths) + list(args.extra_paths)
    config = default_config(args.root)
    tiers = (
        ["ast", "ir", "dep"] if args.all_tiers
        else ["ir"] if args.ir
        else ["dep"] if args.dep
        else ["ast"]
    )

    if args.manifest is not None and not args.manifest:
        # an empty path is almost always `--manifest "$UNSET_VAR"`: the
        # operator asked for a manifest audit and would get a silent skip
        print("error: --manifest requires a non-empty path (is "
              "KARMADA_TPU_TRACE_MANIFEST set?)", file=sys.stderr)
        return 2
    if args.manifest and "ir" not in tiers:
        print("error: --manifest is an IR-tier audit (use --ir or --all)",
              file=sys.stderr)
        return 2
    if args.all_tiers and paths:
        print("error: --all takes no path/family scope (paths mean files "
              "to the AST tier but family names to --ir/--dep; use "
              "--changed-only for a scoped all-tier run)", file=sys.stderr)
        return 2

    changed = None
    if args.changed_only:
        if args.write_baseline:
            print("error: --write-baseline needs the FULL lint scope — a "
                  "baseline regenerated from only the changed files would "
                  "delete every justified entry outside them",
                  file=sys.stderr)
            return 2
        try:
            changed = changed_py_files(config.root)
        except RuntimeError as exc:
            print(f"error: --changed-only needs a git checkout: {exc}",
                  file=sys.stderr)
            return 2
        if not changed:
            print("0 changed python files: nothing to lint")
            return 0

    if args.write_baseline:
        # baseline=None: the new baseline must hold EVERY current finding
        # (a baselined run would drop — and thereby delete — entries that
        # still match); write_baseline carries existing justifications
        # over. ALL tiers always run here — the baseline file is shared,
        # so a one-tier regeneration would delete the other tiers' entries.
        raw = run(paths or DEFAULT_TARGETS, root=args.root, baseline=None)
        findings = list(raw.findings)
        from .dep import run_dep
        from .ir import run_ir

        findings += run_ir(
            root=args.root, baseline=None, manifest=args.manifest
        ).findings
        findings += run_dep(root=args.root, baseline=None).findings
        path = config.root / config.baseline_path
        n = write_baseline(path, findings)
        print(f"wrote {n} entries to {path} — add a justification to each "
              "new entry (empty justifications are rejected)")
        return 0

    results: dict = {}
    timings: dict = {}
    for tier in tiers:
        t0 = time.perf_counter()
        try:
            results[tier] = _run_tier(tier, args, paths, changed, config)
        except KeyError as exc:
            print(f"error: {exc.args[0]}", file=sys.stderr)
            return 2
        timings[tier] = time.perf_counter() - t0

    ok = all(r.ok for r in results.values())
    if args.format == "json":
        if len(tiers) == 1:
            tier = tiers[0]
            doc = results[tier].to_json()
            doc["tier"] = tier
            doc["seconds"] = round(timings[tier], 3)
            for f in doc["findings"] + doc["baselined"]:
                f["tier"] = tier
        else:
            doc = {"ok": ok, "tiers": {}}
            for tier in tiers:
                td = results[tier].to_json()
                td["tier"] = tier
                td["seconds"] = round(timings[tier], 3)
                for f in td["findings"] + td["baselined"]:
                    f["tier"] = tier
                doc["tiers"][tier] = td
        print(json.dumps(doc, indent=2))
    else:
        blocks = []
        for tier in tiers:
            text = results[tier].render_text()
            if len(tiers) > 1:
                text = (
                    f"== {tier} tier ({timings[tier]:.2f}s) ==\n{text}"
                )
            blocks.append(text)
        print("\n\n".join(blocks))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
