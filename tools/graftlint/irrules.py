"""The IR00x analyzers: jaxpr-level invariants over traced kernels.

| id    | invariant                                                        |
|-------|------------------------------------------------------------------|
| IR001 | no float64 / weak-float promotion anywhere in a kernel jaxpr     |
| IR002 | no host round-trip primitives (callbacks) inside a kernel        |
| IR003 | no large closed-over constants (captured arrays bake snapshot    |
|       | data into the trace -> per-snapshot recompiles)                  |
| IR004 | trace-manifest fidelity: records re-trace to their recorded      |
|       | signature; the fleet-kernel registries cannot drift apart        |
| IR005 | donation audit: buffers declared donated are actually consumed   |

Each rule walks a ``TracedKernel`` (see ir.py) — an entry point abstractly
traced via ``jax.make_jaxpr`` over one bucket of the representative grid.
The walk is duck-typed over jaxpr objects (``.eqns``, ``.aval``,
``.primitive.name``) so this module never imports jax: like the AST tier,
listing rules and computing registries must stay dependency-free; only the
TRACING step (ir.py) needs a live jax.
"""

from __future__ import annotations

from typing import Iterator

from .core import Finding, Rule, rule

# -- jaxpr walking (duck-typed; no jax import) ------------------------------


def _subjaxprs(params: dict):
    """Jaxpr objects nested in an eqn's params (scan/cond/pjit bodies)."""
    for value in params.values():
        items = value if isinstance(value, (list, tuple)) else (value,)
        for item in items:
            inner = getattr(item, "jaxpr", None)  # ClosedJaxpr
            if inner is not None and hasattr(inner, "eqns"):
                yield inner
            elif hasattr(item, "eqns"):  # raw Jaxpr
                yield item


def walk_eqns(jaxpr, _depth: int = 0):
    """Every eqn of ``jaxpr`` and its nested sub-jaxprs (scan bodies,
    cond branches, inner pjit calls), depth-first."""
    if _depth > 32:  # defensive: malformed self-referential params
        return
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _subjaxprs(eqn.params):
            yield from walk_eqns(sub, _depth + 1)


def _aval(var):
    av = getattr(var, "aval", None)
    return av if av is not None and hasattr(av, "dtype") else None


class IRRule(Rule):
    kind = "ir"
    id = "IR000"

    def check(self, traced, ctx) -> Iterator[Finding]:  # type: ignore[override]
        return iter(())

    def finalize(self, ctx) -> Iterator[Finding]:  # type: ignore[override]
        return iter(())


# -- IR001 — dtype discipline -----------------------------------------------

#: dtypes that must never appear in a kernel trace: x64 is enabled
#: process-wide for the INTEGER math (ops/__init__.py), so any float64 is
#: an accidental promotion paying doubled VPU/memory cost — every float
#: the kernels legitimately use is a pinned float32
_BANNED_DTYPES = ("float64", "complex128", "complex64")


@rule
class DtypeDiscipline(IRRule):
    id = "IR001"
    title = "no float64 / weak-float promotion in kernel jaxprs"

    def check(self, traced, ctx) -> Iterator[Finding]:
        seen: set = set()
        jaxpr = traced.closed_jaxpr.jaxpr

        def probe(av, where: str):
            if av is None:
                return None
            d = str(av.dtype)
            if d in _BANNED_DTYPES:
                return f"{d}:{where}"
            # a weak float intermediate is a promotion waiting for a
            # partner operand (and flips with jax.config drift) — every
            # float in a kernel must be pinned via .astype/dtype=
            if getattr(av, "weak_type", False) and d.startswith("float"):
                return f"weak-{d}:{where}"
            return None

        hits = [probe(_aval(v), "input") for v in jaxpr.invars]
        hits += [probe(_aval(v), "const") for v in jaxpr.constvars]
        for eqn in walk_eqns(jaxpr):
            hits += [
                probe(_aval(v), eqn.primitive.name) for v in eqn.outvars
            ]
        for detail in filter(None, hits):
            if detail in seen:
                continue
            seen.add(detail)
            yield traced.finding(
                self.id,
                f"{traced.label}: {detail.rsplit(':', 1)[0]} value produced "
                f"by `{detail.rsplit(':', 1)[1]}` in the traced jaxpr — pin "
                "the dtype explicitly (ops/dispense.py ACC_WIDE/ACC_NARROW "
                "for accumulators, .astype(jnp.float32) for float math); "
                "unpinned dtypes flip with jax.config drift and double "
                "VPU/memory cost on TPU",
                detail,
            )


# -- IR002 — host round-trips -----------------------------------------------

#: primitives that leave the device mid-kernel: any callback flavor plus
#: the infeed/outfeed escape hatches; `device_get` never appears as a
#: primitive (it is an eager host fetch) but is listed for completeness
_HOST_PRIMS = {"infeed", "outfeed", "device_get"}


def _is_host_primitive(name: str) -> bool:
    return name in _HOST_PRIMS or "callback" in name


@rule
class HostRoundTrip(IRRule):
    id = "IR002"
    title = "no host round-trip primitives inside kernel jaxprs"

    def check(self, traced, ctx) -> Iterator[Finding]:
        seen: set = set()
        for eqn in walk_eqns(traced.closed_jaxpr.jaxpr):
            name = eqn.primitive.name
            if not _is_host_primitive(name) or name in seen:
                continue
            seen.add(name)
            yield traced.finding(
                self.id,
                f"{traced.label}: host round-trip primitive `{name}` inside "
                "the kernel jaxpr — every dispatch blocks on a device->host"
                "->device transfer on the serving path; hoist the host work "
                "out of the kernel or precompute it into an input",
                name,
            )


# -- IR003 — closed-over constants ------------------------------------------

#: bytes above which a captured constant is flagged: big captures are
#: snapshot-state arrays baked into the executable — every new snapshot
#: re-traces AND re-transfers them (the inputs-not-captures contract the
#: fleet kernels are built on)
CONST_BYTES_THRESHOLD = 4096


@rule
class ConstCapture(IRRule):
    id = "IR003"
    title = "no large closed-over constants in kernel jaxprs"

    def check(self, traced, ctx) -> Iterator[Finding]:
        threshold = getattr(
            ctx, "const_bytes_threshold", CONST_BYTES_THRESHOLD
        )
        for i, const in enumerate(traced.closed_jaxpr.consts):
            nbytes = getattr(const, "nbytes", 0)
            if nbytes <= threshold:
                continue
            shape = tuple(getattr(const, "shape", ()))
            dtype = getattr(const, "dtype", type(const).__name__)
            yield traced.finding(
                self.id,
                f"{traced.label}: closed-over constant #{i} "
                f"({shape} {dtype}, {nbytes} bytes) captured into the "
                "trace — captured arrays are baked into the executable, so "
                "every rebuilt snapshot/table mints a fresh compile AND "
                "re-uploads the data; pass it as a kernel input instead",
                f"const:{shape}:{dtype}",
            )


# -- IR004 — trace-manifest fidelity ----------------------------------------


@rule
class ManifestFidelity(IRRule):
    id = "IR004"
    title = ("trace-manifest records re-trace to their recorded signature; "
             "kernel registries stay in lockstep")

    def finalize(self, ctx) -> Iterator[Finding]:
        # (a) every registry spec must trace: a spec that no longer traces
        # means the entry-point registry drifted from the kernel signature
        # — exactly the drift that would make prewarm replay a stale
        # manifest record into a failed compile at boot
        for entry, spec, err in ctx.trace_failures:
            yield Finding(
                rule=self.id, path=entry.path, line=ctx.entry_line(entry),
                col=1,
                message=(
                    f"{entry.name}[{spec.variant}]: entry-point spec failed "
                    f"to trace ({err}) — the IR registry "
                    "(tools/graftlint/ir.py) drifted from the kernel "
                    "signature; update the spec builder or the kernel"
                ),
                anchor=entry.attr, detail=f"trace:{spec.variant}",
                anchor_line=ctx.entry_line(entry),
            )
        # (b) the three fleet-kernel registries must agree: FLEET_KERNELS
        # (dispatch), prewarm._KERNELS (manifest load filter + replay),
        # and the IR entry points (audit). A kernel present in one but not
        # the others is a serving-path dispatch prewarm can never cover.
        cov = ctx.registry_coverage
        if cov is not None:
            surfaces = {
                "fleet": ("karmada_tpu/scheduler/fleet.py", "FLEET_KERNELS"),
                "prewarm": ("karmada_tpu/scheduler/prewarm.py", "_KERNELS"),
                "ir": ("tools/graftlint/ir.py", "ENTRY_POINTS"),
            }
            union = set().union(*cov.values())
            for kernel in sorted(union):
                missing = [s for s, names in cov.items() if kernel not in names]
                if not missing:
                    continue
                for s in missing:
                    path, anchor = surfaces[s]
                    yield Finding(
                        rule=self.id, path=path, line=1, col=1,
                        message=(
                            f"fleet kernel family {kernel!r} is missing "
                            f"from {anchor} ({path}) but present in "
                            f"{sorted(set(cov) - set(missing))} — prewarm "
                            "would silently cover less than the serving "
                            "path dispatches; register it everywhere"
                        ),
                        anchor=anchor, detail=f"coverage:{kernel}",
                    )
        # (c) manifest records: each must resolve to a known kernel,
        # re-trace under the recorded shapes/statics, and round-trip to a
        # byte-identical content signature
        for res in ctx.manifest_results:
            if res.error is None:
                continue
            if res.index < 0:  # manifest-level: unreadable/empty file
                yield Finding(
                    rule=self.id, path=ctx.manifest_rel, line=1, col=1,
                    message=(
                        f"{ctx.manifest_rel}: {res.error} — the audited "
                        "manifest proves NO prewarm coverage; a warmup "
                        "against it would be a silent no-op"
                    ),
                    anchor="<manifest>", detail=f"manifest:{res.reason}",
                )
                continue
            yield Finding(
                rule=self.id, path=ctx.manifest_rel, line=1, col=1,
                message=(
                    f"manifest record #{res.index} ({res.kernel}): "
                    f"{res.error} — prewarm replay of this manifest would "
                    "fail or compile something the serving path never "
                    "dispatches; re-record the manifest "
                    "(delete it and run a warm pass) or fix the kernel"
                ),
                anchor=res.kernel, detail=f"record[{res.index}]:{res.reason}",
            )


# -- IR005 — donation audit --------------------------------------------------


@rule
class DonationAudit(IRRule):
    id = "IR005"
    title = "buffers declared donated are actually consumed by an output"

    def check(self, traced, ctx) -> Iterator[Finding]:
        # donation is declared on the jit wrapper, so it surfaces on the
        # top-level pjit eqn of the outer trace; XLA can only alias a
        # donated input into an output of IDENTICAL shape+dtype — a
        # donated buffer with no such output is silently copied, doubling
        # its HBM footprint (the dense resident is the largest tenant)
        for eqn in traced.closed_jaxpr.jaxpr.eqns:
            if eqn.primitive.name != "pjit":
                continue
            donated = eqn.params.get("donated_invars") or ()
            if not any(donated):
                continue
            pool = [
                (tuple(av.shape), str(av.dtype))
                for av in (_aval(v) for v in eqn.outvars)
                if av is not None
            ]
            for pos, (var, don) in enumerate(zip(eqn.invars, donated)):
                if not don:
                    continue
                av = _aval(var)
                if av is None:
                    continue
                sig = (tuple(av.shape), str(av.dtype))
                if sig in pool:
                    pool.remove(sig)  # one output consumes one donation
                    continue
                yield traced.finding(
                    self.id,
                    f"{traced.label}: donated argument #{pos} "
                    f"({sig[0]} {sig[1]}) has no output of identical "
                    "shape/dtype to alias into — XLA silently drops the "
                    "donation and keeps BOTH buffers live; return the "
                    "updated buffer or stop donating it",
                    f"donated[{pos}]:{sig[0]}:{sig[1]}",
                )
