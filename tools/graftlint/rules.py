"""The GL00x analyzers.

| id    | invariant                                                        |
|-------|------------------------------------------------------------------|
| GL001 | trace safety: no host control flow / host sync inside jit        |
| GL002 | trace-key completeness: kernel dispatches ledger their signature |
| GL003 | env-flag registry: KARMADA_TPU_* reads declared + documented     |
| GL004 | lock discipline: lock-guarded attrs never mutated lock-free      |
| GL005 | cold-start import hygiene: no module-level jax in entry modules, |
|       | no scheduler imports from ops/                                   |
| GL006 | metric naming: registry.counter/gauge/histogram names must carry |
|       | the karmada_tpu_/karmada_scheduler_ prefix and be unique         |
| GL007 | bounded RPCs: every gRPC unary stub / urlopen call site passes   |
|       | an explicit timeout (watch streams are deliberately unbounded)   |
| GL008 | span taxonomy: every span name recorded on a tracer must be      |
|       | registered in utils.tracing SPAN_NAMES (stitcher + docs key on)  |
| GL009 | history series: every HistorySeries source must map to a         |
|       | registered metric family or the SPAN_NAMES taxonomy              |
| GL010 | reason taxonomy: every Condition(reason=...) / .inc(reason=...)  |
|       | literal must be registered in utils.reasons REASONS              |
| GL011 | lock-READ discipline: attrs mutated under a class's lock must    |
|       | not be read lock-free (GL004's write-side rule, read side)       |
| GL012 | budget construction: Deadline/BackoffPolicy built inside a       |
|       | for/while loop resets the budget every iteration                 |
| GL013 | bounded caches: dict/deque attrs grown on worker/controller hot  |
|       | paths must have an eviction site or a maxlen cap                 |

Each rule is a pure-AST pass over one ``ModuleInfo`` (plus cross-module
``finalize`` hooks); nothing here imports jax.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from .core import (
    ROLE_ENTRY,
    ROLE_HOTPATH,
    ROLE_JIT,
    ROLE_LEDGER,
    ROLE_OPS,
    Finding,
    LintContext,
    ModuleInfo,
    Rule,
    rule,
)

# --------------------------------------------------------------------------
# shared: jit detection
# --------------------------------------------------------------------------


def _is_jax_jit(node: ast.AST) -> bool:
    """``jax.jit`` / bare ``jit`` (from jax import jit)."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return isinstance(node.value, ast.Name) and node.value.id == "jax"
    return isinstance(node, ast.Name) and node.id == "jit"


def _is_partial(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "partial"
    return isinstance(node, ast.Attribute) and node.attr == "partial"


def _static_names(call: ast.Call, func: ast.FunctionDef) -> set:
    """static_argnames / static_argnums from a jit(...) call, as param
    names of ``func``."""
    names: set = set()
    positional = [a.arg for a in func.args.posonlyargs + func.args.args]
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in _str_elements(kw.value):
                names.add(n)
        elif kw.arg == "static_argnums":
            for i in _int_elements(kw.value):
                if 0 <= i < len(positional):
                    names.add(positional[i])
    return names


def _str_elements(node: ast.AST) -> list:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
    return []


def _int_elements(node: ast.AST) -> list:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        ]
    return []


def jitted_functions(mod: ModuleInfo) -> dict:
    """FunctionDef -> set of static param names, for every function the
    module jits: ``@jax.jit``, ``@partial(jax.jit, ...)``, and the
    ``name = jax.jit(fn, ...)`` / ``return jax.jit(fn, ...)`` wrapper
    forms. Also returns (via ``.aliases``-style second dict) the bound
    jitted NAMES a call site can refer to."""
    defs: dict = {}
    by_name: dict = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)
            for dec in node.decorator_list:
                if _is_jax_jit(dec):
                    defs[node] = set()
                elif isinstance(dec, ast.Call):
                    if _is_jax_jit(dec.func):
                        defs[node] = _static_names(dec, node)
                    elif (
                        _is_partial(dec.func)
                        and dec.args
                        and _is_jax_jit(dec.args[0])
                    ):
                        defs[node] = _static_names(dec, node)
    jit_names: set = set()
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and _is_jax_jit(node.func)):
            continue
        if not (node.args and isinstance(node.args[0], ast.Name)):
            continue
        target = by_name.get(node.args[0].id)
        if target is not None and not isinstance(
            target, ast.AsyncFunctionDef
        ):
            defs.setdefault(target, set()).update(
                _static_names(node, target)
            )
            jit_names.add(target.name)
        # the wrapper's bound name is jitted too (schedule_step = jax.jit(f))
        parent = mod.parents.get(node)
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                if isinstance(t, ast.Name):
                    jit_names.add(t.id)
    jit_names |= {f.name for f in defs}
    return {"defs": defs, "names": jit_names}


def _enclosing_functions(mod: ModuleInfo, node: ast.AST) -> list:
    out = []
    cur = mod.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(cur)
        cur = mod.parents.get(cur)
    return out


# --------------------------------------------------------------------------
# GL001 — trace safety
# --------------------------------------------------------------------------

#: attribute reads of a traced array that resolve at TRACE time (static)
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}
#: builtins whose result over a traced array is static (len = shape[0])
SAFE_CALLS = {"len", "isinstance", "hasattr", "getattr", "type"}
#: host-conversion builtins that force a device sync inside a trace
HOST_CONVERSIONS = {"float", "int", "bool", "complex"}
#: time-module calls that bake a host clock read into the trace
TIME_CALLS = {"time", "perf_counter", "monotonic", "process_time", "sleep"}


def _traced_use(node: ast.AST, traced: set) -> Optional[str]:
    """First traced-parameter name used as a VALUE in ``node``, ignoring
    static-at-trace-time reads (``x.shape``, ``len(x)``...)."""
    if isinstance(node, ast.Name):
        return node.id if node.id in traced else None
    if isinstance(node, ast.Attribute) and node.attr in STATIC_ATTRS:
        return None
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in SAFE_CALLS:
            return None
    for child in ast.iter_child_nodes(node):
        hit = _traced_use(child, traced)
        if hit:
            return hit
    return None


@rule
class TraceSafety(Rule):
    id = "GL001"
    title = "no host control flow or host sync inside jitted functions"

    def check(self, mod: ModuleInfo, ctx: LintContext) -> Iterator[Finding]:
        if ROLE_JIT not in mod.roles:
            return
        info = jitted_functions(mod)
        for func, statics in info["defs"].items():
            args = func.args
            params = {
                a.arg
                for a in args.posonlyargs + args.args + args.kwonlyargs
            }
            traced = params - statics
            anchor = mod.qualname(func)

            def emit(node, message, detail):
                return Finding(
                    rule=self.id, path=mod.rel, line=node.lineno,
                    col=node.col_offset + 1, message=message,
                    anchor=anchor, detail=detail,
                )

            for node in ast.walk(func):
                if isinstance(node, (ast.If, ast.While)):
                    hit = _traced_use(node.test, traced)
                    if hit:
                        kind = "if" if isinstance(node, ast.If) else "while"
                        yield emit(
                            node,
                            f"Python `{kind}` on traced value {hit!r} inside "
                            f"jitted {func.name}() — use jnp.where/lax.cond "
                            "or make it a static argument",
                            f"{kind}:{hit}",
                        )
                elif isinstance(node, ast.Call):
                    fn = node.func
                    if isinstance(fn, ast.Name):
                        if fn.id in HOST_CONVERSIONS:
                            hit = next(
                                filter(None, (
                                    _traced_use(a, traced) for a in node.args
                                )), None,
                            )
                            if hit:
                                yield emit(
                                    node,
                                    f"host conversion {fn.id}() of traced "
                                    f"value {hit!r} inside jitted "
                                    f"{func.name}() — forces a device sync "
                                    "per call",
                                    f"{fn.id}:{hit}",
                                )
                        elif fn.id == "print":
                            yield emit(
                                node,
                                f"print() inside jitted {func.name}() — "
                                "runs at TRACE time only (or syncs under "
                                "debug callbacks); use jax.debug.print",
                                "print",
                            )
                    elif isinstance(fn, ast.Attribute):
                        if fn.attr in ("item", "tolist") and not node.args:
                            yield emit(
                                node,
                                f".{fn.attr}() inside jitted {func.name}() "
                                "— host sync on the serving path",
                                f".{fn.attr}",
                            )
                        elif (
                            fn.attr in TIME_CALLS
                            and isinstance(fn.value, ast.Name)
                            and fn.value.id in ("time", "_time")
                        ):
                            yield emit(
                                node,
                                f"time.{fn.attr}() inside jitted "
                                f"{func.name}() — the clock read is baked "
                                "into the trace, not evaluated per call",
                                f"time.{fn.attr}",
                            )
                        elif (
                            fn.attr in ("getenv",)
                            and isinstance(fn.value, ast.Name)
                            and fn.value.id == "os"
                        ):
                            yield emit(
                                node,
                                f"os.getenv() inside jitted {func.name}() "
                                "— env reads are trace-time constants; "
                                "thread the value through a static arg",
                                "os.getenv",
                            )
                elif isinstance(node, ast.Attribute):
                    if (
                        node.attr == "environ"
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "os"
                    ):
                        yield emit(
                            node,
                            f"os.environ read inside jitted {func.name}() "
                            "— env reads are trace-time constants; thread "
                            "the value through a static arg",
                            "os.environ",
                        )


# --------------------------------------------------------------------------
# GL002 — trace-key completeness
# --------------------------------------------------------------------------


@rule
class TraceKeyCompleteness(Rule):
    id = "GL002"
    title = "jit-kernel dispatch sites must ledger their trace signature"

    def check(self, mod: ModuleInfo, ctx: LintContext) -> Iterator[Finding]:
        if ROLE_LEDGER not in mod.roles:
            return
        info = jitted_functions(mod)
        kernels = info["names"]
        if not kernels:
            return
        jit_defs = set(info["defs"])
        helpers = set(ctx.config.ledger_helpers)

        def has_ledger_call(func: ast.AST) -> bool:
            for node in ast.walk(func):
                if isinstance(node, ast.Call):
                    fn = node.func
                    name = (
                        fn.id if isinstance(fn, ast.Name)
                        else fn.attr if isinstance(fn, ast.Attribute)
                        else None
                    )
                    if name in helpers:
                        return True
            return False

        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in kernels
            ):
                continue
            enclosing = _enclosing_functions(mod, node)
            # a kernel called from inside another jitted kernel traces as
            # ONE composed program — the outer dispatch site ledgers it
            if any(f in jit_defs for f in enclosing):
                continue
            if any(has_ledger_call(f) for f in enclosing):
                continue
            anchor = mod.qualname(node.func) or "<module>"
            yield Finding(
                rule=self.id, path=mod.rel, line=node.lineno,
                col=node.col_offset + 1,
                message=(
                    f"jitted kernel {node.func.id}() dispatched without a "
                    "trace-key ledger call "
                    f"({'/'.join(sorted(helpers))}) in any enclosing "
                    "function — a fresh compile here is invisible to "
                    "new_trace_last_pass and the prewarm manifest"
                ),
                anchor=anchor, detail=node.func.id,
            )


# --------------------------------------------------------------------------
# GL003 — env-flag registry
# --------------------------------------------------------------------------


def _os_aliases(tree: ast.Module) -> tuple:
    """(getenv aliases, environ aliases) bound by ``from os import ...``
    — the import style that would otherwise slip past the registry gate."""
    getenv_names: set = set()
    environ_names: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "os":
            for a in node.names:
                if a.name == "getenv":
                    getenv_names.add(a.asname or a.name)
                elif a.name == "environ":
                    environ_names.add(a.asname or a.name)
    return getenv_names, environ_names


def _is_environ(node: ast.AST, environ_names: set) -> bool:
    """``os.environ`` or a ``from os import environ [as e]`` binding."""
    if isinstance(node, ast.Attribute):
        return (
            node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os"
        )
    return isinstance(node, ast.Name) and node.id in environ_names


def _env_key_node(
    call_or_sub: ast.AST, getenv_names: set, environ_names: set
) -> Optional[ast.AST]:
    """The key expression of an env READ, or None.

    Shapes: ``os.environ[k]``, ``os.environ.get(k, ...)``,
    ``os.getenv(k, ...)``, and the aliased forms bound by
    ``from os import getenv/environ [as name]``. Env WRITES/constructions
    (``os.environ[k] = v`` handled by caller, ``dict(os.environ, K=v)``)
    are not reads."""
    node = call_or_sub
    if isinstance(node, ast.Subscript):
        if _is_environ(node.value, environ_names):
            return node.slice
        return None
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr == "get" and _is_environ(fn.value, environ_names):
                return node.args[0] if node.args else None
            if (
                fn.attr == "getenv"
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "os"
            ):
                return node.args[0] if node.args else None
        elif isinstance(fn, ast.Name) and fn.id in getenv_names:
            return node.args[0] if node.args else None
    return None


@rule
class EnvFlagRegistry(Rule):
    id = "GL003"
    title = "KARMADA_TPU_* env reads must be registered and documented"

    @staticmethod
    def _reads(ctx: LintContext) -> set:
        # per-run accumulator lives on the context (rule instances are
        # process-global singletons; state must not leak across runs)
        if not hasattr(ctx, "_gl003_reads"):
            ctx._gl003_reads = set()
        return ctx._gl003_reads

    def check(self, mod: ModuleInfo, ctx: LintContext) -> Iterator[Finding]:
        prefix = ctx.config.env_prefix
        getenv_names, environ_names = _os_aliases(mod.tree)
        for node in ast.walk(mod.tree):
            key = _env_key_node(node, getenv_names, environ_names)
            if key is None:
                continue
            # a Subscript on the left of an assignment is a WRITE
            if isinstance(node, ast.Subscript) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                continue
            name: Optional[str] = None
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                name = key.value
            elif isinstance(key, ast.Name):
                name = ctx.resolve_env_constant(mod, key.id)
            if not name or not name.startswith(prefix):
                continue
            self._reads(ctx).add(name)
            if name not in ctx.env_registry:
                yield Finding(
                    rule=self.id, path=mod.rel, line=node.lineno,
                    col=node.col_offset + 1,
                    message=(
                        f"env flag {name} read here but not declared in "
                        f"{ctx.config.flags_module} ENV_FLAGS — register "
                        "it with a default and description"
                    ),
                    anchor=mod.qualname(node), detail=name,
                )

    def finalize(self, ctx: LintContext) -> Iterator[Finding]:
        """Registry-side drift, anchored on flags.py: undocumented flags
        and registered-but-never-read flags (unless declared external —
        read by tests/bench drivers outside the scanned tree)."""
        scanned = {m.rel for m in ctx.modules}
        if ctx.config.flags_module not in scanned:
            return
        docs = ctx.docs_text
        reads = self._reads(ctx)
        for name, flag in sorted(ctx.env_registry.items()):
            if name not in docs:
                yield Finding(
                    rule=self.id, path=ctx.config.flags_module, line=1,
                    col=1,
                    message=(
                        f"registered env flag {name} is not documented in "
                        f"{ctx.config.docs_env_table} — regenerate the env "
                        "table (python tools/docs_from_bench.py --env-table)"
                    ),
                    anchor="ENV_FLAGS", detail=f"undocumented:{name}",
                )
            if not getattr(ctx, "full_scope", True):
                # a scoped run (--changed-only / --paths) cannot prove
                # "never read" — the read sites are outside the scan
                continue
            if name not in reads and not getattr(flag, "external", False):
                yield Finding(
                    rule=self.id, path=ctx.config.flags_module, line=1,
                    col=1,
                    message=(
                        f"registered env flag {name} is never read in the "
                        "scanned tree — remove it or mark it external=True "
                        "(read by tests/bench drivers)"
                    ),
                    anchor="ENV_FLAGS", detail=f"stale:{name}",
                )


# --------------------------------------------------------------------------
# GL004 — lock discipline
# --------------------------------------------------------------------------

#: method calls that mutate the receiver in place
MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "update", "pop",
    "popitem", "popleft", "remove", "discard", "clear", "setdefault",
}
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _mutated_self_attr(node: ast.AST) -> Optional[str]:
    """self attr mutated by ``node``: assignment/augassign/del targets
    (including self.x[...] = v) and in-place mutator calls."""
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            base = t
            if isinstance(base, ast.Subscript):
                base = base.value
            attr = _self_attr(base)
            if attr:
                return attr
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            base = t
            if isinstance(base, ast.Subscript):
                base = base.value
            attr = _self_attr(base)
            if attr:
                return attr
    elif isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in MUTATORS:
            attr = _self_attr(fn.value)
            if attr:
                return attr
    return None


def _class_lock_attrs(cls: ast.ClassDef) -> set:
    """Which self attrs ARE locks (threading.Lock/RLock/Condition(...))."""
    lock_attrs: set = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            fn = node.value.func
            factory = (
                fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else None
            )
            if factory in _LOCK_FACTORIES:
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr:
                        lock_attrs.add(attr)
    return lock_attrs


def _under_lock(
    mod: ModuleInfo, cls: ast.ClassDef, lock_attrs: set, node: ast.AST
) -> bool:
    """``node`` sits inside a ``with self.<lock>:`` block of ``cls``."""
    cur = mod.parents.get(node)
    while cur is not None and cur is not cls:
        if isinstance(cur, ast.With):
            for item in cur.items:
                expr = item.context_expr
                # with self._lock: / with self._cond: (Condition
                # wraps the same lock)
                if isinstance(expr, ast.Call):
                    expr = expr.func  # e.g. self._lock.acquire? no-op
                attr = _self_attr(expr)
                if attr in lock_attrs:
                    return True
        cur = mod.parents.get(cur)
    return False


def _class_mutations(cls: ast.ClassDef) -> list:
    """(attr, node, method) mutation sites of non-lock self attrs —
    methods are the DIRECT defs; nested closures attribute to their
    outermost method."""
    lock_attrs = _class_lock_attrs(cls)
    mutations = []
    for method in cls.body:
        if not isinstance(
            method, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            continue
        for node in ast.walk(method):
            attr = _mutated_self_attr(node)
            if attr and attr not in lock_attrs:
                mutations.append((attr, node, method))
    return mutations


def _guarded_attrs(mod: ModuleInfo, cls: ast.ClassDef) -> set:
    """Attrs the class treats as lock-guarded: mutated under the class's
    lock at least once — GL004's definition, shared with GL011 so the
    write-side and read-side rules can never disagree on what 'guarded'
    means."""
    lock_attrs = _class_lock_attrs(cls)
    if not lock_attrs:
        return set()
    return {
        attr
        for attr, node, _method in _class_mutations(cls)
        if _under_lock(mod, cls, lock_attrs, node)
    }


@rule
class LockDiscipline(Rule):
    id = "GL004"
    title = "lock-guarded attributes must not be mutated lock-free"

    def check(self, mod: ModuleInfo, ctx: LintContext) -> Iterator[Finding]:
        for cls in ast.walk(mod.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(mod, cls)

    def _check_class(self, mod: ModuleInfo, cls: ast.ClassDef):
        lock_attrs = _class_lock_attrs(cls)
        if not lock_attrs:
            return

        def under_lock(node: ast.AST) -> bool:
            return _under_lock(mod, cls, lock_attrs, node)

        mutations = _class_mutations(cls)
        guarded = {
            attr
            for attr, node, method in mutations
            if under_lock(node)
        }
        for attr, node, method in mutations:
            if attr not in guarded or under_lock(node):
                continue
            # construction happens before the object is shared: __init__
            # (and __new__) mutations are the single-writer window
            if method.name in ("__init__", "__new__"):
                continue
            yield Finding(
                rule=self.id, path=mod.rel, line=node.lineno,
                col=node.col_offset + 1,
                message=(
                    f"self.{attr} is mutated under "
                    f"{cls.name}'s lock elsewhere but lock-free in "
                    f"{method.name}() — take the lock, or document the "
                    "single-writer invariant with "
                    f"`# graftlint: disable={self.id}`"
                ),
                anchor=f"{mod.qualname(cls)}.{method.name}", detail=attr,
                anchor_line=method.lineno,
            )


# --------------------------------------------------------------------------
# GL006 — metric naming & uniqueness
# --------------------------------------------------------------------------

#: registry factory methods whose first argument is a metric family name
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
#: allowed metric-name prefixes: the project family and the reference's
#: scheduler names carried over verbatim (metrics.go:61-115)
_METRIC_PREFIXES = ("karmada_tpu_", "karmada_scheduler_")


@rule
class MetricNaming(Rule):
    id = "GL006"
    title = (
        "metric families must be karmada_tpu_*/karmada_scheduler_* and "
        "unique across the import graph"
    )

    @staticmethod
    def _defined(ctx: LintContext) -> dict:
        # name -> [(rel, line, anchor)], accumulated per run on the
        # context (rule instances are process-global singletons)
        if not hasattr(ctx, "_gl006_defined"):
            ctx._gl006_defined = {}
        return ctx._gl006_defined

    def check(self, mod: ModuleInfo, ctx: LintContext) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_FACTORIES
            ):
                continue
            if not (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            # restrict to Registry-shaped receivers (``registry.counter``,
            # ``reg.histogram``, ``self.registry.gauge``) so unrelated
            # APIs with a str-first ``counter(...)`` method don't trip
            recv = node.func.value
            recv_name = (
                recv.id if isinstance(recv, ast.Name)
                else recv.attr if isinstance(recv, ast.Attribute)
                else None
            )
            if recv_name is None or "reg" not in recv_name.lower():
                continue
            name = node.args[0].value
            anchor = mod.qualname(node) or "<module>"
            self._defined(ctx).setdefault(name, []).append(
                (mod.rel, node.lineno, anchor)
            )
            if not name.startswith(_METRIC_PREFIXES):
                yield Finding(
                    rule=self.id, path=mod.rel, line=node.lineno,
                    col=node.col_offset + 1,
                    message=(
                        f"metric family {name!r} does not carry a "
                        f"{'/'.join(_METRIC_PREFIXES)} prefix — scrapers "
                        "aggregate fleets by prefix, and an unprefixed "
                        "name collides with other exporters on the node"
                    ),
                    anchor=anchor, detail=name,
                )

    def finalize(self, ctx: LintContext) -> Iterator[Finding]:
        """Cross-module uniqueness: the same family name registered twice
        double-renders on /metrics (scrapers reject the exposition)."""
        for name, sites in sorted(self._defined(ctx).items()):
            if len(sites) < 2:
                continue
            first = f"{sites[0][0]}:{sites[0][1]}"
            for rel, line, anchor in sites[1:]:
                yield Finding(
                    rule=self.id, path=rel, line=line, col=1,
                    message=(
                        f"metric family {name!r} is already registered at "
                        f"{first} — duplicate registration double-renders "
                        "the family in the exposition"
                    ),
                    anchor=anchor, detail=f"dup:{name}",
                )


# --------------------------------------------------------------------------
# GL005 — cold-start import hygiene
# --------------------------------------------------------------------------


def _module_level_stmts(tree: ast.Module):
    """Top-level statements, descending into module-level if/try blocks
    (conditional imports still run at import time) but not into defs."""
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.If, ast.Try, ast.With)):
            for f in ast.iter_child_nodes(node):
                if not isinstance(
                    f, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    stack.append(f)


@rule
class ImportHygiene(Rule):
    id = "GL005"
    title = "entry modules import jax lazily; ops/ never imports scheduler"

    def check(self, mod: ModuleInfo, ctx: LintContext) -> Iterator[Finding]:
        if ROLE_ENTRY in mod.roles:
            for node in _module_level_stmts(mod.tree):
                bad = None
                if isinstance(node, ast.Import):
                    bad = next(
                        (
                            a.name for a in node.names
                            if a.name == "jax" or a.name.startswith("jax.")
                        ),
                        None,
                    )
                elif isinstance(node, ast.ImportFrom) and node.level == 0:
                    m = node.module or ""
                    if m == "jax" or m.startswith("jax."):
                        bad = m
                if bad:
                    yield Finding(
                        rule=self.id, path=mod.rel, line=node.lineno,
                        col=node.col_offset + 1,
                        message=(
                            f"module-level `import {bad}` in entry module "
                            f"{mod.rel} — jax import costs seconds of cold "
                            "start on every CLI/controlplane boot; defer it "
                            "into the function that needs it"
                        ),
                        anchor="<module>", detail=f"jax:{bad}",
                    )
        if ROLE_OPS in mod.roles:
            pkg = ctx.config.package
            for node in ast.walk(mod.tree):
                bad = None
                if isinstance(node, ast.Import):
                    bad = next(
                        (
                            a.name for a in node.names
                            if a.name.startswith(pkg + ".scheduler")
                        ),
                        None,
                    )
                elif isinstance(node, ast.ImportFrom):
                    m = node.module or ""
                    if m.startswith(pkg + ".scheduler"):
                        bad = m
                    elif node.level >= 1 and (
                        m == "scheduler" or m.startswith("scheduler.")
                    ):
                        bad = "." * node.level + m
                if bad:
                    yield Finding(
                        rule=self.id, path=mod.rel, line=node.lineno,
                        col=node.col_offset + 1,
                        message=(
                            f"ops/ imports the scheduler ({bad}) — the "
                            "kernel layer must stay dependency-free of the "
                            "engine that dispatches it (layering, and the "
                            "scheduler import pulls the whole fleet engine "
                            "into every ops consumer's cold start)"
                        ),
                        anchor=mod.qualname(node) or "<module>",
                        detail=f"scheduler:{bad}",
                    )


# --------------------------------------------------------------------------
# GL008 — span taxonomy: recorded span names must be registered
# --------------------------------------------------------------------------

#: WaveTracer methods whose first argument is a span name
_SPAN_METHODS = {"span", "server_span", "record", "open_manual"}


@rule
class SpanTaxonomy(Rule):
    id = "GL008"
    title = (
        "span names recorded on a tracer must be registered in "
        "utils.tracing SPAN_NAMES"
    )

    def check(self, mod: ModuleInfo, ctx: LintContext) -> Iterator[Finding]:
        """Every ``tracer.span("name")`` / ``.record`` / ``.server_span``
        / ``.open_manual`` call with a literal (or f-string) first
        argument must resolve to the central taxonomy — the stitcher's
        channel attribution and the generated docs span table key on
        those names, so an unregistered span is invisible to both.
        Receivers are restricted to tracer-shaped names (``tracer``,
        ``_tracer``), the GL006 receiver heuristic; a first argument
        that is a plain variable is out of static reach and stays
        unchecked (the GL006/GL002 precedent)."""
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SPAN_METHODS
            ):
                continue
            recv = node.func.value
            recv_name = (
                recv.id if isinstance(recv, ast.Name)
                else recv.attr if isinstance(recv, ast.Attribute)
                else None
            )
            if recv_name is None or "tracer" not in recv_name.lower():
                continue
            if not node.args:
                continue
            arg = node.args[0]
            anchor = mod.qualname(node) or "<module>"
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                name = arg.value
                if not ctx.span_registered(name):
                    yield Finding(
                        rule=self.id, path=mod.rel, line=node.lineno,
                        col=node.col_offset + 1,
                        message=(
                            f"span name {name!r} is not registered in "
                            "utils.tracing SPAN_NAMES — the stitcher's "
                            "channel attribution and the docs span-"
                            "taxonomy table key on the registry; add "
                            "the name (or a `family.*` entry) there"
                        ),
                        anchor=anchor, detail=name,
                    )
            elif isinstance(arg, ast.JoinedStr):
                head = arg.values[0] if arg.values else None
                prefix = (
                    head.value
                    if isinstance(head, ast.Constant)
                    and isinstance(head.value, str)
                    else ""
                )
                if not ctx.span_family_registered(prefix):
                    yield Finding(
                        rule=self.id, path=mod.rel, line=node.lineno,
                        col=node.col_offset + 1,
                        message=(
                            f"dynamic span name with literal prefix "
                            f"{prefix!r} matches no `family.*` entry in "
                            "utils.tracing SPAN_NAMES — register the "
                            "family (a dynamic name needs a literal "
                            "head the linter and stitcher can key on)"
                        ),
                        anchor=anchor, detail=f"dynamic:{prefix}",
                    )


# --------------------------------------------------------------------------
# GL007 — bounded RPCs: explicit timeout on every unary call site
# --------------------------------------------------------------------------
#
# ISSUE 7 satellite: an RPC without a deadline is an unbounded stall — a
# black-holed peer freezes whatever thread issued it, and mid-storm that
# is a scheduling wave. Channels are built once (``chan.unary_unary(...)``
# assigned to an attribute or name); this rule tracks those stub bindings
# per scope and requires a ``timeout=`` keyword at every direct CALL of a
# stub (and every ``stub.future(...)`` / ``stub.with_call(...)`` — the
# grpc call forms the ISSUE 11 batched write path uses are stubs too; a
# 4096-op ApplyBatch without a deadline stalls the whole write SET, not
# one object). ``unary_stream`` watch/WatchBatch streams are exempt —
# they are deliberately open-ended and bounded by their reconnect loop.
# ``urllib.request.urlopen`` must pass ``timeout=`` too.


def _is_stub_factory(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "unary_unary"
    )


def _has_timeout_kw(call: ast.Call) -> bool:
    return any(
        kw.arg == "timeout" or kw.arg is None  # **kwargs may carry it
        for kw in call.keywords
    )


@rule
class BoundedRpc(Rule):
    id = "GL007"
    title = (
        "gRPC unary stubs and urlopen must pass an explicit timeout "
        "(no unbounded RPCs)"
    )

    def check(self, mod: ModuleInfo, ctx: LintContext) -> Iterator[Finding]:
        # ---- collect stub bindings: self._x = chan.unary_unary(...) per
        # class, and bare x = chan.unary_unary(...) per module
        attr_stubs: set[str] = set()
        name_stubs: set[str] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) or not _is_stub_factory(
                node.value
            ):
                continue
            for target in node.targets:
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    attr_stubs.add(target.attr)
                elif isinstance(target, ast.Name):
                    name_stubs.add(target.id)
        # urlopen aliases: `from urllib.request import urlopen [as u]`
        urlopen_names: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "urllib.request":
                for alias in node.names:
                    if alias.name == "urlopen":
                        urlopen_names.add(alias.asname or alias.name)

        def is_stub_ref(expr: ast.AST) -> Optional[str]:
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in attr_stubs
            ):
                return f"self.{expr.attr}"
            if isinstance(expr, ast.Name) and expr.id in name_stubs:
                return expr.id
            return None

        def is_urlopen(expr: ast.AST) -> bool:
            if isinstance(expr, ast.Name) and expr.id in urlopen_names:
                return True
            # ONLY urllib.request.urlopen(...) / request.urlopen(...) —
            # an arbitrary `pool.urlopen(...)` (urllib3 et al.) is out of
            # scope for this rule
            if not (
                isinstance(expr, ast.Attribute) and expr.attr == "urlopen"
            ):
                return False
            base = expr.value
            if isinstance(base, ast.Name):
                return base.id == "request"
            return (
                isinstance(base, ast.Attribute)
                and base.attr == "request"
                and isinstance(base.value, ast.Name)
                and base.value.id == "urllib"
            )

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            stub = is_stub_ref(node.func)
            kind = None
            if stub is not None:
                kind = f"stub:{stub}"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("future", "with_call")
                and is_stub_ref(node.func.value) is not None
            ):
                stub = is_stub_ref(node.func.value)
                kind = f"{node.func.attr}:{stub}"
            elif is_urlopen(node.func):
                kind = "urlopen"
            if kind is None or _has_timeout_kw(node):
                continue
            yield Finding(
                rule=self.id, path=mod.rel, line=node.lineno,
                col=node.col_offset + 1,
                message=(
                    f"unbounded RPC: {kind.split(':', 1)[-1]} is called "
                    "without an explicit timeout= — a black-holed peer "
                    "stalls this thread indefinitely (thread a deadline "
                    "budget through the call, utils.backoff.Deadline)"
                ),
                anchor=mod.qualname(node) or "<module>", detail=kind,
            )


# --------------------------------------------------------------------------
# GL009 — history series: sources must map to a metric family or span name
# --------------------------------------------------------------------------
#
# ISSUE 12 satellite: every per-wave history series (utils/history.py
# ``HistorySeries``) declares the surface backing it — ``metric:<family>``
# or ``span:<name>``. A series whose reference rots (family renamed, span
# retired) would keep rendering plausible zeros forever; this rule makes
# the reference machine-checked, the GL006/GL008 pattern: metric families
# are collected across the scanned import graph with GL006's receiver
# heuristic, span names resolve through the LIVE taxonomy matcher.


@rule
class HistorySeriesSource(Rule):
    id = "GL009"
    title = (
        "history series must source a registered metric family "
        "(metric:<family>) or a SPAN_NAMES entry (span:<name>)"
    )

    @staticmethod
    def _families(ctx: LintContext) -> set:
        if not hasattr(ctx, "_gl009_families"):
            ctx._gl009_families = set()
        return ctx._gl009_families

    @staticmethod
    def _series(ctx: LintContext) -> list:
        if not hasattr(ctx, "_gl009_series"):
            ctx._gl009_series = []
        return ctx._gl009_series

    def check(self, mod: ModuleInfo, ctx: LintContext) -> Iterator[Finding]:
        """Collection pass: metric-family definitions (the GL006
        registry-receiver heuristic) and ``HistorySeries(...)``
        constructions. Findings emit in ``finalize`` — resolution needs
        every scanned module's families first."""
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _METRIC_FACTORIES
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                recv = func.value
                recv_name = (
                    recv.id if isinstance(recv, ast.Name)
                    else recv.attr if isinstance(recv, ast.Attribute)
                    else None
                )
                if recv_name is not None and "reg" in recv_name.lower():
                    self._families(ctx).add(node.args[0].value)
                continue
            ctor = (
                func.id if isinstance(func, ast.Name)
                else func.attr if isinstance(func, ast.Attribute)
                else None
            )
            if ctor != "HistorySeries":
                continue
            name = source = None
            if node.args and isinstance(node.args[0], ast.Constant):
                name = node.args[0].value
            if len(node.args) >= 3 and isinstance(node.args[2], ast.Constant):
                source = node.args[2].value
            for kw in node.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    name = kw.value.value
                if kw.arg == "source" and isinstance(kw.value, ast.Constant):
                    source = kw.value.value
            if isinstance(source, str):
                self._series(ctx).append(
                    (mod, node, str(name or "?"), source)
                )
        return iter(())

    def finalize(self, ctx: LintContext) -> Iterator[Finding]:
        families = self._families(ctx)
        full_scope = getattr(ctx, "full_scope", True)
        for mod, node, name, source in self._series(ctx):
            kind, sep, ref = source.partition(":")
            if sep and kind == "span":
                if ctx.span_registered(ref):
                    continue
                message = (
                    f"history series {name!r} sources span {ref!r}, "
                    "which is not registered in utils.tracing "
                    "SPAN_NAMES — the sampler would aggregate a span "
                    "nothing records; register the span or fix the "
                    "reference"
                )
            elif sep and kind == "metric":
                if ref in families:
                    continue
                if not full_scope:
                    # a scoped scan (--changed-only/--paths) cannot see
                    # the whole registry, so it cannot prove "never
                    # registered" — the GL003 staleness precedent
                    continue
                message = (
                    f"history series {name!r} sources metric family "
                    f"{ref!r}, which no scanned registry defines — the "
                    "sampler would read a family nothing publishes; "
                    "register the family (utils/metrics.py) or fix the "
                    "reference"
                )
            else:
                message = (
                    f"history series {name!r} source {source!r} is "
                    "neither `metric:<family>` nor `span:<name>` — the "
                    "docs schema table and this rule key on that grammar"
                )
            yield Finding(
                rule=self.id, path=mod.rel, line=node.lineno,
                col=node.col_offset + 1, message=message,
                anchor=mod.qualname(node) or "<module>",
                detail=f"{name}:{source}",
            )


# --------------------------------------------------------------------------
# GL010 — reason taxonomy: emitted reason codes must be registered
# --------------------------------------------------------------------------
#
# ISSUE 13 satellite: the provenance plane (exclusion masks, the
# Scheduled=False breakdowns, karmada_tpu_unschedulable_total{reason},
# the generated docs reason table) all key on utils.reasons REASONS — a
# reason emitted outside the registry is invisible to every one of those
# surfaces and undocumented by construction. The GL008 pattern: literal
# emissions are checked statically (Condition(... reason="...") ctor
# calls and .inc(reason="...") metric labels); a reason passed as a
# plain variable is out of static reach and stays unchecked (resolution
# through module constants rides LintContext's constant table only for
# env vars — reason constants are covered by the tier-1 registry tests).


@rule
class ReasonTaxonomy(Rule):
    id = "GL010"
    title = (
        "reason codes emitted via Condition(reason=...) or "
        ".inc(reason=...) must be registered in utils.reasons REASONS"
    )

    def check(self, mod: ModuleInfo, ctx: LintContext) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            ctor = (
                func.id if isinstance(func, ast.Name)
                else func.attr if isinstance(func, ast.Attribute)
                else None
            )
            is_condition = ctor == "Condition"
            is_inc = (
                isinstance(func, ast.Attribute) and func.attr == "inc"
            )
            if not (is_condition or is_inc):
                continue
            for kw in node.keywords:
                if kw.arg != "reason":
                    continue
                if not (
                    isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)
                ):
                    continue  # dynamic reason: out of static reach
                code = kw.value.value
                if code in ctx.reasons_registry:
                    continue
                surface = "Condition" if is_condition else ".inc"
                yield Finding(
                    rule=self.id, path=mod.rel, line=node.lineno,
                    col=node.col_offset + 1,
                    message=(
                        f"reason code {code!r} ({surface} emission) is "
                        "not registered in utils.reasons REASONS — the "
                        "explain surface, the unschedulable metric "
                        "family and the generated docs reason table all "
                        "key on the taxonomy; register the code there"
                    ),
                    anchor=mod.qualname(node) or "<module>",
                    detail=code,
                )


# --------------------------------------------------------------------------
# GL011 — lock-READ discipline: guarded attrs must not be read lock-free
# --------------------------------------------------------------------------
#
# ISSUE 17 satellite: GL004 polices the WRITE side of lock discipline; a
# torn READ is the same bug from the other end — a thread that reads
# ``self._by_key`` while the writer mutates it mid-``with self._lock``
# sees a half-updated dict (or a RuntimeError from iterating a resizing
# one). An attr GL004 establishes as lock-guarded (mutated under the
# class's lock at least once) must be READ under that lock too, or the
# single-reader/snapshot invariant documented with a pragma. ``__init__``
# and ``__new__`` run before the object is shared, so their reads are the
# same single-writer window GL004 exempts.


@rule
class LockReadDiscipline(Rule):
    id = "GL011"
    title = "lock-guarded attributes must not be read lock-free"

    def check(self, mod: ModuleInfo, ctx: LintContext) -> Iterator[Finding]:
        for cls in ast.walk(mod.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(mod, cls)

    def _check_class(self, mod: ModuleInfo, cls: ast.ClassDef):
        lock_attrs = _class_lock_attrs(cls)
        guarded = _guarded_attrs(mod, cls)
        if not guarded:
            return
        for method in cls.body:
            if not isinstance(
                method, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if method.name in ("__init__", "__new__"):
                continue
            flagged: set = set()  # one finding per (method, attr)
            for node in ast.walk(method):
                if not (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                ):
                    continue
                attr = _self_attr(node)
                if attr not in guarded or attr in flagged:
                    continue
                parent = mod.parents.get(node)
                # writes are GL004's beat, not reads: self.x[k] = v /
                # del self.x[k] ...
                if isinstance(parent, ast.Subscript) and isinstance(
                    parent.ctx, (ast.Store, ast.Del)
                ):
                    continue
                # ... and so are in-place mutator calls (self.x.append(v))
                if (
                    isinstance(parent, ast.Attribute)
                    and parent.attr in MUTATORS
                    and isinstance(mod.parents.get(parent), ast.Call)
                    and mod.parents.get(parent).func is parent
                ):
                    continue
                if _under_lock(mod, cls, lock_attrs, node):
                    continue
                flagged.add(attr)
                yield Finding(
                    rule=self.id, path=mod.rel, line=node.lineno,
                    col=node.col_offset + 1,
                    message=(
                        f"self.{attr} is mutated under {cls.name}'s lock "
                        f"but read lock-free in {method.name}() — a "
                        "concurrent writer hands this read a half-updated "
                        "structure; take the lock (or snapshot under it), "
                        "or document the racy-read invariant with "
                        f"`# graftlint: disable={self.id}`"
                    ),
                    anchor=f"{mod.qualname(cls)}.{method.name}",
                    detail=attr, anchor_line=method.lineno,
                )


# --------------------------------------------------------------------------
# GL012 — budget construction: no Deadline/BackoffPolicy inside a loop
# --------------------------------------------------------------------------
#
# ISSUE 17 satellite: ``Deadline`` is ONE overall budget threaded through
# a multi-step call (utils/backoff.py's contract) — constructing it
# inside the retry loop resets the budget every iteration, so the loop
# it was meant to bound never times out as a whole. Same for
# ``BackoffPolicy``: a policy built per iteration restarts the
# decorrelated-jitter ladder at ``base`` every time, defeating the
# de-stampeding it exists for. Both must be hoisted above the loop; a
# deliberately per-item budget (iterating independent requests) is a
# pragma with the rationale attached.

_BUDGET_CTORS = {"Deadline", "BackoffPolicy"}


@rule
class BudgetConstructionInLoop(Rule):
    id = "GL012"
    title = (
        "Deadline/BackoffPolicy constructed inside a loop resets the "
        "budget every iteration"
    )

    def check(self, mod: ModuleInfo, ctx: LintContext) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (
                fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute)
                else None
            )
            if name not in _BUDGET_CTORS:
                continue
            # a loop between the call and its enclosing def means a
            # fresh budget per iteration; a def boundary resets the
            # search (a closure body is not lexically "in" the loop
            # that defines it — it runs when called)
            loop = None
            cur = mod.parents.get(node)
            while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                    loop = cur
                    break
                cur = mod.parents.get(cur)
            if loop is None:
                continue
            kind = "for" if isinstance(loop, (ast.For, ast.AsyncFor)) \
                else "while"
            yield Finding(
                rule=self.id, path=mod.rel, line=node.lineno,
                col=node.col_offset + 1,
                message=(
                    f"{name}(...) constructed inside a `{kind}` loop — "
                    "the budget/jitter ladder resets every iteration, so "
                    "the loop never times out (or never de-stampedes) as "
                    "a whole; hoist the construction above the loop and "
                    "thread the one instance through "
                    "(utils.backoff.call_with_resilience's contract)"
                ),
                anchor=mod.qualname(node) or "<module>",
                detail=f"{name}:{kind}",
            )


# --------------------------------------------------------------------------
# GL013 — bounded caches: grown hot-path containers need an eviction site
# --------------------------------------------------------------------------
#
# ISSUE 17 satellite: a dict/deque attribute on a long-lived worker,
# controller or registry object that only ever GROWS is a slow leak — in
# a control plane that runs for months, "per-key memo with no eviction"
# is an OOM with a delay fuse. The rule is structural: a container attr
# constructed unbounded (``{}``/``dict()``/``defaultdict(...)``/
# ``OrderedDict()``/``deque()`` with no ``maxlen=``) that some method
# outside ``__init__`` grows must have SOME shrink site anywhere in the
# class (``pop``/``popitem``/``popleft``/``clear``/``remove``/
# ``discard``/``del self.x[...]``/a reassignment that resets it).
# Bounded-by-construction tables (keyed by a static enum, the trace-
# ledger pattern) document the bound with a pragma. Scope: the
# long-lived-process dirs (``cache_dirs`` in the config) — a CLI helper
# that dies in seconds cannot leak for months.

_CACHE_FACTORIES = {"dict", "OrderedDict", "defaultdict", "deque",
                    "Counter"}
_GROWERS = {"append", "appendleft", "extend", "extendleft", "add",
            "setdefault", "update"}
_SHRINKERS = {"pop", "popitem", "popleft", "clear", "remove", "discard"}


def _unbounded_cache_attrs(cls: ast.ClassDef) -> dict:
    """attr -> construction line for self attrs built as unbounded
    dict/deque containers anywhere in the class."""
    out: dict = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        unbounded = False
        if isinstance(value, ast.Dict) and not value.keys:
            unbounded = True
        elif isinstance(value, ast.Call):
            fn = value.func
            factory = (
                fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute)
                else None
            )
            if factory in _CACHE_FACTORIES:
                capped = any(
                    kw.arg == "maxlen" and not (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value is None
                    )
                    for kw in value.keywords
                )
                # deque(iterable, maxlen) positional form
                if factory == "deque" and len(value.args) >= 2:
                    capped = True
                unbounded = not capped
        if not unbounded:
            continue
        for t in node.targets:
            attr = _self_attr(t)
            if attr:
                out.setdefault(attr, node.lineno)
    return out


@rule
class BoundedHotPathCaches(Rule):
    id = "GL013"
    title = (
        "hot-path dict/deque attrs that grow must have an eviction "
        "site or a maxlen cap"
    )

    def check(self, mod: ModuleInfo, ctx: LintContext) -> Iterator[Finding]:
        if ROLE_HOTPATH not in mod.roles:
            return
        for cls in ast.walk(mod.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(mod, cls)

    def _check_class(self, mod: ModuleInfo, cls: ast.ClassDef):
        caches = _unbounded_cache_attrs(cls)
        if not caches:
            return
        grow: dict = {}  # attr -> (node, method) first grow site
        shrinkable: set = set()
        for method in cls.body:
            if not isinstance(
                method, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            init = method.name in ("__init__", "__new__")
            for node in ast.walk(method):
                # self.x[k] = v / self.x[k] += v
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if isinstance(t, ast.Subscript):
                            attr = _self_attr(t.value)
                            if attr in caches and not init:
                                grow.setdefault(attr, (node, method))
                        else:
                            # a reassignment outside __init__ resets the
                            # container — that IS an eviction site
                            attr = _self_attr(t)
                            if attr in caches and not init:
                                shrinkable.add(attr)
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        base = t.value if isinstance(t, ast.Subscript) \
                            else t
                        attr = _self_attr(base)
                        if attr in caches:
                            shrinkable.add(attr)
                elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ):
                    attr = _self_attr(node.func.value)
                    if attr not in caches:
                        continue
                    if node.func.attr in _SHRINKERS:
                        shrinkable.add(attr)
                    elif node.func.attr in _GROWERS and not init:
                        grow.setdefault(attr, (node, method))
        for attr, (node, method) in sorted(grow.items()):
            if attr in shrinkable:
                continue
            yield Finding(
                rule=self.id, path=mod.rel, line=node.lineno,
                col=node.col_offset + 1,
                message=(
                    f"self.{attr} grows in {method.name}() but no method "
                    f"of {cls.name} ever shrinks it — on a long-lived "
                    "worker/controller this is an OOM with a delay fuse; "
                    "add an eviction path (pop/clear/TTL sweep), cap it "
                    "(deque(maxlen=...)), or document the structural "
                    "bound with "
                    f"`# graftlint: disable={self.id}`"
                ),
                anchor=f"{mod.qualname(cls)}.{method.name}",
                detail=attr, anchor_line=method.lineno,
            )
