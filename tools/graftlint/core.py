"""graftlint core: the visitor framework behind the GL00x analyzers.

A project-native static analyzer in the spirit of Karmada's golangci/vet
gates: the invariants the hot path lives or dies on (XLA trace discipline,
trace-key ledgering, env-flag registration, lock discipline, cold-start
import hygiene) become machine-checked rules that run in tier-1 instead of
surfacing as perf regressions after the fact.

Pieces:

- ``Finding`` — one diagnostic, with a STABLE identity (rule, path,
  anchor, detail) so baseline entries survive line-number drift.
- ``ModuleInfo`` — a parsed file: AST + parent map + role tags (which
  rules apply where) + suppression comments.
- ``LintContext`` — cross-module state (the env-flag registry, the
  module-level constant table GL003 resolves indirect keys through).
- ``Linter`` — walks files, runs every registered rule, applies inline
  suppressions (``# graftlint: disable=GL001``) and the committed
  baseline (``graftlint_baseline.json``), and returns a ``LintResult``.

Rules self-register via the ``@rule`` decorator (see rules.py).
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator, Optional

#: roles a module can carry; rules declare which roles they act on
ROLE_JIT = "jit"  # trace-safety scope (ops/, scheduler/, parallel/, refimpl/)
ROLE_LEDGER = "ledger"  # trace-key ledger scope (scheduler/)
ROLE_ENTRY = "entry"  # cold-start-sensitive entry module
ROLE_OPS = "ops"  # kernel layer: must not import the scheduler
ROLE_HOTPATH = "hotpath"  # long-lived worker/controller scope (GL013)

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?P<scope>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\s]+?)\s*(?:#|$)"
)


@dataclass(frozen=True)
class Finding:
    """One diagnostic. ``anchor`` (enclosing class.func qualname or a
    symbol) + ``detail`` (the offending name: env var, attribute, import)
    form the line-number-independent identity baseline entries match on."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    col: int
    message: str
    anchor: str = ""
    detail: str = ""
    #: line of the enclosing def/class — a suppression pragma there (or
    #: the line above it) silences the finding too (0 = unset)
    anchor_line: int = 0

    @property
    def identity(self) -> tuple:
        return (self.rule, self.path, self.anchor, self.detail)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "anchor": self.anchor,
            "detail": self.detail,
        }


@dataclass
class Config:
    """What the rules need to know about THIS repo's layout."""

    root: Path
    package: str = "karmada_tpu"
    env_prefix: str = "KARMADA_TPU_"
    #: package subdirs whose jitted functions get GL001 trace-safety checks
    jit_dirs: tuple = ("ops", "scheduler", "parallel", "refimpl", "models",
                      "estimator")
    #: package subdirs whose jit-kernel call sites must ledger trace keys
    ledger_dirs: tuple = ("scheduler",)
    #: the trace-key ledger helpers (FleetTable._mark_trace family)
    ledger_helpers: tuple = (
        "_mark_trace", "_mark_entries_trace", "_record_trace",
    )
    #: package-relative entry modules that must not import jax at module
    #: level (PR 1's cold-start win); every ``*/__main__.py`` is implied
    entry_modules: tuple = (
        "__init__.py", "cli.py", "localup.py", "controlplane.py",
        "bus/agent.py",
    )
    #: package subdirs hosting long-lived worker/controller/registry
    #: objects — GL013's unbounded-cache scope (a short-lived CLI helper
    #: cannot leak for months)
    cache_dirs: tuple = (
        "controllers", "bus", "scheduler", "estimator", "solver",
        "metricsadapter", "operator", "webhook",
    )
    flags_module: str = "karmada_tpu/utils/flags.py"
    docs_env_table: str = "docs/OPERATIONS.md"
    baseline_path: str = "graftlint_baseline.json"

    def roles_for(self, rel: str) -> set:
        """Role tags from a repo-relative posix path."""
        roles: set = set()
        prefix = self.package + "/"
        if not rel.startswith(prefix):
            return roles
        sub = rel[len(prefix):]
        top = sub.split("/", 1)[0]
        if top in self.jit_dirs:
            roles.add(ROLE_JIT)
        if top in self.ledger_dirs:
            roles.add(ROLE_LEDGER)
        if top == "ops":
            roles.add(ROLE_OPS)
        if top in self.cache_dirs:
            roles.add(ROLE_HOTPATH)
        if sub in self.entry_modules or sub.endswith("__main__.py"):
            roles.add(ROLE_ENTRY)
        return roles


@dataclass
class ModuleInfo:
    path: Path
    rel: str
    tree: ast.Module
    lines: list
    roles: set
    parents: dict = field(default_factory=dict)
    suppress_file: set = field(default_factory=set)
    suppress_line: dict = field(default_factory=dict)  # line -> set(rules)

    @classmethod
    def parse(cls, path: Path, rel: str, roles: set) -> "ModuleInfo":
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
        mod = cls(
            path=path, rel=rel, tree=tree,
            lines=source.splitlines(), roles=roles,
        )
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                mod.parents[child] = parent
        for i, line in enumerate(mod.lines, start=1):
            if "graftlint" not in line:
                continue
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
            if m.group("scope"):
                mod.suppress_file |= rules
            else:
                mod.suppress_line.setdefault(i, set()).update(rules)
        return mod

    def suppressed(self, rule: str, *lines: int) -> bool:
        """A finding is suppressed by a file-level pragma, or a line
        pragma on the flagged line, the line above it, or any anchor line
        the rule passed (typically the enclosing ``def``)."""
        if rule in self.suppress_file or "all" in self.suppress_file:
            return True
        for ln in lines:
            if ln <= 0:
                continue
            for candidate in (ln, ln - 1):
                marked = self.suppress_line.get(candidate, ())
                if rule in marked or "all" in marked:
                    return True
        return False

    def qualname(self, node: ast.AST) -> str:
        """Dotted class/function chain enclosing ``node`` (inclusive when
        node itself is a def/class); "" at module level."""
        parts: list = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts))


class LintContext:
    """Cross-module state shared by every rule invocation of one run."""

    def __init__(self, config: Config, modules: list):
        self.config = config
        self.modules = modules
        self._env_registry: Optional[dict] = None
        self._span_registry: Optional[dict] = None
        self._tracing_mod = None
        self._docs_text: Optional[str] = None
        # module-level NAME = "KARMADA_TPU_..." constants: GL003 resolves
        # os.environ.get(MANIFEST_ENV) through these. Per-module first
        # (same-named constants in different modules must not shadow each
        # other), then a cross-module fallback for imported constants
        # (from ..utils.compilecache import MANIFEST_ENV) — but only when
        # the identifier maps to ONE value repo-wide; ambiguous names
        # stay unresolved rather than misresolve.
        self._module_constants: dict = {}  # rel -> {name: value}
        global_values: dict = {}  # name -> set(values)
        for mod in modules:
            local: dict = {}
            for node in mod.tree.body:
                targets: list = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                    value = node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets = [node.target]
                    value = node.value
                else:
                    continue
                if not (
                    isinstance(value, ast.Constant)
                    and isinstance(value.value, str)
                    and value.value.startswith(config.env_prefix)
                ):
                    continue
                for t in targets:
                    if isinstance(t, ast.Name):
                        local[t.id] = value.value
                        global_values.setdefault(t.id, set()).add(value.value)
            self._module_constants[mod.rel] = local
        self._global_constants = {
            name: next(iter(values))
            for name, values in global_values.items()
            if len(values) == 1
        }

    def resolve_env_constant(self, mod: "ModuleInfo", ident: str):
        """The env-var name a bare identifier refers to in ``mod`` (None
        when unknown or ambiguous across modules)."""
        local = self._module_constants.get(mod.rel, {})
        if ident in local:
            return local[ident]
        return self._global_constants.get(ident)

    @property
    def env_registry(self) -> dict:
        """name -> EnvFlag from utils/flags.py (imported live: the
        registry IS code, so the linter can never drift from it)."""
        if self._env_registry is None:
            import importlib
            import sys

            root = str(self.config.root)
            if root not in sys.path:
                sys.path.insert(0, root)
            flags = importlib.import_module(
                self.config.package + ".utils.flags"
            )
            self._env_registry = dict(flags.ENV_FLAGS)
        return self._env_registry

    @property
    def _tracing_module(self):
        """``utils/tracing`` imported live (same pattern as env_registry —
        the module is stdlib-only, so the import stays jax-free). GL008's
        ground truth: both the registry dict AND the wildcard-matching
        semantics come from here, so the linter's notion of "registered"
        can never drift from the stitcher's."""
        if self._tracing_mod is None:
            import importlib
            import sys

            root = str(self.config.root)
            if root not in sys.path:
                sys.path.insert(0, root)
            self._tracing_mod = importlib.import_module(
                self.config.package + ".utils.tracing"
            )
        return self._tracing_mod

    @property
    def span_registry(self) -> dict:
        """name -> description from utils/tracing.py SPAN_NAMES."""
        if self._span_registry is None:
            self._span_registry = dict(self._tracing_module.SPAN_NAMES)
        return self._span_registry

    def span_registered(self, name: str) -> bool:
        """``name`` is in the taxonomy, directly or via a ``*`` family."""
        return self._tracing_module.span_name_registered(name)

    def span_family_registered(self, prefix: str) -> bool:
        """A dynamic (f-string) span name whose literal head is
        ``prefix`` resolves to a registered ``*`` family."""
        if not prefix:
            return False
        return any(
            prefix.startswith(k[:-1])
            for k in self.span_registry
            if k.endswith("*")
        )

    @property
    def reasons_registry(self) -> dict:
        """code -> Reason from utils/reasons.py REASONS (imported live,
        the env_registry/span_registry pattern — the module is
        stdlib-only, so the import stays jax-free). GL010's ground
        truth: the linter's notion of "registered" can never drift from
        the taxonomy the explain plane decodes with."""
        if getattr(self, "_reasons_registry", None) is None:
            import importlib
            import sys

            root = str(self.config.root)
            if root not in sys.path:
                sys.path.insert(0, root)
            reasons = importlib.import_module(
                self.config.package + ".utils.reasons"
            )
            self._reasons_registry = dict(reasons.REASONS)
        return self._reasons_registry

    @property
    def docs_text(self) -> str:
        if self._docs_text is None:
            path = self.config.root / self.config.docs_env_table
            self._docs_text = path.read_text() if path.exists() else ""
        return self._docs_text


class Rule:
    #: which analyzer tier the rule belongs to: "ast" rules walk parsed
    #: source modules (GL00x), "ir" rules walk traced kernel jaxprs
    #: (IR00x, see ir.py/irrules.py), "dep" rules consume the row-
    #: dependence analyses the dep tier computes over those same jaxprs
    #: (IR006+, see dep.py/deprules.py) — the registries are separate so
    #: the AST tier stays jax-free and sub-second
    kind = "ast"
    id = "GL000"
    title = ""

    def check(self, mod: ModuleInfo, ctx: LintContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finalize(self, ctx: LintContext) -> Iterator[Finding]:
        """Cross-module findings emitted after every file was checked."""
        return iter(())


RULES: dict = {}  # AST-tier analyzers (GL00x)
IR_RULES: dict = {}  # IR-tier analyzers (IR00x)
DEP_RULES: dict = {}  # dep-tier analyzers (row-dependence certification)


def rule(cls):
    """Register an analyzer class (decorator); the registry is chosen by
    ``cls.kind`` ("ast" default, "ir" for jaxpr-level analyzers, "dep"
    for the row-dependence certification tier)."""
    kind = getattr(cls, "kind", "ast")
    registry = {"ir": IR_RULES, "dep": DEP_RULES}.get(kind, RULES)
    registry[cls.id] = cls()
    return cls


@dataclass
class LintResult:
    findings: list  # non-suppressed, non-baselined — these fail the gate
    baselined: list  # matched a justified baseline entry
    suppressed_count: int
    checked_files: int
    baseline_errors: list  # malformed baseline entries (missing justification)
    unused_baseline: list  # baseline entries no finding matched

    @property
    def ok(self) -> bool:
        return not self.findings and not self.baseline_errors

    def to_json(self) -> dict:
        return {
            "ok": self.ok,
            "checked_files": self.checked_files,
            "findings": [f.to_json() for f in self.findings],
            "baselined": [f.to_json() for f in self.baselined],
            "suppressed": self.suppressed_count,
            "baseline_errors": self.baseline_errors,
            "unused_baseline": self.unused_baseline,
        }

    def render_text(self) -> str:
        out = [f.render() for f in self.findings]
        for err in self.baseline_errors:
            out.append(f"baseline: {err}")
        for ent in self.unused_baseline:
            out.append(
                "baseline: unused entry "
                f"{ent.get('rule')} {ent.get('path')} "
                f"anchor={ent.get('anchor', '')!r} — remove it"
            )
        tail = (
            f"{self.checked_files} files: {len(self.findings)} finding(s), "
            f"{len(self.baselined)} baselined, "
            f"{self.suppressed_count} suppressed"
        )
        out.append(tail)
        return "\n".join(out)


def load_baseline(path: Path) -> tuple:
    """Returns (entries, errors). An entry without a written justification
    is an ERROR, not a grandfather: the baseline exists to carry debt
    with a reason attached, never silently."""
    if not path.exists():
        return [], []
    data = json.loads(path.read_text())
    entries = data.get("entries", [])
    errors = []
    for ent in entries:
        just = (ent.get("justification") or "").strip()
        if not just or just.upper().startswith("TODO"):
            errors.append(
                f"entry {ent.get('rule')} {ent.get('path')} "
                f"anchor={ent.get('anchor', '')!r} has no written "
                "justification — fix the finding or justify it"
            )
    return entries, errors


def write_baseline(path: Path, findings: Iterable[Finding]) -> int:
    """Write the baseline for the CURRENT findings, carrying over the
    hand-written justification of any entry whose identity still matches
    — regenerating must never destroy a justification someone wrote.
    New entries get an EMPTY justification; the linter refuses them until
    a human writes the reason in."""
    previous, _ = load_baseline(path)
    carried: dict = {}
    for e in previous:
        key = (e.get("rule"), e.get("path"), e.get("anchor", ""),
               e.get("detail", ""))
        just = e.get("justification") or ""
        # several findings can share one identity (two reads of the same
        # env var in one function); a justified entry must not be
        # clobbered by an empty duplicate
        if just or key not in carried:
            carried[key] = just
    entries = [
        {
            "rule": f.rule,
            "path": f.path,
            "anchor": f.anchor,
            "detail": f.detail,
            "justification": carried.get(f.identity, ""),
        }
        for f in findings
    ]
    path.write_text(json.dumps({"version": 1, "entries": entries}, indent=2) + "\n")
    return len(entries)


def apply_baseline(
    raw: list,
    *,
    baseline: Optional[Path],
    checked_files: int,
    suppressed: int = 0,
) -> LintResult:
    """Split raw findings into gate-failing vs baselined and package the
    ``LintResult`` — the shared tail of BOTH analyzer tiers (the AST
    ``Linter`` and the IR auditor), so baseline identity semantics cannot
    drift between them."""
    entries, baseline_errors = (
        load_baseline(baseline) if baseline else ([], [])
    )
    by_identity = {
        (e.get("rule"), e.get("path"), e.get("anchor", ""),
         e.get("detail", "")): e
        for e in entries
    }
    matched: set = set()
    findings, baselined = [], []
    for f in raw:
        if f.identity in by_identity:
            matched.add(f.identity)
            baselined.append(f)
        else:
            findings.append(f)
    unused = [
        e for key, e in by_identity.items() if key not in matched
    ]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(
        findings=findings,
        baselined=baselined,
        suppressed_count=suppressed,
        checked_files=checked_files,
        baseline_errors=baseline_errors,
        unused_baseline=unused,
    )


def iter_py_files(root: Path, targets: Iterable[str]) -> Iterator[Path]:
    skip_parts = {"__pycache__", ".git", ".jax_cache", "graftlint_fixtures"}
    for target in targets:
        p = (root / target) if not Path(target).is_absolute() else Path(target)
        if p.is_file():
            if p.suffix == ".py":
                yield p
            continue
        for f in sorted(p.rglob("*.py")):
            if not skip_parts & set(f.parts):
                yield f


class Linter:
    def __init__(self, config: Config, rules: Optional[dict] = None):
        self.config = config
        self.rules = rules if rules is not None else RULES

    def parse(self, path: Path, roles: Optional[set] = None) -> ModuleInfo:
        try:
            rel = path.resolve().relative_to(self.config.root.resolve())
            rel_s = rel.as_posix()
        except ValueError:
            rel_s = path.as_posix()
        if roles is None:
            roles = self.config.roles_for(rel_s)
        return ModuleInfo.parse(path, rel_s, roles)

    def run(
        self,
        targets: Iterable[str],
        *,
        baseline: Optional[Path] = None,
        roles_override: Optional[dict] = None,
        full_scope: bool = True,
    ) -> LintResult:
        """Lint ``targets`` (files or directories, repo-relative or
        absolute). ``roles_override`` maps rel-path -> role set, used by
        the fixture tests to force a role onto an arbitrary file.
        ``full_scope=False`` marks a partial scan (--changed-only /
        explicit --paths): whole-tree negative claims like GL003's
        registered-but-never-read staleness check are skipped — a scoped
        run cannot prove "never read"."""
        modules = []
        for path in iter_py_files(self.config.root, targets):
            roles = None
            if roles_override:
                try:
                    rel = path.resolve().relative_to(
                        self.config.root.resolve()
                    ).as_posix()
                except ValueError:
                    rel = path.as_posix()
                if rel in roles_override:
                    roles = set(roles_override[rel])
            modules.append(self.parse(path, roles))
        ctx = LintContext(self.config, modules)
        ctx.full_scope = full_scope

        raw: list = []
        suppressed = 0
        for mod in modules:
            for r in self.rules.values():
                for finding in r.check(mod, ctx):
                    if mod.suppressed(
                        finding.rule, finding.line, finding.anchor_line
                    ):
                        suppressed += 1
                    else:
                        raw.append(finding)
        for r in self.rules.values():
            raw.extend(r.finalize(ctx))

        return apply_baseline(
            raw, baseline=baseline, checked_files=len(modules),
            suppressed=suppressed,
        )


def default_config(root: Optional[Path] = None) -> Config:
    if root is None:
        root = Path(__file__).resolve().parent.parent.parent
    return Config(root=Path(root))
