"""graftlint — two-tier static analyzer for this repo.

AST tier (core.py/rules.py): trace-safety & concurrency invariants over
Python source — pure ``ast``, no jax import, sub-second. IR tier
(ir.py/irrules.py): jaxpr-level kernel auditor — abstractly traces every
registered kernel entry point and machine-checks dtype, transfer,
const-capture, manifest-fidelity and donation invariants in the lowered
IR, where those bugs actually live.

Run it:

    python -m tools.graftlint                 # AST: karmada_tpu/ + tools/
    python -m tools.graftlint --changed-only  # AST: pre-commit scope
    python -m tools.graftlint --ir            # IR: the full kernel grid
    karmadactl-tpu lint [--ir]                # same, as a CLI verb

Rules: GL001 trace safety, GL002 trace-key completeness, GL003 env-flag
registry, GL004 lock discipline, GL005 cold-start import hygiene, GL006
metric naming & uniqueness; IR001
dtype discipline, IR002 host round-trips, IR003 const capture, IR004
trace-manifest fidelity, IR005 donation audit. Suppress per line with
``# graftlint: disable=GL00X`` (same line, line above, or the enclosing
``def`` line — the only form IR rules honor, anchored at the kernel's
``def``), per file with ``# graftlint: disable-file=GL00X``.
Grandfathered findings live in ``graftlint_baseline.json`` and MUST carry
a written justification; both tiers share that baseline.
"""

from . import irrules  # noqa: F401 — registers the IR00x analyzers
from . import rules  # noqa: F401 — registers the GL00x analyzers
from .core import (  # noqa: F401
    IR_RULES,
    RULES,
    Config,
    Finding,
    Linter,
    LintResult,
    default_config,
    load_baseline,
    write_baseline,
)

DEFAULT_TARGETS = ("karmada_tpu", "tools")


def run(
    targets=DEFAULT_TARGETS,
    *,
    root=None,
    baseline="auto",
    roles_override=None,
    full_scope=True,
) -> LintResult:
    """One-call API used by the CLI verb and the tier-1 test.

    ``baseline="auto"`` loads the repo's committed baseline; ``None``
    disables baselining (fixture tests want raw findings).
    ``full_scope=False`` marks a partial scan (--changed-only / explicit
    --paths): whole-tree negative checks (GL003 staleness) are skipped."""
    config = default_config(root)
    linter = Linter(config)
    baseline_path = None
    if baseline == "auto":
        baseline_path = config.root / config.baseline_path
    elif baseline:
        baseline_path = config.root / baseline
    return linter.run(
        targets, baseline=baseline_path, roles_override=roles_override,
        full_scope=full_scope,
    )


def run_ir(families=None, **kwargs):
    """IR-tier one-call API (lazy import: the tracing machinery needs
    jax; everything else in this package must not)."""
    from .ir import run_ir as _run_ir

    return _run_ir(families, **kwargs)
