"""graftlint — three-tier static analyzer for this repo.

AST tier (core.py/rules.py): trace-safety & concurrency invariants over
Python source — pure ``ast``, no jax import, sub-second. IR tier
(ir.py/irrules.py): jaxpr-level kernel auditor — abstractly traces every
registered kernel entry point and machine-checks dtype, transfer,
const-capture, manifest-fidelity and donation invariants in the lowered
IR, where those bugs actually live. Dep tier (dep.py/deprules.py):
abstract row-dependence propagation over those same jaxprs — certifies
every kernel's ``row_coupled`` declaration (the delta-safety contract
the incremental dirty-row solve will assert at arm time) and the
replicated-scan discipline in sharded variants.

Run it:

    python -m tools.graftlint                 # AST: karmada_tpu/ + tools/
    python -m tools.graftlint --changed-only  # AST, changed files only
    python -m tools.graftlint --all --changed-only  # pre-commit: all tiers
    python -m tools.graftlint --ir            # IR: the full kernel grid
    python -m tools.graftlint --dep           # dep: row-dependence certify
    python -m tools.graftlint --all           # AST + IR + dep, one gate
    karmadactl-tpu lint [--ir|--dep|--all]    # same, as a CLI verb

Rules: GL001 trace safety, GL002 trace-key completeness, GL003 env-flag
registry, GL004 lock discipline, GL005 cold-start import hygiene, GL006
metric naming & uniqueness, GL007 bounded RPCs, GL008 span taxonomy,
GL009 history series, GL010 reason taxonomy, GL011 lock-READ
discipline, GL012 budget-in-loop, GL013 bounded hot-path caches; IR001
dtype discipline, IR002 host round-trips, IR003 const capture, IR004
trace-manifest fidelity, IR005 donation audit; IR006 row-independence
certification, IR007 replicated-scan discipline. Suppress per line with
``# graftlint: disable=GL00X`` (same line, line above, or the enclosing
``def`` line — the only form IR/dep rules honor, anchored at the
kernel's ``def``), per file with ``# graftlint: disable-file=GL00X``.
Grandfathered findings live in ``graftlint_baseline.json`` and MUST
carry a written justification; all tiers share that baseline.
"""

from . import deprules  # noqa: F401 — registers the dep-tier analyzers
from . import irrules  # noqa: F401 — registers the IR00x analyzers
from . import rules  # noqa: F401 — registers the GL00x analyzers
from .core import (  # noqa: F401
    DEP_RULES,
    IR_RULES,
    RULES,
    Config,
    Finding,
    Linter,
    LintResult,
    default_config,
    load_baseline,
    write_baseline,
)

DEFAULT_TARGETS = ("karmada_tpu", "tools")


def run(
    targets=DEFAULT_TARGETS,
    *,
    root=None,
    baseline="auto",
    roles_override=None,
    full_scope=True,
) -> LintResult:
    """One-call API used by the CLI verb and the tier-1 test.

    ``baseline="auto"`` loads the repo's committed baseline; ``None``
    disables baselining (fixture tests want raw findings).
    ``full_scope=False`` marks a partial scan (--changed-only / explicit
    --paths): whole-tree negative checks (GL003 staleness) are skipped."""
    config = default_config(root)
    linter = Linter(config)
    baseline_path = None
    if baseline == "auto":
        baseline_path = config.root / config.baseline_path
    elif baseline:
        baseline_path = config.root / baseline
    return linter.run(
        targets, baseline=baseline_path, roles_override=roles_override,
        full_scope=full_scope,
    )


def run_ir(families=None, **kwargs):
    """IR-tier one-call API (lazy import: the tracing machinery needs
    jax; everything else in this package must not)."""
    from .ir import run_ir as _run_ir

    return _run_ir(families, **kwargs)


def run_dep(families=None, **kwargs):
    """Dep-tier one-call API (lazy import, the run_ir pattern)."""
    from .dep import run_dep as _run_dep

    return _run_dep(families, **kwargs)
