"""graftlint — AST-based trace-safety & concurrency analyzer for this repo.

Run it:

    python -m tools.graftlint                 # karmada_tpu/ + tools/
    python -m tools.graftlint path/to/file.py
    karmadactl-tpu lint --format json

Rules (see rules.py): GL001 trace safety, GL002 trace-key completeness,
GL003 env-flag registry, GL004 lock discipline, GL005 cold-start import
hygiene. Suppress per line with ``# graftlint: disable=GL00X`` (same line,
line above, or the enclosing ``def`` line for GL004), per file with
``# graftlint: disable-file=GL00X``. Grandfathered findings live in
``graftlint_baseline.json`` and MUST carry a written justification.
"""

from . import rules  # noqa: F401 — registers the GL00x analyzers
from .core import (  # noqa: F401
    RULES,
    Config,
    Finding,
    Linter,
    LintResult,
    default_config,
    load_baseline,
    write_baseline,
)

DEFAULT_TARGETS = ("karmada_tpu", "tools")


def run(
    targets=DEFAULT_TARGETS,
    *,
    root=None,
    baseline="auto",
    roles_override=None,
) -> LintResult:
    """One-call API used by the CLI verb and the tier-1 test.

    ``baseline="auto"`` loads the repo's committed baseline; ``None``
    disables baselining (fixture tests want raw findings)."""
    config = default_config(root)
    linter = Linter(config)
    baseline_path = None
    if baseline == "auto":
        baseline_path = config.root / config.baseline_path
    elif baseline:
        baseline_path = config.root / baseline
    return linter.run(
        targets, baseline=baseline_path, roles_override=roles_override
    )
