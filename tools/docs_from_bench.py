"""Regenerate the measured-numbers blocks in the docs from a bench record.

Usage: python tools/docs_from_bench.py BENCH_SELF_r05.json
       python tools/docs_from_bench.py --env-table

Rewrites the text between ``<!-- bench:begin -->`` / ``<!-- bench:end -->``
markers in docs/OPERATIONS.md and BASELINE.md from the JSON line bench.py
printed (either the raw line or the driver's ``{"parsed": ...}`` wrapper).
Round 4 shipped docs claiming ~10 s where the recorded JSON said 71.6 s
(VERDICT r4 weak #2); with this tool the prose can never drift from the
record again — regenerate, don't hand-edit.

The same contract covers the environment-variable table: the block between
``<!-- envflags:begin -->`` / ``<!-- envflags:end -->`` in
docs/OPERATIONS.md is generated from ``karmada_tpu.utils.flags.ENV_FLAGS``
(``--env-table`` rewrites it), and EVERY doc-regeneration run fails loudly
when the committed table has drifted from the registry — the docs half of
graftlint's GL003 gate.

Same drift-guard pattern for the kernel audit surface: every regeneration
run also fails loudly when a kernel family exported from
``karmada_tpu/ops/`` is missing from the graftlint IR entry-point registry
(``tools/graftlint/ir.py`` ENTRY_POINTS) — a kernel the IR tier cannot see
is a kernel whose dtype/transfer/capture invariants nothing proves.
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def fmt(v, unit="s") -> str:
    return "n/a" if v is None else f"{v:.2f} {unit}"


def block(d: dict) -> str:
    tiers = d.get("tiers", {})
    bad = {k: v for k, v in tiers.items() if v != "ok"}
    lines = [
        "| tier | measured |",
        "|---|---|",
        f"| 100k×5k steady storm p50 | {fmt(d.get('value'))} "
        f"({d.get('vs_cpp_native', 0):.0f}× the calibrated C++ -O2 "
        f"referent, {d.get('vs_numpy_host', 0):.0f}× vectorized numpy) |",
        f"| 100k×5k full-drift churn p50 / max | {fmt(d.get('churn_p50'))}"
        f" / {fmt(d.get('churn_max'))} |",
        f"| hetero 3500 uniques steady p50 | {fmt(d.get('hetero3500_p50'))} |",
        f"| hetero 9000 uniques steady p50 | {fmt(d.get('hetero9000_p50'))} |",
        f"| hetero 9000 slot-eviction churn p50 (10% unique rotation/pass) |"
        f" {fmt(d.get('hetero9k_churn_p50'))} |",
        f"| live-gRPC estimator tier (512 clusters, 4 server processes) "
        f"storm p50 | {fmt(d.get('estimator512_p50'))} (refresh "
        f"{fmt(d.get('estimator512_refresh_p50'))}, placements "
        + {True: "identical", False: "DIVERGED", None: "n/a"}[
            d.get("estimator512_identical")
        ]
        + " vs snapshot-fed) |",
        f"| 1M×5k steady p50 | {fmt(d.get('scale1m_steady_p50'))} |",
        f"| 1M×5k full-drift churn p50 / max | "
        f"{fmt(d.get('scale1m_churn_p50'))} / "
        f"{fmt(d.get('scale1m_churn_max'))} |",
        f"| 1M×5k legacy entry-resident steady p50 | "
        f"{fmt(d.get('scale1m_legacy_p50'))} |",
    ]
    wp = d.get("whole_plane_bindings_s")
    if wp is not None:
        lines.append(
            f"| whole-plane storm (detector→scheduler→binding→works) | "
            f"{wp:,.0f} bindings/s |"
        )
    lines.append(
        f"| verification | {d.get('verified_rows', 0):,} oracle-verified "
        f"rows, {d.get('verified_mismatches', 0)} mismatches |"
    )
    if bad:
        lines.append(f"| FAILED tiers this run | {sorted(bad)} |")
    return "\n".join(lines)


def cold_block(cd: dict) -> str:
    """Rows for a ``bench.py --cold-start`` record (the plane-restart
    first-wave tier): cold = cache+manifest disabled, restore = manifest
    prewarm + persistent compile cache."""
    scale = cd.get("metric", "").removeprefix("cold_start_first_wave_")
    warm = cd.get("restore_new_trace_first_pass")
    return "\n".join(
        [
            f"| cold-start {scale}: first wave, no cache (pre-subsystem "
            f"restart) | {fmt(cd.get('cold_first_wave_s'))} "
            f"({cd.get('cold_over_warm', 0):.1f}× the warm all-change "
            f"wave) |",
            f"| cold-start {scale}: first wave, cached + manifest-prewarmed "
            f"restart | {fmt(cd.get('restore_first_wave_s'))} "
            f"({cd.get('restore_over_warm', 0):.2f}× warm, "
            f"{cd.get('vs_baseline', 0):.1f}× faster than cold, first pass "
            f"new_trace={'False' if warm is False else warm}) |",
        ]
    )


def estimator_block(ed: dict) -> str:
    """Rows for a ``bench.py --estimator-only`` record (the batched
    estimator wire tier): full-refresh storm over one batch RPC per
    server, generation-ping no-movement refresh, and the unary-fallback
    parity run with its width-1 (blocking sequential) reference."""
    scale = ed.get("metric", "").removeprefix("estimator512_wire_")

    def rpcs(key):
        d = ed.get(key) or {}
        parts = [
            f"{d.get(k, 0)} {k}" for k in ("batch", "unary", "ping")
            if d.get(k)
        ]
        return " + ".join(parts) if parts else "0"

    ident = {True: "identical", False: "DIVERGED", None: "n/a"}
    return "\n".join(
        [
            f"| estimator wire {scale}: full-refresh storm p50 (batched "
            f"protocol) | {fmt(ed.get('estimator512_p50'))} (RPCs/pass: "
            f"{rpcs('estimator512_rpc_full')}; placements "
            f"{ident[ed.get('estimator512_identical')]} vs snapshot-fed) |",
            f"| estimator wire {scale}: no-movement refresh pass "
            f"(generation pings only) | "
            f"{fmt(ed.get('estimator512_refresh_p50'))} (RPCs/pass: "
            f"{rpcs('estimator512_rpc_steady')}) |",
            f"| estimator wire {scale}: unary-fallback full refresh "
            f"(mixed-version path, pipelined) | "
            f"{fmt(ed.get('estimator512_fallback_p50'))} (RPCs/pass: "
            f"{rpcs('estimator512_rpc_fallback')}; placements "
            f"{ident[ed.get('estimator512_fallback_identical')]}; "
            f"blocking-sequential reference "
            f"{fmt(ed.get('estimator512_fallback_seq_s'))}) |",
        ]
    )


def obs_block(od: dict) -> str:
    """Rows for a ``bench.py --observability`` record (the wave-trace
    attribution tier): coverage of the measured wall clock, the kernel
    compile/device/host split, and the heaviest wave phases."""
    scale = od.get("metric", "").removeprefix("observability_wave_")
    cov = od.get("coverage_vs_wall", 0.0)
    phases = od.get("phases", {}) or {}
    top = sorted(phases.items(), key=lambda kv: -kv[1])[:5]
    top_s = ", ".join(f"{k} {v:.2f}s" for k, v in top)
    compiles = od.get("kernel_compiles", {}) or {}
    comp_s = (
        ", ".join(f"{k} x{int(v)}" for k, v in sorted(compiles.items()))
        or "none"
    )
    rows = [
        f"| observability {scale}: storm wave wall / span coverage | "
        f"{fmt(od.get('value'))} wall, {cov * 100:.1f}% attributed to "
        f"named spans ({od.get('bindings_s', 0):,.0f} bindings/s, "
        f"{od.get('works', 0):,} works) |",
        f"| observability {scale}: kernel span split | "
        f"host(pack/decode) {phases.get('kernel.host', 0.0):.2f}s, "
        f"dispatch {phases.get('kernel.dispatch', 0.0):.2f}s (sync "
        f"backends execute inside it), device-fence "
        f"{phases.get('kernel.device', 0.0):.2f}s, fetch "
        f"{phases.get('kernel.fetch', 0.0):.2f}s; compile-bearing "
        f"{od.get('compile_s', 0.0):.2f}s |",
        f"| observability {scale}: heaviest wave phases (self time) | "
        f"{top_s} |",
        f"| observability {scale}: serving-path kernel compiles "
        f"(whole run) | {comp_s} |",
    ]
    # ISSUE 12: device-byte ledger columns + the history-backed wave
    # table summary
    dev = od.get("device_bytes") or {}
    if dev:
        dev_s = ", ".join(
            f"{k} {v / 1e6:.2f} MB" for k, v in sorted(dev.items())
        )
        const = {True: "constant", False: "MOVED"}[
            bool(od.get("device_bytes_steady_constant"))
        ]
        rows.append(
            f"| observability {scale}: resident device bytes "
            f"({od.get('device_bytes_platform', '?')} buffers; exact "
            f"nbytes of the held arrays) | {dev_s} — total "
            f"{od.get('device_bytes_total', 0) / 1e6:.2f} MB, {const} "
            f"across steady passes, gauge-ledger sum matches="
            f"{bool(od.get('device_bytes_matches_gauge'))} |"
        )
    hist = od.get("history_digests") or {}
    if hist:
        bits = []
        for key, label in (
            ("wall_s", "wall"),
            ("bindings_s", "bindings/s"),
            ("rows_packed", "rows packed"),
            ("rows_replayed", "rows replayed"),
        ):
            d = hist.get(key)
            if d:
                bits.append(
                    f"{label} p50 {d['p50']:g} / p95 {d['p95']:g}"
                )
        rows.append(
            f"| observability {scale}: per-wave history ring "
            f"({od.get('history_waves', 0)} waves sampled) | "
            f"{'; '.join(bits) or 'n/a'} |"
        )
    # ISSUE 10: the 4-process stitched wave (plane + solver sidecar +
    # estimator server + bus) with per-process and per-channel columns,
    # and the flight-recorder proof
    st = od.get("stitched")
    if st:
        proc_s = ", ".join(
            f"{k} {v:.2f}s"
            for k, v in sorted(
                (st.get("process_s") or {}).items(), key=lambda kv: -kv[1]
            )
        )
        chan_s = "; ".join(
            f"{k}: {v.get('rpcs', 0)} rpcs ({v.get('events_per_rpc', 1.0):g}"
            f" ev/msg), client {v.get('client_s', 0.0):.2f}s"
            f" = server {v.get('server_s', 0.0):.2f}s + network "
            f"{v.get('network_s', 0.0):.2f}s"
            for k, v in sorted((st.get("channels") or {}).items())
        )
        rows += [
            f"| observability {scale}: stitched 4-process wave "
            f"({', '.join(st.get('procs', []))}) | "
            f"{fmt(od.get('stitched_wall_s'))} wall, "
            f"{od.get('stitched_coverage_vs_wall', 0.0) * 100:.1f}% "
            f"attributed across processes ({st.get('spans', 0)} spans) |",
            f"| observability {scale}: per-process self time | "
            f"{proc_s or 'n/a'} |",
            f"| observability {scale}: per-channel columns "
            f"(client = server + network/serialization) | "
            f"{chan_s or 'n/a'} |",
            f"| observability {scale}: flight recorder (seeded breaker "
            f"trip mid-wave) | record written="
            f"{bool(od.get('flight_recorded'))}, reasons "
            f"{od.get('flight_reasons', [])}, `trace analyze` re-derives "
            f"identically={od.get('flight_analyze_identical')} |",
        ]
    # ISSUE 13: the provenance-plane rows — armed-vs-disarmed storm
    # overhead (benchguard-guarded), capture sizes, and the live
    # denied-binding + flight-record "why" proofs
    if od.get("explain_overhead_x") is not None:
        resolved = {True: "resolved", False: "UNRESOLVED"}[
            bool(od.get("explain_resolved"))
        ]
        flight = {True: "identical", False: "DIVERGED", None: "n/a"}[
            od.get("explain_flight_identical")
        ]
        rows += [
            f"| explain {scale}: armed vs disarmed storm wave | "
            f"{fmt(od.get('explain_armed_wave_s'))} armed vs "
            f"{fmt(od.get('explain_disarmed_wave_s'))} disarmed — "
            f"{od.get('explain_overhead_x', 0):.3f}x (within the "
            f"benchguard noise band; disarmed = one `is None` check) |",
            f"| explain {scale}: capture sizes | "
            f"{od.get('explain_capture_bindings', 0):,} bindings over "
            f"{od.get('explain_captures', 0)} capture(s), "
            f"{od.get('explain_capture_bytes', 0) / 1e6:.2f} MB interned "
            f"({od.get('explain_unique_masks', 0)} unique mask rows) |",
            f"| explain {scale}: decision chains | live denied binding "
            f"{resolved} via `karmadactl-tpu explain` "
            f"(stage={od.get('explain_denied_stage', '?')}); flight "
            f"record carries worst-binding explanations, `trace "
            f"analyze` re-renders {flight} |",
        ]
    # ISSUE 11: the columnar bus channel rows — storm throughput over
    # the live 4-process bus, the unary re-run ratio, the top stitched
    # self-time phase (bus.rpc must no longer lead), and the batched↔
    # unary plane-state parity verdict
    if od.get("bus_parity_identical") is not None:
        parity = {True: "IDENTICAL", False: "DIVERGED"}[
            bool(od.get("bus_parity_identical"))
        ]
        n_st = od.get("stitched_bindings", 0)
        rows += [
            f"| bus channel {n_st}x{od.get('stitched_clusters', 0)} "
            f"(4-process storm): batched vs unary wall | "
            f"{fmt(od.get('stitched_wall_s'))} batched "
            f"({od.get('stitched_bindings_s', 0):,.0f} bindings/s) vs "
            f"{fmt(od.get('bus_unary_wall_s'))} unary write path — "
            f"{od.get('bus_unary_vs_batched', 0):g}x |",
            f"| bus channel: top stitched self-time phase | "
            f"{od.get('bus_top_self_phase', '?')} "
            f"{od.get('bus_top_self_phase_s', 0.0):.2f}s |",
            f"| bus channel: template-delta rendering | "
            f"{od.get('bus_template_delta_works', 0):,} delta Works over "
            f"{od.get('bus_templates', 0):,} content-addressed templates |",
            f"| bus channel: plane state batched vs unary "
            f"(placements + rehydrated manifests) | {parity} |",
        ]
    return "\n".join(rows)


def chaos_block(cd: dict) -> str:
    """Rows for a ``bench.py --chaos`` record (the chaos-failover tier):
    time-to-stable-placement after the seeded kill wave, the displaced-
    binding count against the batched-solve count, the oracle-parity
    flag, and the breaker's degraded/recovery story."""
    scale = cd.get("metric", "").removeprefix("chaos_storm_")
    parity = {True: "IDENTICAL", False: "DIVERGED"}[
        bool(cd.get("oracle_identical"))
    ]
    degraded = cd.get("degraded_storm_s") or []
    degraded_s = ", ".join(f"{s:.1f}s" for s in degraded) or "n/a"
    return "\n".join(
        [
            f"| chaos {scale}: kill {len(cd.get('killed_clusters', []))} "
            f"clusters + partition 1 estimator server mid-wave → stable "
            f"placement | {fmt(cd.get('time_to_stable_s'))} "
            f"(steady storm p50 disarmed "
            f"{fmt(cd.get('steady_p50_disarmed_s'))}) |",
            f"| chaos {scale}: displaced bindings / batched solves | "
            f"{cd.get('displaced_bindings', 0):,} displaced rescheduled "
            f"in {cd.get('solves_failover_wave', 0)} batched solve(s) — "
            f"ordered ClusterAffinities fallback as one tensorized pass, "
            f"not per-binding Python |",
            f"| chaos {scale}: oracle parity (numpy per-binding replay of "
            f"the seeded event log, seed {cd.get('chaos_seed')}) | "
            f"{parity} ({cd.get('oracle_mismatches', 0)} mismatches, "
            f"{cd.get('replay_events', 0)} logged fault events) |",
            f"| chaos {scale}: estimator channel degraded mode | breaker "
            f"open observed={cd.get('breaker_open_observed')}, degraded "
            f"storms {degraded_s}, "
            f"{cd.get('degraded_estimator_passes', 0)} degraded passes "
            f"(never replay-armed), recovered half-open→closed without "
            f"operator action={cd.get('breaker_recovered_closed')} |",
        ]
    )


def quota_block(qd: dict) -> str:
    """Rows for a ``bench.py --quota`` record (the quota-enforcement
    tier): the CronFederatedHPA surge against tightened namespace quotas,
    the oracle-parity flags for admission AND placements, the
    enforcement-overhead bound against quota-disabled storms, and the
    raise-without-re-pack proof."""
    scale = qd.get("metric", "").removeprefix("quota_surge_")
    adm = {True: "IDENTICAL", False: "DIVERGED"}[
        bool(qd.get("admission_identical"))
    ]
    plc = {True: "IDENTICAL", False: "DIVERGED"}[
        bool(qd.get("placements_identical"))
    ]
    return "\n".join(
        [
            f"| quota {scale}: CronFederatedHPA surge "
            f"({qd.get('surged_bindings', 0):,} bindings rescaling into "
            f"{qd.get('quota_namespaces', 0)} quota'd namespaces, "
            f"{qd.get('capped_namespaces', 0)} with static caps) | "
            f"{fmt(qd.get('surge_wave_s'))} wave, "
            f"{qd.get('surge_solves', 0)} batched solve(s) — "
            f"{qd.get('scaled_bindings', 0):,} scaled, "
            f"{qd.get('denied_bindings', 0):,} denied QuotaExceeded |",
            f"| quota {scale}: oracle parity (sequential numpy admission "
            f"+ per-pass divider replay) | admission {adm} "
            f"({qd.get('admission_checked', 0):,} decisions), placements "
            f"{plc} ({qd.get('placements_checked', 0):,} rows) |",
            f"| quota {scale}: enforcement overhead on steady storms | "
            f"wall enforced {fmt(qd.get('steady_p50_enforced_s'))} vs "
            f"disabled {fmt(qd.get('steady_p50_disabled_s'))} "
            f"({qd.get('enforcement_overhead_x', 0):.3f}×); engine "
            f"schedule {fmt(qd.get('steady_sched_enforced_s'))} vs "
            f"{fmt(qd.get('steady_sched_disabled_s'))} "
            f"({qd.get('sched_overhead_x', 0):.3f}×) |",
            f"| quota {scale}: quota raise clears denials without a "
            f"re-pack | namespace {qd.get('raise_namespace')}: cleared "
            f"all={qd.get('raise_cleared_all')} in "
            f"{qd.get('raise_solves')} batched solve(s) |",
        ]
    )


def preempt_block(pd: dict) -> str:
    """Rows for a ``bench.py --preemption`` record (the scarcity tier):
    the high-priority surge against an exactly-saturated fleet with the
    victim/placement oracle-parity flags, the batched-solve shape, the
    armed-vs-disarmed steady-storm bound, and the bounded-disruption
    drift round."""
    scale = pd.get("metric", "").removeprefix("preempt_storm_")
    vic = {True: "IDENTICAL", False: "DIVERGED"}[
        bool(pd.get("victims_identical"))
    ]
    plc = {True: "IDENTICAL", False: "DIVERGED"}[
        bool(pd.get("placements_identical"))
    ]
    return "\n".join(
        [
            f"| preempt {scale}: high-priority surge on a saturated "
            f"fleet ({pd.get('surged_bindings', 0):,} priority-100 "
            f"bindings, zero free capacity) | "
            f"{fmt(pd.get('surge_wave_s'))} to stable, "
            f"{pd.get('victims_evicted', 0):,} victims evicted in "
            f"{pd.get('preemption_passes', 0)} preemption pass(es), "
            f"{pd.get('surge_solves', 0)} batched solves over "
            f"{pd.get('surge_engine_passes', 0)} engine passes |",
            f"| preempt {scale}: oracle parity (sequential numpy victim "
            f"selection + boosted per-binding divides) | victims {vic} "
            f"({pd.get('victims_checked', 0):,} rows), demander "
            f"placements {plc} ({pd.get('placements_checked', 0):,} "
            f"rows) |",
            f"| preempt {scale}: arming overhead on steady storms | "
            f"wall armed {fmt(pd.get('steady_p50_armed_s'))} vs "
            f"disarmed {fmt(pd.get('steady_p50_disarmed_s'))}; engine "
            f"schedule {fmt(pd.get('steady_sched_armed_s'))} vs "
            f"{fmt(pd.get('steady_sched_disarmed_s'))} "
            f"({pd.get('preempt_overhead_x', 0):.3f}×) |",
            f"| preempt {scale}: continuous-descheduler drift round | "
            f"{pd.get('drift_drifted', 0):,} of "
            f"{pd.get('drift_scored', 0):,} residents drifted; "
            f"{pd.get('drift_triggered', 0)}/{pd.get('drift_budget', 0)} "
            f"triggered (budget exact={pd.get('drift_budget_exact')}, "
            f"oracle identical={pd.get('drift_oracle_identical')}), "
            f"{pd.get('drift_replaced', 0)} re-placed in "
            f"{fmt(pd.get('drift_round_s'))} |",
        ]
    )


def multichip_block(md: dict) -> str:
    """Rows for a ``bench.py --multichip`` record (the sharded-engine
    tier): per-mesh steady p50 with the placement-identity flags, the
    donation (buffer-reuse) proof, and the steady-pass transfer bound
    against the full packed-grid upload."""
    scale = md.get("metric", "").removeprefix("multichip_scaling_")
    sizes = [str(s) for s in md.get("mesh_sizes", [])]
    p50 = md.get("steady_p50_s", {}) or {}
    ident = md.get("identical", {}) or {}
    don = md.get("donated", {}) or {}
    up = md.get("steady_upload_mb", {}) or {}
    curve = ", ".join(f"mesh {m}: {p50.get(m, 0.0):.2f}s" for m in sizes)
    ident_ok = all(ident.get(m) for m in sizes)
    don_ok = all(don.get(m) for m in sizes)
    max_up = max((up.get(m, 0.0) for m in sizes), default=0.0)
    full = md.get("full_grid_upload_mb", 0.0) or 0.0
    cpu_rig = md.get("platform") == "cpu"
    dev_kind = "forced host" if cpu_rig else "real"
    curve_note = (
        "virtual devices share one CPU, so the curve proves "
        "identity/transfer, not speedup"
        if cpu_rig
        else "real devices: the curve is a genuine scaling measurement"
    )
    return "\n".join(
        [
            f"| multichip {scale}: steady storm p50 across mesh sizes "
            f"({md.get('platform')}, {md.get('devices')} {dev_kind} "
            f"devices) | {curve} — placements "
            f"{'bit-identical' if ident_ok else 'DIVERGED'} across sizes; "
            f"{curve_note} |",
            f"| multichip {scale}: donated persistent residents | "
            f"pre-pass packed-state buffers consumed in place across "
            f"every mesh size: {'YES' if don_ok else 'NO'} (runtime "
            f"buffer-reuse probe; graftlint IR005 proves it statically) |",
            f"| multichip {scale}: steady-pass host→device upload | "
            f"{max_up:.4f} MB/pass vs {full:.2f} MB full packed-grid "
            f"upload ({(max_up / full * 100) if full else 0:.2f}%) |",
        ]
    )


def extra_block(src: Path) -> str:
    """Dispatch an extra record file by its metric prefix."""
    d = json.loads(src.read_text())
    if "parsed" in d:
        d = d["parsed"]
    metric = d.get("metric", "")
    if metric.startswith("cold_start"):
        return cold_block(d)
    if metric.startswith("estimator512_wire"):
        return estimator_block(d)
    if metric.startswith("observability_wave"):
        return obs_block(d)
    if metric.startswith("chaos_storm"):
        return chaos_block(d)
    if metric.startswith("quota_surge"):
        return quota_block(d)
    if metric.startswith("preempt_storm"):
        return preempt_block(d)
    if metric.startswith("multichip_scaling"):
        return multichip_block(d)
    raise SystemExit(f"{src}: unrecognized bench record metric {metric!r}")


def rewrite(path: Path, body: str, marker: str = "bench") -> None:
    text = path.read_text()
    pat = _marker_re(marker)
    if not pat.search(text):
        raise SystemExit(f"{path}: no {marker} markers")
    text = pat.sub(lambda m: m.group(1) + body + "\n" + m.group(2), text)
    path.write_text(text)
    print(f"rewrote {path} [{marker}]")


def _marker_re(marker: str) -> "re.Pattern":
    return re.compile(
        rf"(<!-- {marker}:begin[^>]*-->\n).*?(<!-- {marker}:end -->)", re.S
    )


def env_table() -> str:
    """The generated env-var table (karmada_tpu.utils.flags is the single
    source of truth; graftlint GL003 keeps the READ sites honest)."""
    sys.path.insert(0, str(ROOT))
    from karmada_tpu.utils.flags import render_env_table

    return (
        "_Generated from `karmada_tpu/utils/flags.py` ENV_FLAGS by "
        "`tools/docs_from_bench.py --env-table` — regenerate, don't "
        "hand-edit._\n\n" + render_env_table()
    )


def check_env_table() -> None:
    """Fail loudly when the committed OPERATIONS.md env table drifted from
    the flags registry — runs on EVERY doc regeneration."""
    path = ROOT / "docs" / "OPERATIONS.md"
    m = _marker_re("envflags").search(path.read_text())
    if not m:
        raise SystemExit(
            f"{path}: no envflags markers — restore the Environment "
            "variables section and run "
            "`python tools/docs_from_bench.py --env-table`"
        )
    committed_body = m.group(0).split("-->\n", 1)[1].rsplit("<!--", 1)[0]
    if committed_body.strip() != env_table().strip():
        raise SystemExit(
            f"{path}: env table drifted from karmada_tpu/utils/flags.py "
            "ENV_FLAGS — run `python tools/docs_from_bench.py --env-table`"
        )


def metrics_table() -> str:
    """The generated metric-families table (karmada_tpu.utils.metrics
    ``registry`` is the single source of truth; graftlint GL006 keeps the
    names prefixed and unique)."""
    sys.path.insert(0, str(ROOT))
    from karmada_tpu.utils.metrics import render_families_table

    return (
        "_Generated from the `karmada_tpu/utils/metrics.py` registry by "
        "`tools/docs_from_bench.py --metrics-table` — regenerate, don't "
        "hand-edit._\n\n" + render_families_table()
    )


def check_metrics_table() -> None:
    """Fail loudly when the committed OPERATIONS.md metric-families table
    drifted from the live registry (a family the table misses is a family
    operators won't know to scrape) — runs on EVERY doc regeneration,
    same pattern as the env-flag gate."""
    path = ROOT / "docs" / "OPERATIONS.md"
    m = _marker_re("metricfamilies").search(path.read_text())
    if not m:
        raise SystemExit(
            f"{path}: no metricfamilies markers — restore the "
            "Observability metric-families section and run "
            "`python tools/docs_from_bench.py --metrics-table`"
        )
    committed_body = m.group(0).split("-->\n", 1)[1].rsplit("<!--", 1)[0]
    if committed_body.strip() != metrics_table().strip():
        raise SystemExit(
            f"{path}: metric-families table drifted from "
            "karmada_tpu/utils/metrics.py registry — run "
            "`python tools/docs_from_bench.py --metrics-table`"
        )


def span_table() -> str:
    """The generated span-taxonomy table (karmada_tpu.utils.tracing
    SPAN_NAMES is the single source of truth; graftlint GL008 keeps the
    recording sites honest)."""
    sys.path.insert(0, str(ROOT))
    from karmada_tpu.utils.tracing import render_span_table

    return (
        "_Generated from `karmada_tpu/utils/tracing.py` SPAN_NAMES by "
        "`tools/docs_from_bench.py --span-table` — regenerate, don't "
        "hand-edit._\n\n" + render_span_table()
    )


def check_span_table() -> None:
    """Fail loudly when the committed OPERATIONS.md span-taxonomy table
    drifted from the SPAN_NAMES registry (a span the table misses is a
    span operators can't read in a dumped wave) — runs on EVERY doc
    regeneration, same pattern as the env-flag gate."""
    path = ROOT / "docs" / "OPERATIONS.md"
    m = _marker_re("spantaxonomy").search(path.read_text())
    if not m:
        raise SystemExit(
            f"{path}: no spantaxonomy markers — restore the span-taxonomy "
            "section and run `python tools/docs_from_bench.py "
            "--span-table`"
        )
    committed_body = m.group(0).split("-->\n", 1)[1].rsplit("<!--", 1)[0]
    if committed_body.strip() != span_table().strip():
        raise SystemExit(
            f"{path}: span-taxonomy table drifted from "
            "karmada_tpu/utils/tracing.py SPAN_NAMES — run "
            "`python tools/docs_from_bench.py --span-table`"
        )


def history_table() -> str:
    """The generated wave-row schema table (karmada_tpu.utils.history
    ``HISTORY_SERIES`` is the single source of truth; graftlint GL009
    keeps each series' source reference honest)."""
    sys.path.insert(0, str(ROOT))
    from karmada_tpu.utils.history import render_history_schema_table

    return (
        "_Generated from `karmada_tpu/utils/history.py` HISTORY_SERIES "
        "by `tools/docs_from_bench.py --history-table` — regenerate, "
        "don't hand-edit._\n\n" + render_history_schema_table()
    )


def check_history_schema() -> None:
    """Fail loudly when the committed OPERATIONS.md wave-row schema
    table drifted from the HISTORY_SERIES registry (a series the table
    misses is a series operators can't read off /debug/history) — runs
    on EVERY doc regeneration, same pattern as the env-flag gate."""
    path = ROOT / "docs" / "OPERATIONS.md"
    m = _marker_re("historyschema").search(path.read_text())
    if not m:
        raise SystemExit(
            f"{path}: no historyschema markers — restore the Telemetry "
            "history section and run `python tools/docs_from_bench.py "
            "--history-table`"
        )
    committed_body = m.group(0).split("-->\n", 1)[1].rsplit("<!--", 1)[0]
    if committed_body.strip() != history_table().strip():
        raise SystemExit(
            f"{path}: wave-row schema table drifted from "
            "karmada_tpu/utils/history.py HISTORY_SERIES — run "
            "`python tools/docs_from_bench.py --history-table`"
        )


def reasons_table() -> str:
    """The generated reason-taxonomy table (karmada_tpu.utils.reasons
    ``REASONS`` is the single source of truth; graftlint GL010 keeps the
    emission sites honest)."""
    sys.path.insert(0, str(ROOT))
    from karmada_tpu.utils.reasons import render_reasons_table

    return (
        "_Generated from `karmada_tpu/utils/reasons.py` REASONS by "
        "`tools/docs_from_bench.py --reasons-table` — regenerate, don't "
        "hand-edit._\n\n" + render_reasons_table()
    )


def check_reasons_table() -> None:
    """Fail loudly when the committed OPERATIONS.md reason-taxonomy
    table drifted from the REASONS registry (a reason the table misses
    is a reason operators can't decode off /debug/explain) — runs on
    EVERY doc regeneration, same pattern as the env-flag gate."""
    path = ROOT / "docs" / "OPERATIONS.md"
    m = _marker_re("reasontaxonomy").search(path.read_text())
    if not m:
        raise SystemExit(
            f"{path}: no reasontaxonomy markers — restore the Explaining "
            "placements section and run `python tools/docs_from_bench.py "
            "--reasons-table`"
        )
    committed_body = m.group(0).split("-->\n", 1)[1].rsplit("<!--", 1)[0]
    if committed_body.strip() != reasons_table().strip():
        raise SystemExit(
            f"{path}: reason-taxonomy table drifted from "
            "karmada_tpu/utils/reasons.py REASONS — run "
            "`python tools/docs_from_bench.py --reasons-table`"
        )


def delta_safe_table() -> str:
    """The generated delta-safe kernel registry table (the dep tier's
    ``delta_safe_registry`` is the single source of truth; graftlint
    IR006 proves every ``row_coupled`` declaration it summarizes).
    Unlike the other generated tables this one traces the kernel grid —
    it imports jax and costs a few seconds."""
    sys.path.insert(0, str(ROOT))
    from tools.graftlint.dep import render_delta_safe_table

    return (
        "_Generated from `tools/graftlint/dep.py` `delta_safe_registry` "
        "by `tools/docs_from_bench.py --delta-safe-table` — regenerate, "
        "don't hand-edit._\n\n" + render_delta_safe_table(ROOT)
    )


def check_delta_safe_table() -> None:
    """Fail loudly when the committed DEVELOPMENT.md delta-safe table
    drifted from the analyzer's verdicts (a kernel whose certification
    changed under a refactor must change the committed docs in the same
    PR) — runs on EVERY doc regeneration, same pattern as the env-flag
    gate."""
    path = ROOT / "docs" / "DEVELOPMENT.md"
    m = _marker_re("deltasafe").search(path.read_text())
    if not m:
        raise SystemExit(
            f"{path}: no deltasafe markers — restore the delta-safe "
            "kernel contract section and run "
            "`python tools/docs_from_bench.py --delta-safe-table`"
        )
    committed_body = m.group(0).split("-->\n", 1)[1].rsplit("<!--", 1)[0]
    if committed_body.strip() != delta_safe_table().strip():
        raise SystemExit(
            f"{path}: delta-safe kernel table drifted from the dep "
            "tier's certification registry — run "
            "`python tools/docs_from_bench.py --delta-safe-table`"
        )


def check_ir_registry() -> None:
    """Fail loudly when a kernel family exported from karmada_tpu/ops/ is
    missing from the graftlint IR entry-point registry (or the registry
    carries a stale entry) — runs on EVERY doc regeneration, same pattern
    as the env-flag table gate. Pure AST on the ops side and a plain
    import of the registry module: no jax needed."""
    sys.path.insert(0, str(ROOT))
    from tools.graftlint.ir import ops_registry_drift

    unregistered, stale = ops_registry_drift(ROOT)
    if unregistered or stale:
        raise SystemExit(
            "tools/graftlint/ir.py ENTRY_POINTS drifted from the "
            "karmada_tpu/ops exports — "
            f"exported but unregistered: {unregistered}, registered but "
            f"no longer exported: {stale}; register the kernel (with a "
            "spec builder) or drop the stale entry"
        )


#: the generated-table modes:
#: flag -> (marker, body builder, drift check, target doc)
_TABLE_MODES = {
    "--env-table": ("envflags", env_table, check_env_table,
                    "docs/OPERATIONS.md"),
    "--metrics-table": ("metricfamilies", metrics_table,
                        check_metrics_table, "docs/OPERATIONS.md"),
    "--span-table": ("spantaxonomy", span_table, check_span_table,
                     "docs/OPERATIONS.md"),
    "--history-table": ("historyschema", history_table,
                        check_history_schema, "docs/OPERATIONS.md"),
    "--reasons-table": ("reasontaxonomy", reasons_table,
                        check_reasons_table, "docs/OPERATIONS.md"),
    "--delta-safe-table": ("deltasafe", delta_safe_table,
                           check_delta_safe_table,
                           "docs/DEVELOPMENT.md"),
}


def _check_all(skip: str = "") -> None:
    """Every generated table's drift guard (minus the one just
    rewritten) + the IR registry gate — run on EVERY doc regeneration."""
    for flag, (_marker, _body, check, _doc) in _TABLE_MODES.items():
        if flag != skip:
            check()
    check_ir_registry()


def main() -> None:
    if len(sys.argv) == 2 and sys.argv[1] in _TABLE_MODES:
        flag = sys.argv[1]
        marker, body, _check, doc = _TABLE_MODES[flag]
        rewrite(ROOT / doc, body(), marker)
        _check_all(skip=flag)
        return
    src = Path(sys.argv[1])
    d = json.loads(src.read_text())
    if "parsed" in d:  # the driver's BENCH_r{N}.json wrapper
        d = d["parsed"]
    names = src.name
    body = block(d)
    # optional extra records: bench.py --cold-start / --estimator-only
    for extra in sys.argv[2:]:
        extra_src = Path(extra)
        body += "\n" + extra_block(extra_src)
        names += f" {extra_src.name}"
    body = (
        f"_Generated by `tools/docs_from_bench.py {names}` — regenerate, "
        f"don't hand-edit._\n\n" + body
    )
    rewrite(ROOT / "docs" / "OPERATIONS.md", body)
    rewrite(ROOT / "BASELINE.md", body)
    _check_all()


if __name__ == "__main__":
    main()
