"""Repo tooling: doc generation (docs_from_bench) and static analysis
(graftlint). Not shipped with the karmada_tpu package — run from a
checkout (``python -m tools.graftlint``, ``python tools/docs_from_bench.py``)."""
