"""On-demand-compiled native host runtime (ctypes, no pip deps).

The TPU compute path is XLA; the HOST side of the wire (byte-widening
the fetched buffers, folding entry runs into the mirror) is plain memory
movement that numpy does in several strided passes — at the 1M-binding
tier that is seconds per churn pass. This package compiles ``fold.c``
with the baked-in g++ on first use (cached under ``_build/`` next to the
sources, keyed by source hash) and exposes the loops via ctypes; every
caller keeps a numpy fallback, so a machine without a toolchain just
runs the slower path.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build() -> Optional[ctypes.CDLL]:
    src = os.path.join(_DIR, "fold.c")
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    build_dir = os.path.join(_DIR, "_build")
    so_path = os.path.join(build_dir, f"fold-{digest}.so")
    if not os.path.exists(so_path):
        os.makedirs(build_dir, exist_ok=True)
        tmp = so_path + f".tmp{os.getpid()}"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, src],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, so_path)  # atomic under concurrent builders
    lib = ctypes.CDLL(so_path)
    i64 = ctypes.c_int64
    p_u8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    p_i32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    p_i64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.decode3.argtypes = [p_u8, i64, p_i32]
    lib.decode2.argtypes = [p_u8, i64, p_i32]
    lib.decode21.argtypes = [p_u8, i64, p_i32]
    lib.fold_entries.argtypes = [p_i32, i64, p_i32, p_i64, i64, p_i32]
    lib.apply_deltas.argtypes = [p_i32, i64, p_i32, p_i64, i64, p_i32, p_i32]
    return lib


def get() -> Optional[ctypes.CDLL]:
    """The loaded library, or None (no toolchain / build failure /
    KARMADA_TPU_NO_NATIVE=1). Never raises."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    with _LOCK:
        if _TRIED:
            return _LIB
        if os.environ.get("KARMADA_TPU_NO_NATIVE") == "1":
            _TRIED = True
            return None
        try:
            _LIB = _build()
        except Exception:  # noqa: BLE001 — fallback path is always valid
            _LIB = None
        _TRIED = True
    return _LIB


def le32(raw: np.ndarray) -> int:
    """First 4 bytes as a little-endian int (the wire's total header)."""
    return (
        int(raw[0]) | (int(raw[1]) << 8)
        | (int(raw[2]) << 16) | (int(raw[3]) << 24)
    )


def decode3(raw: np.ndarray) -> np.ndarray:
    """uint8[3n] little-endian packed entries -> int32[n]."""
    n = len(raw) // 3
    lib = get()
    if lib is None:
        e = raw[: 3 * n].astype(np.int32)
        return e[0::3] | (e[1::3] << 8) | (e[2::3] << 16)
    out = np.empty(n, np.int32)
    lib.decode3(np.ascontiguousarray(raw[: 3 * n]), n, out)
    return out


def decode2(raw: np.ndarray) -> np.ndarray:
    """uint8[2n] little-endian meta words -> int32[n]."""
    n = len(raw) // 2
    lib = get()
    if lib is None:
        m = raw[: 2 * n].astype(np.int32)
        return m[0::2] | (m[1::2] << 8)
    out = np.empty(n, np.int32)
    lib.decode2(np.ascontiguousarray(raw[: 2 * n]), n, out)
    return out


def decode21(raw: np.ndarray, n: int) -> np.ndarray:
    """21-bit little-endian bitstream -> int32[n]; ``raw`` must extend at
    least 3 bytes past the packed payload (the device wire pads)."""
    lib = get()
    if lib is None:
        bit = np.arange(n, dtype=np.int64) * 21
        byte = bit >> 3
        sh = (bit & 7).astype(np.uint32)
        b = raw.astype(np.uint32)
        u32 = (
            b[byte] | (b[byte + 1] << 8)
            | (b[byte + 2] << 16) | (b[byte + 3] << 24)
        )
        return ((u32 >> sh) & 0x1FFFFF).astype(np.int32)
    out = np.empty(n, np.int32)
    lib.decode21(np.ascontiguousarray(raw), n, out)
    return out


def fold_entries(
    mirror: np.ndarray,  # int32[cap, k_res] C-contiguous
    rows: np.ndarray,  # per changed row (any int dtype)
    counts: np.ndarray,  # entries per row
    stream: np.ndarray,  # int32 concatenated runs, row order
) -> None:
    """Scatter entry runs into the host mirror (zero-filling each row's
    tail). In-place on ``mirror``."""
    lib = get()
    if lib is None or not mirror.flags["C_CONTIGUOUS"]:
        total = int(counts.sum())
        mirror[rows] = 0
        flat_rows = np.repeat(rows, counts)
        starts = np.cumsum(counts) - counts
        cols = np.arange(total) - np.repeat(starts, counts)
        # clamp overlong runs exactly like the C path (which memcpys at
        # most k_res entries per row) so the two paths stay equivalent
        ok = cols < mirror.shape[1]
        mirror[flat_rows[ok], cols[ok]] = stream[:total][ok]
        return
    lib.fold_entries(
        mirror, mirror.shape[1],
        np.ascontiguousarray(rows, np.int32),
        np.ascontiguousarray(counts, np.int64),
        len(rows),
        np.ascontiguousarray(stream, np.int32),
    )


def apply_deltas(
    mirror: np.ndarray,  # int32[cap, k_res] C-contiguous
    rows: np.ndarray,  # per delta row (any int dtype)
    dcounts: np.ndarray,  # deltas per row
    stream: np.ndarray,  # int32 (site<<9 | newcount+1), row order,
    # site-ascending within each row
) -> None:
    """Merge cell deltas into the host mirror's sorted entry runs
    (newcount 0 removes the site, otherwise set/insert). In-place on
    ``mirror``; rows are clamped to k_res merged entries like
    fold_entries."""
    k_res = mirror.shape[1]
    lib = get()
    if lib is None or not mirror.flags["C_CONTIGUOUS"]:
        off = 0
        for r, nd in zip(rows, dcounts):
            nd = int(nd)
            d = stream[off : off + nd]
            off += nd
            if not nd:
                continue
            run = mirror[r]
            sites = {int(v) >> 8: int(v) & 0xFF for v in run if v != 0}
            for v in d:
                v = int(v)
                site, cnt = v >> 9, (v & 0x1FF) - 1
                if cnt > 0:
                    sites[site] = cnt
                else:
                    sites.pop(site, None)
            merged = [
                (s << 8) | c for s, c in sorted(sites.items())
            ][:k_res]
            mirror[r] = 0
            mirror[r, : len(merged)] = merged
        return
    scratch = np.empty(k_res, np.int32)
    lib.apply_deltas(
        mirror, k_res,
        np.ascontiguousarray(rows, np.int32),
        np.ascontiguousarray(dcounts, np.int64),
        len(rows),
        np.ascontiguousarray(stream, np.int32),
        scratch,
    )
