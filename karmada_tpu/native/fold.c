/* Native host-side hot loops for the fleet engine's wire handling.
 *
 * Ref parity note: the reference's runtime hot paths are Go/C++ (the
 * scheduler cache, codec, and informer delivery are compiled code); the
 * TPU-native plane keeps device work in XLA and gives the HOST side of
 * the wire the same treatment. These two loops dominate the host cost of
 * a churn pass at scale (measured ~7-9 s of numpy fancy indexing at
 * 1M bindings x 32M entries):
 *
 *  - decode3/decode2: byte-wire widening (3-byte packed entries / 2-byte
 *    meta words -> int32) without numpy's three strided passes;
 *  - fold_entries: scatter variable-length entry runs into the
 *    [cap, k_res] int32 host mirror row-contiguously (memcpy + zero-fill
 *    per row instead of a 32M-element advanced-index assignment).
 *
 * Compiled on demand by karmada_tpu.native (g++ -O2 -shared -fPIC);
 * callers fall back to the numpy forms when no toolchain is present.
 */

#include <stdint.h>
#include <string.h>

#ifdef __cplusplus
extern "C" {
#endif

void decode3(const uint8_t *src, int64_t n, int32_t *dst) {
    for (int64_t i = 0; i < n; i++) {
        const uint8_t *p = src + 3 * i;
        dst[i] = (int32_t)p[0] | ((int32_t)p[1] << 8) | ((int32_t)p[2] << 16);
    }
}

void decode2(const uint8_t *src, int64_t n, int32_t *dst) {
    for (int64_t i = 0; i < n; i++) {
        const uint8_t *p = src + 2 * i;
        dst[i] = (int32_t)p[0] | ((int32_t)p[1] << 8);
    }
}

/* 21-bit little-endian bitstream -> int32[n]; src must carry 3 pad bytes
 * past the packed payload (the device wire appends them). */
void decode21(const uint8_t *src, int64_t n, int32_t *dst) {
    for (int64_t i = 0; i < n; i++) {
        int64_t bit = 21 * i;
        const uint8_t *p = src + (bit >> 3);
        uint32_t v = (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
                     ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
        dst[i] = (int32_t)((v >> (bit & 7)) & 0x1FFFFF);
    }
}

/* mirror: int32[cap * k_res]; rows/counts: per changed row; stream: the
 * concatenated entry runs in row order. Each row's run lands at the row
 * start, with the remainder of the row zeroed (results decode the first
 * n_placed lanes, but a stale tail must not survive a shrink). */
void fold_entries(int32_t *mirror, int64_t k_res, const int32_t *rows,
                  const int64_t *counts, int64_t n_rows,
                  const int32_t *stream) {
    int64_t off = 0;
    for (int64_t i = 0; i < n_rows; i++) {
        int32_t *dst = mirror + (int64_t)rows[i] * k_res;
        int64_t c = counts[i];
        if (c > k_res) c = k_res;
        memcpy(dst, stream + off, (size_t)(c * 4));
        memset(dst + c, 0, (size_t)((k_res - c) * 4));
        off += counts[i];
    }
}

/* Cell-delta fold: merge per-row sorted (site<<9 | newcount+1) deltas
 * into the [cap, k_res] host mirror of sorted (site<<8 | count) entry
 * runs. newcount 0 removes the site; an existing site updates in place;
 * a new site inserts in site order. The merged row is clamped to k_res
 * entries (same clamp as fold_entries) and zero-padded. `scratch` must
 * hold k_res int32s. */
void apply_deltas(int32_t *mirror, int64_t k_res, const int32_t *rows,
                  const int64_t *dcounts, int64_t n_rows,
                  const int32_t *stream, int32_t *scratch) {
    int64_t off = 0;
    for (int64_t i = 0; i < n_rows; i++) {
        int32_t *row = mirror + (int64_t)rows[i] * k_res;
        int64_t nd = dcounts[i];
        const int32_t *d = stream + off;
        off += nd;
        if (nd == 0) continue;
        int64_t e = 0, j = 0, out = 0;
        while (e < k_res && row[e] != 0 && j < nd) {
            int32_t site_e = row[e] >> 8;
            int32_t site_d = d[j] >> 9;
            int32_t cnt_d = (d[j] & 0x1FF) - 1;
            if (site_e < site_d) {
                if (out < k_res) scratch[out++] = row[e];
                e++;
            } else if (site_e > site_d) {
                if (cnt_d > 0 && out < k_res)
                    scratch[out++] = (site_d << 8) | cnt_d;
                j++;
            } else {
                if (cnt_d > 0 && out < k_res)
                    scratch[out++] = (site_d << 8) | cnt_d;
                e++;
                j++;
            }
        }
        while (e < k_res && row[e] != 0) {
            if (out < k_res) scratch[out++] = row[e];
            e++;
        }
        for (; j < nd; j++) {
            int32_t cnt_d = (d[j] & 0x1FF) - 1;
            if (cnt_d > 0 && out < k_res)
                scratch[out++] = ((d[j] >> 9) << 8) | cnt_d;
        }
        memcpy(row, scratch, (size_t)(out * 4));
        memset(row + out, 0, (size_t)((k_res - out) * 4));
    }
}

#ifdef __cplusplus
}
#endif
