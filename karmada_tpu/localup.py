"""Multi-process local-up: the hack/local-up-karmada.sh analogue.

Ref: hack/local-up-karmada.sh:33-46 boots a full multi-process Karmada
(apiserver + controller-manager + scheduler + webhook + agent in kind
clusters); hack/run-e2e.sh:44-56 then drives 36 e2e suites against it.

This module composes the TPU-native plane the same way, as REAL OS
processes wired only by network surfaces:

- the PLANE process (``python -m karmada_tpu.localup serve``) runs the
  store + controller fleet + scheduler and serves three network surfaces:
  the store bus (gRPC watch/apply), the cluster proxy (HTTP), and
  /metrics (Prometheus text);
- a SOLVER sidecar process (``python -m karmada_tpu.solver``) owns the
  Score/Assign engine; the plane routes scheduling over gRPC with
  snapshot-version fencing;
- an ESTIMATOR server process (``python -m karmada_tpu.estimator``) per
  designated member answers MaxAvailableReplicas over gRPC;
- a pull-mode AGENT process (``python -m karmada_tpu.bus.agent``) mirrors
  the plane over the bus and drives its member cluster.

``LocalUp`` is the orchestrator: it spawns the children, scrapes their
ports, and exposes the endpoints — used by the CLI (``local-up
--processes``) and by tests/test_localup_processes.py, which drives the
quickstart through the network surfaces only.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import time
from typing import Optional


def spawn_child(
    cmd: list[str], platform: str = "cpu", extra_env: dict | None = None
) -> subprocess.Popen:
    """Spawn a component child process: ``platform`` selects its jax
    backend (default CPU — control-plane components must never dial the
    accelerator), package importable regardless of the caller's cwd.
    Shared by LocalUp and the process operator — one copy of the env
    construction. ``extra_env`` overlays the inherited environment (the
    orchestrator hands the plane child its peers' trace endpoints this
    way).

    The accelerator is SINGLE-CLIENT: exactly one component per machine
    may run with a non-cpu platform (deployment-wise that is the solver
    sidecar — the "dedicate a chip to scheduling" shape in
    docs/OPERATIONS.md). KARMADA_TPU_PLATFORM is the authoritative
    channel: the tunnel sitecustomize overrides JAX_PLATFORMS
    programmatically, so each child entrypoint re-asserts the policy via
    utils.platform.apply_child_platform()."""
    env = dict(
        os.environ, JAX_PLATFORMS=platform, KARMADA_TPU_PLATFORM=platform,
        **(extra_env or {}),
    )
    if platform != "cpu":
        # the test harness exports --xla_force_host_platform_device_count
        # for its own virtual CPU mesh (tests/conftest.py); the tunnel
        # client DEADLOCKS at backend init when an accelerator child
        # inherits it (observed: the solver sidecar silent for 600 s under
        # pytest, instant standalone). The accelerator-owning child starts
        # with that flag stripped.
        flags = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        if flags:
            env["XLA_FLAGS"] = " ".join(flags)
        else:
            env.pop("XLA_FLAGS", None)
    pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = (
        pkg_parent + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH")
        else pkg_parent
    )
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env,
    )


def scrape_line(proc: subprocess.Popen, pattern: str, timeout: float = 240.0) -> str:
    """First regex group of the first stdout line matching ``pattern``.

    select()-gated so a child that hangs BEFORE printing (import stall,
    bind wait) raises after ``timeout`` instead of blocking readline
    forever; a child that dies mid-startup raises immediately — with its
    recent output in the error, so startup failures are diagnosable from
    the orchestrator's traceback alone."""
    import collections
    import select

    tail: collections.deque = collections.deque(maxlen=15)

    def die(reason: str) -> None:
        if proc.poll() is not None:
            try:
                rest = proc.stdout.read() or ""
                tail.extend(rest.splitlines()[-10:])
            except Exception:  # noqa: BLE001 — best-effort diagnostics
                pass
        out = "\n".join(f"    | {ln.rstrip()}" for ln in tail)
        raise RuntimeError(
            f"{reason} (cmd: {' '.join(proc.args[:6])}...)\n"
            f"  recent child output:\n{out or '    | <none>'}"
        )

    deadline = time.time() + timeout
    while True:
        remaining = deadline - time.time()
        if remaining <= 0:
            die(f"no line matching {pattern!r} within {timeout}s")
        ready, _, _ = select.select([proc.stdout], [], [], min(remaining, 0.5))
        if not ready:
            if proc.poll() is not None:
                die(f"child exited rc={proc.returncode} during startup")
            continue
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                die(f"child exited rc={proc.returncode} during startup")
            time.sleep(0.05)  # stdout closed but child alive: avoid spin
            continue
        tail.append(line)
        m = re.search(pattern, line)
        if m:
            return m.group(1)


def _scrape_port(proc: subprocess.Popen, pattern: str, timeout: float = 240.0) -> int:
    return int(scrape_line(proc, pattern, timeout))


# --------------------------------------------------------------------------
# the plane process
# --------------------------------------------------------------------------


def serve_plane_replica(args) -> None:
    """HA plane replica (the reference's --leader-elect active-standby
    shape, cmd/scheduler/app/options/options.go:130-165): the controller
    fleet runs over a bus StoreReplica of an EXTERNAL store process
    (python -m karmada_tpu.bus), and only the Lease-elected leader
    reconciles. Standbys stay warm — their mirrors track every event and
    their workqueues accumulate keys — so takeover is one settle away.
    No double-scheduling: leadership is CAS-exclusive per tick, and the
    scheduler's observed-generation guard makes a raced duplicate
    reconcile idempotent."""
    import os

    from .bus.agent import ReplicaStoreFacade
    from .bus.service import StoreReplica
    from .controlplane import ControlPlane
    from .utils.builders import new_cluster
    from .utils.leaderelect import LeaderElector
    from .utils.member import MemberCluster
    from .utils.metrics import MetricsServer
    from .utils.net import parse_hostport as addr
    from .utils.tracing import register_peers_from_env, tracer

    tracer.set_process("plane")
    register_peers_from_env()

    replica = StoreReplica(args.connect_bus)
    replica.start()
    if not replica.wait_synced(30):
        print("error: bus replica failed to sync", file=sys.stderr)
        sys.exit(2)
    facade = ReplicaStoreFacade(replica)
    cp = ControlPlane(
        store=facade,
        enable_descheduler=args.descheduler,
        lease_grace_seconds=args.lease_grace or None,
    )
    from .utils.store import ConflictError

    for name in args.pull:
        # every replica registers the local inventory shell + status
        # watch; the Cluster OBJECT is created create-only (expected_rv=0)
        # so two concurrently booting replicas cannot clobber the agent's
        # already-written status through their async mirrors (a check-
        # then-act on the mirror races; the CAS loses cleanly instead)
        member = MemberCluster(name)
        cp.members.register(member)
        cp.work_status_controller.watch_member(member)
        if facade.get("Cluster", name) is None:
            cluster = new_cluster(name, cpu="100", memory="200Gi")
            cluster.spec.sync_mode = "Pull"
            try:
                facade.apply(cluster, expected_rv=0)
            except ConflictError:
                pass  # a peer replica won the create
    # HA standbys prewarm at boot: a takeover's first scheduling wave is
    # exactly the cold wave the manifest exists to kill — a standby that
    # compiles AFTER winning the lease serves its first storm cold.
    from .scheduler.prewarm import resolve_boot_manifest
    from .utils.compilecache import MANIFEST_ENV

    manifest_path = resolve_boot_manifest(args.warmup_manifest)
    # export the resolved path (including an explicit "" opt-out): the
    # scheduler controller builds its engine lazily and resolves the
    # manifest from this env var — without it the replica would prewarm
    # but never seed its trace ledger or record fresh traces back
    os.environ[MANIFEST_ENV] = manifest_path
    if manifest_path:
        from .scheduler.prewarm import warmup

        stats = warmup(manifest_path)
        print(
            f"# replica prewarm: {stats['compiled']}/{stats['specs']} "
            f"traces in {stats['seconds']:.1f}s",
            file=sys.stderr,
        )
    cp.runtime.realtime = True
    metrics = MetricsServer(address=addr(args.metrics_address))
    metrics_port = metrics.start()
    identity = args.identity or f"plane-{os.getpid()}"
    elector = LeaderElector(
        facade,
        "karmada-plane",
        identity,
        lease_duration=args.lease_duration,
        renew_deadline=args.renew_deadline,
        on_started_leading=lambda: print(
            json.dumps({"leading": identity}), flush=True
        ),
        on_stopped_leading=lambda: print(
            json.dumps({"standby": identity}), flush=True
        ),
    )
    # renewals must survive long settles (client-go renews on its own
    # goroutine; this runtime is cooperative, so renewal rides the drain
    # loop via the heartbeat seam), throttled to lease/5 so neither the
    # settle loop nor the serve loop hammers the bus with CAS writes —
    # and the moment leadership is lost mid-settle, the heartbeat's False
    # aborts the drain so a deposed leader stops writing immediately
    last_tick = [0.0]

    def renew_tick() -> bool:
        now = time.time()
        if now - last_tick[0] >= args.lease_duration / 5:
            last_tick[0] = now
            elector.tick()
        return elector.is_leader

    cp.runtime.heartbeat = renew_tick
    print(
        json.dumps({"metrics": metrics_port, "identity": identity}),
        flush=True,
    )

    stop = [False]

    def on_term(signum, frame):
        stop[0] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    try:
        while not stop[0]:
            leading = renew_tick()
            if leading:
                cp.settle()
                due = cp.runtime.next_due()
                time.sleep(
                    max(0.001, min(args.loop_interval, due))
                    if due is not None
                    else args.loop_interval
                )
            else:
                time.sleep(args.loop_interval)
    finally:
        elector.release()
        metrics.stop()
        replica.close()


def serve_plane(args) -> None:
    """Run the control plane + its network surfaces until SIGTERM."""
    if args.connect_bus:
        return serve_plane_replica(args)
    from .bus.service import StoreBusServer
    from .cli import cmd_init, cmd_join
    from .controlplane import ControlPlane  # noqa: F401 (docs)
    from .search.proxyserver import ClusterProxyServer
    from .utils.builders import new_cluster
    from .utils.metrics import MetricsServer
    from .utils.tracing import register_peers_from_env, tracer

    tracer.set_process("plane")
    register_peers_from_env()

    if args.feature_gates:
        from .utils.features import feature_gate

        for spec in args.feature_gates.split(","):
            name, _, val = spec.partition("=")
            feature_gate.set(name.strip(), val.strip().lower() in ("1", "true", ""))

    admission_kw = {}
    if args.admission:
        # out-of-process TLS admission: every store write round-trips the
        # webhook process (cmd/webhook deployment shape)
        from .webhook.server import RemoteAdmission

        ca = open(args.admission_ca, "rb").read() if args.admission_ca else None
        remote = RemoteAdmission(args.admission, ca_bundle=ca)
        admission_kw = {
            "admission_override": remote.admit,
            "delete_admission_override": remote.admit_delete,
        }

    solver = None
    if args.solver:
        # comma-separated targets = HA solver replicas: the plane sticks
        # to the active one and fails over on transport errors
        targets = [t for t in args.solver.split(",") if t]
        if not targets:
            print("error: --solver given but no targets parsed",
                  file=sys.stderr)
            sys.exit(2)
        if len(targets) > 1:
            from .solver.client import HASolver

            solver = HASolver(targets)
        else:
            from .solver.client import RemoteSolver

            solver = RemoteSolver(targets[0])
    cp = cmd_init(solver=solver, enable_descheduler=args.descheduler,
                  lease_grace_seconds=args.lease_grace or None,
                  **admission_kw)
    if args.state_file and os.path.exists(args.state_file):
        # etcd-persistence analogue: a restarted plane restores the store
        # snapshot its predecessor checkpointed on shutdown, so operator
        # upgrades don't wipe control-plane state
        restored = cp.store.restore(args.state_file)
        print(f"# restored {restored} objects from {args.state_file}",
              file=sys.stderr)
    for i in range(1, args.members + 1):
        cmd_join(cp, f"member{i}", cpu="100", memory="200Gi")
    for name in args.pull:
        cluster = new_cluster(name, cpu="100", memory="200Gi")
        cluster.spec.sync_mode = "Pull"
        cp.join_cluster(cluster, remote_agent=True)

    # boot-phase prewarm: replay the trace manifest through AOT compile
    # BEFORE the first settle, so the plane's first scheduling wave (the
    # cold wave a restart/HA-failover pays) runs only already-compiled
    # traces. Only meaningful when the plane runs the in-proc engine —
    # with a solver sidecar the sidecar prewarms itself (its own
    # --warmup-manifest).
    from .scheduler.prewarm import resolve_boot_manifest
    from .utils.compilecache import MANIFEST_ENV

    manifest_path = resolve_boot_manifest(args.warmup_manifest)
    # export the resolved path (including an explicit "" opt-out): the
    # scheduler controller builds its engine lazily and resolves the
    # manifest from this env var — without it the plane would prewarm but
    # never seed its trace ledger (first pass still new_trace=True) or
    # record fresh traces back into the manifest
    os.environ[MANIFEST_ENV] = manifest_path
    if manifest_path and not solver:
        from .scheduler.prewarm import warmup

        stats = warmup(manifest_path)
        print(
            f"# plane prewarm: {stats['compiled']}/{stats['specs']} traces "
            f"in {stats['seconds']:.1f}s from {manifest_path}",
            file=sys.stderr,
        )

    # remote estimator registrations: NAME=HOST:PORT
    if args.estimator:
        from .estimator.grpc_transport import (
            GrpcEstimatorConnection,
            RemoteAccurateEstimator,
        )

        for spec in args.estimator:
            name, _, target = spec.partition("=")
            conn = GrpcEstimatorConnection(name, target)
            cp.estimators.register(
                RemoteAccurateEstimator(
                    name, conn, lambda: cp.scheduler.snapshot.dims
                )
            )
        names = sorted(cp.members.names())
        cp.scheduler.extra_estimators = [
            cp.estimators.make_batch_estimator(names)
        ]

    bus = StoreBusServer(cp.store, args.bus_address)
    bus_port = bus.start()

    from .utils.net import parse_hostport as addr

    proxy = ClusterProxyServer(
        cp.members, addr(args.proxy_address),
        tokens={"admin-token": ("admin", ["system:masters"])},
    )
    proxy_port = proxy.start()
    metrics = MetricsServer(address=addr(args.metrics_address))
    metrics_port = metrics.start()
    # serve mode runs against the wall clock: reconcile failures back off
    # exponentially (workqueue DefaultControllerRateLimiter discipline)
    # instead of burning 16 hot-loop retries inside one settle call.
    # Set BEFORE the boot settle — a member that is slow to come up must
    # park its keys for the serve loop, not burn the drop budget at boot.
    cp.runtime.realtime = True
    cp.settle()
    print(
        json.dumps(
            {
                "bus": bus_port,
                "proxy": proxy_port,
                "metrics": metrics_port,
                "clusters": sorted(c.name for c in cp.store.list("Cluster")),
            }
        ),
        flush=True,
    )

    stop = [False]

    def on_term(signum, frame):
        stop[0] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    last_ckpt = time.time()
    last_ckpt_rv = -1
    try:
        while not stop[0]:
            cp.settle()
            if (
                args.state_file
                and args.checkpoint_interval > 0
                and time.time() - last_ckpt >= args.checkpoint_interval
            ):
                # periodic durability: a SIGKILLed plane restarts from the
                # last interval snapshot, not from empty (etcd analogue).
                # Skipped while the store rv is unchanged — an idle plane
                # must not re-serialize its whole store every interval.
                rv = cp.store.rv
                if rv != last_ckpt_rv:
                    cp.store.checkpoint(args.state_file)
                    last_ckpt_rv = rv
                last_ckpt = time.time()
            due = cp.runtime.next_due()
            time.sleep(
                max(0.001, min(args.loop_interval, due))
                if due is not None
                else args.loop_interval
            )
    finally:
        if args.state_file:
            saved = cp.store.checkpoint(args.state_file)
            print(f"# checkpointed {saved} objects to {args.state_file}",
                  file=sys.stderr)
        metrics.stop()
        proxy.stop()
        bus.stop()


# --------------------------------------------------------------------------
# the orchestrator
# --------------------------------------------------------------------------


class LocalUp:
    """Spawn the full multi-process deployment; context-manager teardown.

    Children: solver sidecar, one estimator (member1), the plane (bus +
    proxy + metrics), one pull agent. All wiring is host:port — nothing
    shares memory with anything else."""

    def __init__(
        self,
        members: int = 2,
        pull: tuple[str, ...] = ("pull1",),
        with_solver: bool = True,
        with_estimator: bool = True,
        descheduler: bool = False,
        lease_grace: float = 0.0,
        feature_gates: str = "Failover=true",
        solver_platform: str = "cpu",
        warmup_manifest: str | None = None,
    ):
        self.lease_grace = lease_grace
        self.feature_gates = feature_gates
        # trace-manifest path handed to the scheduling-owning child (the
        # solver sidecar when present, else the plane): that child AOT-
        # prewarms from it at boot and records fresh traces back into it
        self.warmup_manifest = warmup_manifest
        self.members = members
        self.pull = pull
        self.with_solver = with_solver
        self.with_estimator = with_estimator
        self.descheduler = descheduler
        # per-component platform policy: only the solver sidecar may own
        # the accelerator (single-client tunnel); everything else is CPU
        self.solver_platform = solver_platform
        self.solver_backend = ""  # scraped from the sidecar at startup
        self.procs: dict[str, subprocess.Popen] = {}
        self.endpoints: dict[str, int] = {}

    def _spawn(
        self, name: str, cmd: list[str], platform: str = "cpu",
        extra_env: dict | None = None,
    ) -> subprocess.Popen:
        proc = spawn_child(cmd, platform=platform, extra_env=extra_env)
        self.procs[name] = proc
        return proc

    def __enter__(self) -> "LocalUp":
        py = sys.executable
        try:
            if self.with_solver:
                # claim-with-retry: the accelerator tunnel is single-client
                # and a predecessor's unclean exit holds the claim for
                # minutes with NO timeout client-side — a stuck claimant
                # hangs forever. The sidecar watchdogs its own backend init
                # (--backend-timeout -> 'solver backend timeout', rc=3) and
                # we respawn a FRESH claimant until one lands post-expiry.
                attempts = 6 if self.solver_platform != "cpu" else 1
                solver_cmd = [
                    py, "-m", "karmada_tpu.solver", "--address",
                    "127.0.0.1:0", "--report-backend",
                    "--backend-timeout", "90", "--metrics-port", "0",
                ]
                if self.warmup_manifest is not None:
                    # an explicit "" propagates as the child's opt-out
                    # (overrides an inherited KARMADA_TPU_TRACE_MANIFEST)
                    solver_cmd += ["--warmup-manifest", self.warmup_manifest]
                for attempt in range(attempts):
                    p = self._spawn(
                        "solver", solver_cmd, platform=self.solver_platform,
                    )
                    self.endpoints["solver"] = _scrape_port(p, r"port (\d+)")
                    self.endpoints["solver_metrics"] = _scrape_port(
                        p, r"metrics listening on port (\d+)"
                    )
                    self.solver_backend = scrape_line(
                        p, r"solver backend (\S+)", timeout=150.0
                    )
                    if self.solver_backend == "error":
                        # deterministic init failure: retrying replays the
                        # same traceback — surface it instead
                        detail = ""
                        try:
                            p.kill()
                            p.wait(timeout=5)
                            detail = (p.stdout.read() or "")[-2000:]
                        except Exception:  # noqa: BLE001 — diagnostics
                            pass
                        raise RuntimeError(
                            f"solver backend init failed:\n{detail}"
                        )
                    if self.solver_backend != "timeout":
                        break
                    p.kill()
                    p.wait(timeout=5)
                    if attempt == attempts - 1:
                        raise RuntimeError(
                            "solver backend init timed out on every "
                            f"attempt ({attempts}) — the accelerator "
                            "claim never freed"
                        )
                    time.sleep(20)  # let the held claim expire
            if self.with_estimator:
                p = self._spawn(
                    "estimator",
                    [py, "-m", "karmada_tpu.estimator", "--cluster", "member1",
                     "--address", "127.0.0.1:0", "--metrics-port", "0"],
                )
                self.endpoints["estimator"] = _scrape_port(p, r"port (\d+)")
                self.endpoints["estimator_metrics"] = _scrape_port(
                    p, r"metrics listening on port (\d+)"
                )

            # the plane child learns where to stitch cross-process traces
            # from: every spawned peer's metrics endpoint, exported as
            # KARMADA_TPU_TRACE_PEERS (utils.tracing boot hook)
            peer_specs = [
                f"{name.removesuffix('_metrics')}=127.0.0.1:{port}"
                for name, port in self.endpoints.items()
                if name.endswith("_metrics")
            ]
            plane_env = (
                {"KARMADA_TPU_TRACE_PEERS": ",".join(peer_specs)}
                if peer_specs
                else None
            )

            plane_cmd = [
                py, "-m", "karmada_tpu.localup", "serve",
                "--members", str(self.members),
            ]
            for name in self.pull:
                plane_cmd += ["--pull", name]
            if self.with_solver:
                plane_cmd += ["--solver", f"127.0.0.1:{self.endpoints['solver']}"]
            if self.with_estimator:
                plane_cmd += [
                    "--estimator", f"member1=127.0.0.1:{self.endpoints['estimator']}"
                ]
            if self.descheduler:
                plane_cmd += ["--descheduler"]
            if self.lease_grace:
                plane_cmd += ["--lease-grace", str(self.lease_grace)]
            if self.feature_gates:
                plane_cmd += ["--feature-gates", self.feature_gates]
            if self.warmup_manifest is not None:
                plane_cmd += ["--warmup-manifest", self.warmup_manifest]
            p = self._spawn("plane", plane_cmd, extra_env=plane_env)
            deadline = time.time() + 240
            while time.time() < deadline:
                line = p.stdout.readline()
                if line.startswith("{"):
                    info = json.loads(line)
                    self.endpoints.update(
                        bus=info["bus"], proxy=info["proxy"], metrics=info["metrics"]
                    )
                    self.clusters = info["clusters"]
                    break
                if p.poll() is not None:
                    raise RuntimeError(f"plane exited rc={p.returncode}")
            else:
                raise RuntimeError("plane never printed its endpoints")

            for name in self.pull:
                self._spawn(
                    f"agent-{name}",
                    [py, "-m", "karmada_tpu.bus.agent",
                     "--target", f"127.0.0.1:{self.endpoints['bus']}",
                     "--cluster", name],
                )
            return self
        except Exception:
            self.__exit__(None, None, None)
            raise

    def __exit__(self, *exc) -> None:
        for proc in reversed(list(self.procs.values())):
            if proc.poll() is None:
                proc.terminate()
        for proc in self.procs.values():
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)

    def kill(self, name: str) -> None:
        """Fault injection: hard-kill one component process."""
        proc = self.procs[name]
        proc.kill()
        proc.wait(timeout=5)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    sv = sub.add_parser("serve", help="run the plane process (internal)")
    sv.add_argument("--members", type=int, default=2)
    sv.add_argument("--pull", action="append", default=[])
    sv.add_argument(
        "--solver", default="",
        help="solver sidecar host:port (comma-separated = HA replicas "
        "with client failover)",
    )
    sv.add_argument("--estimator", action="append", default=[])
    sv.add_argument("--bus-address", default="127.0.0.1:0")
    sv.add_argument("--descheduler", action="store_true")
    sv.add_argument("--loop-interval", type=float, default=0.05)
    sv.add_argument("--lease-grace", type=float, default=0.0)
    sv.add_argument("--feature-gates", default="",
                    help="comma list NAME=true|false (pkg/features)")
    sv.add_argument("--admission", default="",
                    help="external admission webhook URL (https://.../admit)")
    sv.add_argument("--admission-ca", default="",
                    help="PEM CA bundle for the admission webhook")
    sv.add_argument("--state-file", default="",
                    help="checkpoint/restore path for the store (the etcd "
                    "persistence analogue across plane restarts)")
    sv.add_argument("--checkpoint-interval", type=float, default=15.0,
                    help="periodic store checkpoint seconds (0 = only on "
                    "shutdown); bounds data loss on a hard kill")
    sv.add_argument("--proxy-address", default="127.0.0.1:0",
                    help="pin the cluster-proxy bind address")
    sv.add_argument("--metrics-address", default="127.0.0.1:0",
                    help="pin the /metrics bind address")
    sv.add_argument("--connect-bus", default="",
                    help="HA replica mode: run the controller fleet over a "
                    "StoreReplica of this external store-bus address "
                    "(python -m karmada_tpu.bus) instead of hosting the "
                    "store; pairs with --leader-elect")
    sv.add_argument("--leader-elect", action="store_true",
                    help="Lease-CAS active-standby (every reference binary's "
                    "--leader-elect); implied by --connect-bus")
    sv.add_argument("--identity", default="",
                    help="leader-election identity (default plane-<pid>)")
    sv.add_argument("--lease-duration", type=float, default=15.0)
    sv.add_argument("--renew-deadline", type=float, default=10.0)
    sv.add_argument("--warmup-manifest", default=None,
                    help="trace-manifest path to AOT-prewarm the in-proc "
                    "scheduler from before the first settle (default: "
                    "$KARMADA_TPU_TRACE_MANIFEST; with --solver the "
                    "sidecar prewarms itself instead)")

    up = sub.add_parser("up", help="spawn the full multi-process deployment")
    up.add_argument("--members", type=int, default=2)
    # default applied after parsing: an append action with a non-empty
    # default list would APPEND user values to it (no way to drop pull1)
    up.add_argument("--pull", action="append", default=None)
    up.add_argument("--warmup-manifest", default=None,
                    help="trace-manifest path handed to the scheduling-"
                    "owning child (solver sidecar when present, else the "
                    "plane) for boot-phase AOT prewarm (default: "
                    "$KARMADA_TPU_TRACE_MANIFEST)")

    args = p.parse_args(argv)
    # chaos: arm deterministic fault injection from the environment
    # (KARMADA_TPU_FAULT_SPEC; disarmed when empty — zero overhead)
    from .utils.faultinject import arm_from_env

    arm_from_env()
    if args.command == "up" and args.pull is None:
        args.pull = ["pull1"]
    if args.command == "serve":
        if args.leader_elect and not args.connect_bus:
            # election needs the shared store: a lone plane hosting its own
            # store has nothing to elect against — failing loudly beats an
            # operator believing a single-writer plane is HA
            p.error("--leader-elect requires --connect-bus (the shared "
                    "store-bus the replicas elect over)")
        serve_plane(args)
    elif args.command == "up":
        with LocalUp(
            members=args.members, pull=tuple(args.pull),
            warmup_manifest=args.warmup_manifest,
        ) as lu:
            print(json.dumps(lu.endpoints), flush=True)
            try:
                while all(p.poll() is None for p in lu.procs.values()):
                    time.sleep(1)
            except KeyboardInterrupt:
                pass


if __name__ == "__main__":
    main()
