"""Resource-model grade estimation as a batched tensor kernel.

Semantics (general.go:195-249 + modeling.go):
- each cluster declares G model grades; grade g covers nodes whose capacity
  falls in [min, max) per resource; the cluster status reports how many
  allocatable nodes sit in each grade (AllocatableModelings).
- for a request, the minimum compliant grade per resource is the first grade
  whose *min* boundary covers the request (a 1.5C request cannot trust the
  [1C,2C) grade); the overall index is the max across requested resources;
  no compliant grade for any resource -> 0 replicas.
- every node of grade >= index contributes min over requested dims of
  floor(grade_min / request) replicas, floored at 1 ("the first suitable
  model can hold one pod", general.go:226-231).
- a requested resource absent from the models entirely makes the model path
  inapplicable (error -> fall back to the summary path; general.go:127-135).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops  # noqa: F401  — enables x64 before the int64 kernel traces
from ..api.cluster import Cluster


@dataclass
class ModelPack:
    """Packed model grades for a fleet. G = max grades across clusters;
    clusters with fewer grades pad with counts 0."""

    min_bounds: np.ndarray  # int64[C, G, R]; -1 where grade/resource undefined
    counts: np.ndarray  # int32[C, G] allocatable nodes per grade
    has_models: np.ndarray  # bool[C]
    covered: np.ndarray  # bool[C, R] resource present in the cluster's models


def pack_models(clusters: Sequence[Cluster], dims: Sequence[str]) -> ModelPack:
    c, r = len(clusters), len(dims)
    g_max = max(
        (len(cl.spec.resource_models) for cl in clusters), default=0
    )
    g_max = max(g_max, 1)
    min_bounds = np.full((c, g_max, r), -1, np.int64)
    counts = np.zeros((c, g_max), np.int32)
    has_models = np.zeros(c, bool)
    covered = np.zeros((c, r), bool)
    dim_idx = {d: j for j, d in enumerate(dims)}
    for i, cl in enumerate(clusters):
        models = cl.spec.resource_models
        modelings = cl.status.resource_summary.allocatable_modelings
        if not models or not modelings:
            continue
        has_models[i] = True
        count_by_grade = {m.grade: m.count for m in modelings}
        for g, model in enumerate(sorted(models, key=lambda m: m.grade)):
            counts[i, g] = count_by_grade.get(model.grade, 0)
            for rng_ in model.ranges:
                j = dim_idx.get(rng_.name)
                if j is not None:
                    min_bounds[i, g, j] = rng_.min
                    covered[i, j] = True
    return ModelPack(
        min_bounds=min_bounds, counts=counts, has_models=has_models, covered=covered
    )


@jax.jit
def estimate_by_models(
    min_bounds: jnp.ndarray,  # int64[C, G, R]
    counts: jnp.ndarray,  # int32[C, G]
    covered: jnp.ndarray,  # bool[C, R]
    requests: jnp.ndarray,  # int64[B, R]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (replicas int32[B, C], applicable bool[B, C]).

    applicable=False means the model path cannot answer for that
    (binding, cluster) — requested resource not covered — and the caller
    falls back to the summary estimate.
    """
    c_n, g_n, r_n = min_bounds.shape
    req = requests[:, None, None, :]  # [B,1,1,R]
    is_req = req > 0
    mb = min_bounds[None, :, :, :]  # [1,C,G,R]

    # grade compliant per resource: min boundary >= request
    compliant = (mb >= req) & (mb >= 0)  # [B,C,G,R]
    # first compliant grade per resource (G if none)
    first = jnp.where(
        compliant.any(axis=2),
        jnp.argmax(compliant, axis=2),
        g_n,
    )  # [B,C,R]
    # overall minimum compliant index = max over requested dims (0 if no dims)
    idx = jnp.max(jnp.where(is_req[:, :, 0, :], first, 0), axis=-1)  # [B,C]
    no_grade = idx >= g_n  # some requested resource has no compliant grade

    # per-grade per-node replicas: min over requested dims of mb // req, >= 1
    safe_req = jnp.maximum(req, 1)
    per_dim = jnp.where(mb >= 0, mb, 0) // safe_req  # [B,C,G,R]
    per_node = jnp.min(
        jnp.where(is_req, per_dim, jnp.int64(2**62)), axis=-1
    )  # [B,C,G]
    # degenerate all-zero request -> treat as one pod per node (the reference
    # early-returns on nil requirements before reaching the model path)
    per_node = jnp.where(per_node >= 2**62, 0, per_node)
    per_node = jnp.maximum(per_node, 1)  # general.go:226-231

    grade_ids = jnp.arange(g_n)[None, None, :]
    usable = grade_ids >= idx[:, :, None]  # grades >= minimum compliant index
    total = jnp.sum(
        jnp.where(usable, counts[None, :, :].astype(jnp.int64) * per_node, 0),
        axis=-1,
    )
    total = jnp.where(no_grade, 0, total)
    total = jnp.minimum(total, jnp.int64(2**31 - 1)).astype(jnp.int32)

    # applicability: every requested dim covered by the cluster's models
    applicable = jnp.all(
        jnp.where(is_req[:, :, 0, :], covered[None, :, :], True), axis=-1
    )
    return total, applicable


def estimate_by_models_np(
    min_bounds: "np.ndarray",  # int64[C, G, R]
    counts: "np.ndarray",  # int32[C, G]
    covered: "np.ndarray",  # bool[C, R]
    requests: "np.ndarray",  # int64[B, R]
) -> tuple:
    """numpy mirror of ``estimate_by_models`` — bit-identical (all exact
    int64 arithmetic, same argmax/first-compliant-grade semantics). The
    tiny-batch host fast path and the fleet's avail-max bound consume it
    so model-bearing fleets stay off the device round-trip for small
    work (BASELINE config 3); tests/test_estimators.py fuzzes the two
    against each other."""
    import numpy as np

    c_n, g_n, r_n = min_bounds.shape
    req = requests[:, None, None, :]  # [B,1,1,R]
    is_req = req > 0
    mb = min_bounds[None, :, :, :]  # [1,C,G,R]
    compliant = (mb >= req) & (mb >= 0)  # [B,C,G,R]
    first = np.where(
        compliant.any(axis=2), np.argmax(compliant, axis=2), g_n
    )  # [B,C,R]
    idx = np.max(np.where(is_req[:, :, 0, :], first, 0), axis=-1)  # [B,C]
    no_grade = idx >= g_n
    safe_req = np.maximum(req, 1)
    per_dim = np.where(mb >= 0, mb, 0) // safe_req
    per_node = np.min(
        np.where(is_req, per_dim, np.int64(2**62)), axis=-1
    )  # [B,C,G]
    per_node = np.where(per_node >= 2**62, 0, per_node)
    per_node = np.maximum(per_node, 1)
    grade_ids = np.arange(g_n)[None, None, :]
    usable = grade_ids >= idx[:, :, None]
    total = np.sum(
        np.where(usable, counts[None, :, :].astype(np.int64) * per_node, 0),
        axis=-1,
    )
    total = np.where(no_grade, 0, total)
    total = np.minimum(total, np.int64(2**31 - 1)).astype(np.int32)
    applicable = np.all(
        np.where(is_req[:, :, 0, :], covered[None, :, :], True), axis=-1
    )
    return total, applicable
