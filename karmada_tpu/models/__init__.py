"""Cluster resource modeling: grade-bucket capacity estimation.

Ref: pkg/modeling/modeling.go (node bucketing into resource-model grades) and
the model-based estimation path of pkg/estimator/client/general.go:198-249.
The reference walks grade buckets per cluster with a red-black tree; here the
grade boundaries pack into ``[C, G, R]`` arrays and the whole fleet estimates
in one batched kernel (karmada_tpu.models.estimate_by_models).
"""

from .modeling import ModelPack, estimate_by_models, pack_models  # noqa: F401
