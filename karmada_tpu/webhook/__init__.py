"""Admission webhooks: mutation defaulting + validation invariants.

Ref: pkg/webhook/** (22 handlers registered at cmd/webhook/app/webhook.go:
161-183): mutators default placement/suspension fields and inject permanent
IDs; validators enforce policy/override/quota invariants. Here the chain is
in-process: the store runs it on every apply (the admission seam of the
apiserver), and the same functions are importable for CLI-side validation.
"""

from .chain import AdmissionChain, ValidationError, default_admission_chain  # noqa: F401
