"""Admission webhook as an HTTP(S) service (VERDICT r3 item 10).

Ref: cmd/webhook/app/webhook.go:161-183 — the reference's 22 admission
handlers run in a separate TLS process the apiserver calls per write. Here
the SAME ``AdmissionChain`` (webhook/chain.py) that normally hooks the
Store in-process is hosted behind HTTP(S) (the interpreter webhook's
transport, interpreter/webhook.py), and ``RemoteAdmission`` plugs the wire
round-trip back into a Store's admission seam: every apply/delete POSTs an
AdmissionReview-style document, mutations come back serialized, denials
raise exactly like the in-proc chain.
"""

from __future__ import annotations

import json
import ssl
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..bus.service import decode_object, encode_object
from .chain import AdmissionChain, default_admission_chain

#: env knob for RemoteAdmission's per-request read deadline (registered
#: in utils.flags ENV_FLAGS; module-level so the GL003 read-site scan
#: resolves it)
ADMISSION_TIMEOUT_ENV = "KARMADA_TPU_ADMISSION_TIMEOUT"


class AdmissionDenied(Exception):
    pass


class AdmissionWebhookServer:
    """Hosts an AdmissionChain behind POST /admit.

    Request:  {"kind", "operation": "CREATE"|"DELETE", "object": <json>}
    Response: {"allowed": bool, "object": <mutated json>, "message": str}
    """

    def __init__(
        self,
        chain: Optional[AdmissionChain] = None,
        address: tuple[str, int] = ("127.0.0.1", 0),
        *,
        certfile: Optional[str] = None,
        keyfile: Optional[str] = None,
    ):
        self.chain = chain or default_admission_chain()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                if self.path == "/convert":
                    # CRD conversion-webhook contract (ConversionReview
                    # in/out) — the multi-version seam's wire surface
                    # (ref: conversion strategy Webhook; the reference
                    # serves work/v1alpha1 <-> v1alpha2 this way)
                    from ..api.versioning import handle_conversion_review

                    length = int(self.headers.get("Content-Length", 0))
                    try:
                        review = json.loads(self.rfile.read(length) or b"{}")
                        self._reply(200, handle_conversion_review(review))
                    except Exception as exc:  # noqa: BLE001 — wire surface
                        self._reply(400, {"error": str(exc)})
                    return
                if self.path != "/admit":
                    self._reply(404, {"allowed": False, "message": "not found"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                    kind = body["kind"]
                    obj = decode_object(kind, json.dumps(body["object"]))
                    if body.get("operation") == "DELETE":
                        outer.chain.admit_delete(kind, obj)
                    else:
                        outer.chain.admit(kind, obj)
                    self._reply(
                        200,
                        {
                            "allowed": True,
                            "object": json.loads(encode_object(obj)),
                        },
                    )
                except Exception as exc:  # noqa: BLE001 — wire surface
                    self._reply(200, {"allowed": False, "message": str(exc)})

            def _reply(self, status, payload):
                data = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer(address, Handler)
        self.scheme = "http"
        if certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile, keyfile)
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True
            )
            self.scheme = "https"
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"{self.scheme}://127.0.0.1:{self.port}/admit"

    def start(self) -> str:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.url

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class RemoteAdmission:
    """Store admission hooks that round-trip through the webhook process.

    ``Store(admission=remote.admit, delete_admission=remote.admit_delete)``
    makes every control-plane write call the external webhook — the
    reference's apiserver->webhook TLS hop. Mutations are copied back onto
    the caller's object; a denial (or a malformed response) raises;
    ``fail_open`` mirrors failurePolicy=Ignore for unreachable webhooks
    (default False = fail closed, the reference's default for its own
    policies)."""

    #: A freshly-spawned webhook process on an oversubscribed machine
    #: can take longer than the old fixed 5s to answer its FIRST request
    #: (TLS handshake + interpreter warm-up behind a full test suite) —
    #: the known spawn-family flake. The deadline is env-tunable
    #: (ADMISSION_TIMEOUT_ENV) and every request gets ONE bounded retry
    #: on an unreachable/timed-out channel (admission is a pure
    #: check/mutate, so the retry is idempotent by construction).
    TIMEOUT_ENV = ADMISSION_TIMEOUT_ENV

    def __init__(
        self,
        url: str,
        *,
        ca_bundle: Optional[bytes] = None,
        timeout_seconds: Optional[float] = None,
        fail_open: bool = False,
    ):
        import os

        self.url = url
        if timeout_seconds is None:
            raw = os.environ.get(ADMISSION_TIMEOUT_ENV, "").strip()
            try:
                timeout_seconds = float(raw) if raw else 5.0
            except ValueError:
                timeout_seconds = 5.0
        self.timeout = timeout_seconds
        self.fail_open = fail_open
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if ca_bundle is not None:
            self._ssl_ctx = ssl.create_default_context(cadata=ca_bundle.decode())

    #: transport retries per request (bounded: exactly one re-dial)
    RETRIES = 1

    def _post(self, kind: str, obj, operation: str):
        payload = json.dumps(
            {
                "kind": kind,
                "operation": operation,
                "object": json.loads(encode_object(obj)),
            }
        ).encode()
        body = None
        last_exc: Optional[Exception] = None
        for attempt in range(1 + self.RETRIES):
            req = urllib.request.Request(
                self.url, data=payload,
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(
                    req, timeout=self.timeout, context=self._ssl_ctx
                ) as resp:
                    body = json.loads(resp.read())
                break
            except (urllib.error.URLError, OSError) as exc:
                last_exc = exc
        if body is None:
            if self.fail_open:
                return None
            raise AdmissionDenied(
                f"admission webhook unreachable: {last_exc}"
            )
        if not body.get("allowed"):
            raise ValueError(body.get("message", "admission denied"))
        return body.get("object")

    def admit(self, kind: str, obj) -> None:
        mutated = self._post(kind, obj, "CREATE")
        if mutated is not None:
            new = decode_object(kind, json.dumps(mutated))
            obj.__dict__.update(new.__dict__)

    def admit_delete(self, kind: str, obj) -> None:
        self._post(kind, obj, "DELETE")


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--address", default="127.0.0.1:0")
    p.add_argument("--certfile", default="")
    p.add_argument("--keyfile", default="")
    args = p.parse_args(argv)
    from ..utils.net import parse_hostport

    server = AdmissionWebhookServer(
        address=parse_hostport(args.address, default_host=""),
        certfile=args.certfile or None,
        keyfile=args.keyfile or None,
    )
    url = server.start()
    # the parent process scrapes this line to learn the bound endpoint
    print(f"admission webhook listening on port {server.port} ({url})", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
