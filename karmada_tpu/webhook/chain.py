"""Admission chain: per-kind mutators then validators, run on store.apply."""

from __future__ import annotations

import uuid
from typing import Any, Callable, Optional

from ..api.policy import (
    DIVIDED,
    DUPLICATED,
    WEIGHTED,
    AGGREGATED,
    PropagationPolicy,
)

PERMANENT_ID_ANNOTATION = "policy.karmada.io/permanent-id"
PERMANENT_ID_LABEL = "work.karmada.io/permanent-id"
DELETION_PROTECTION_LABEL = "resourcetemplate.karmada.io/deletion-protected"
DELETION_PROTECTION_ALWAYS = "Always"


class ValidationError(Exception):
    """Admission rejection (webhook validate deny)."""


Mutator = Callable[[Any], None]
Validator = Callable[[Any], None]


class AdmissionChain:
    def __init__(self) -> None:
        self._mutators: dict[str, list[Mutator]] = {}
        self._validators: dict[str, list[Validator]] = {}
        self._delete_validators: dict[str, list[Validator]] = {}

    def register_mutator(self, kind: str, fn: Mutator) -> None:
        self._mutators.setdefault(kind, []).append(fn)

    def register_validator(self, kind: str, fn: Validator) -> None:
        self._validators.setdefault(kind, []).append(fn)

    def register_delete_validator(self, kind: str, fn: Validator) -> None:
        """Delete-operation admission ('*' = every kind); ref:
        resourcedeletionprotection/validating.go handles only Delete."""
        self._delete_validators.setdefault(kind, []).append(fn)

    def admit(self, kind: str, obj: Any) -> None:
        for fn in self._mutators.get(kind, []):
            fn(obj)
        for fn in self._validators.get(kind, []):
            fn(obj)

    def admit_delete(self, kind: str, obj: Any) -> None:
        for fn in self._delete_validators.get(kind, []) + self._delete_validators.get(
            "*", []
        ):
            fn(obj)


# --- mutators (defaulting; ref: pkg/webhook/*/mutating.go) -------------------


def mutate_propagation_policy(policy: PropagationPolicy) -> None:
    if PERMANENT_ID_ANNOTATION not in policy.meta.annotations:
        policy.meta.annotations[PERMANENT_ID_ANNOTATION] = str(uuid.uuid4())
    pl = policy.spec.placement
    for sc in pl.spread_constraints:
        if sc.min_groups <= 0:
            sc.min_groups = 1  # webhook defaults minGroups to 1
    if not policy.spec.scheduler_name:
        policy.spec.scheduler_name = "default-scheduler"
    if not policy.spec.conflict_resolution:
        policy.spec.conflict_resolution = "Abort"


def mutate_override_policy(policy) -> None:
    """Default resource-selector namespaces to the policy's namespace
    (overridepolicy/mutating.go)."""
    for sel in policy.spec.resource_selectors:
        if not getattr(sel, "namespace", "") and policy.meta.namespace:
            sel.namespace = policy.meta.namespace


def mutate_work(work) -> None:
    """Permanent-ID label + prune runtime fields from manifests
    (work/mutating.go: uuid label, prune.RemoveIrrelevantFields)."""
    from ..utils.clone import clone_resource

    if not work.meta.labels.get(PERMANENT_ID_LABEL):
        work.meta.labels[PERMANENT_ID_LABEL] = str(uuid.uuid4())
    # prune on copies: controllers may alias live store objects into
    # spec.workload, and mutating those in place would corrupt the store.
    # Already-pruned manifests (every re-apply of an existing Work — e.g.
    # condition updates) skip the copy entirely: nothing would change, so
    # there is nothing to protect. This runs on EVERY Work apply and the
    # deepcopy was the single largest cost of a propagation storm.
    pruned = []
    for manifest in work.spec.workload:
        if (
            not manifest.status
            and not manifest.meta.uid
            and manifest.meta.resource_version == 0
            and manifest.meta.creation_timestamp == 0.0
        ):
            pruned.append(manifest)
            continue
        manifest = clone_resource(manifest)
        manifest.status = {}
        manifest.meta.uid = ""
        manifest.meta.resource_version = 0
        manifest.meta.creation_timestamp = 0.0
        pruned.append(manifest)
    work.spec.workload = pruned


def mutate_binding_permanent_id(rb) -> None:
    """resourcebinding/clusterresourcebinding mutating.go."""
    if not rb.meta.labels.get(PERMANENT_ID_LABEL):
        rb.meta.labels[PERMANENT_ID_LABEL] = str(uuid.uuid4())


def mutate_multicluster_service(mcs) -> None:
    """multiclusterservice/mutating.go: permanent-ID label."""
    if not mcs.meta.labels.get(PERMANENT_ID_LABEL):
        mcs.meta.labels[PERMANENT_ID_LABEL] = str(uuid.uuid4())


def mutate_federated_hpa(hpa) -> None:
    """federatedhpa/mutating.go → lifted.SetDefaultsFederatedHPA: default
    only nil fields — an explicit invalid 0 must reach the validator."""
    if hpa.spec.min_replicas is None:
        hpa.spec.min_replicas = 1
    if hpa.spec.stabilization_window_seconds is None:
        hpa.spec.stabilization_window_seconds = 300


# --- validators (ref: pkg/webhook/*/validating.go) ---------------------------


def _validate_field_selector(aff) -> None:
    """util/validation.ValidatePolicyFieldSelector: only the cluster
    provider/region/zone fields are matchable, with In/NotIn."""
    if aff is None or aff.field_selector is None:
        return
    for req in aff.field_selector.match_expressions:
        if req.key not in ("provider", "region", "zone"):
            raise ValidationError(
                f"unsupported fieldSelector key {req.key!r} "
                "(only provider/region/zone)"
            )
        if req.operator not in ("In", "NotIn"):
            raise ValidationError(
                f"unsupported fieldSelector operator {req.operator!r}"
            )


def validate_placement(pl) -> None:
    if pl is None:
        return
    if pl.cluster_affinity is not None and pl.cluster_affinities:
        raise ValidationError(
            "clusterAffinity and clusterAffinities are mutually exclusive"
        )
    _validate_field_selector(pl.cluster_affinity)
    for term in pl.cluster_affinities:
        _validate_field_selector(term)
    names = [t.affinity_name for t in pl.cluster_affinities]
    if len(names) != len(set(names)):
        raise ValidationError("clusterAffinities names must be unique")
    if any(not n for n in names):
        raise ValidationError("clusterAffinities entries need affinityName")
    by_field = {}
    for sc in pl.spread_constraints:
        if sc.spread_by_field and sc.spread_by_label:
            raise ValidationError(
                "spreadByField and spreadByLabel are mutually exclusive"
            )
        if sc.spread_by_field:
            if sc.spread_by_field not in ("cluster", "zone", "region", "provider"):
                raise ValidationError(
                    f"invalid spreadByField {sc.spread_by_field!r}"
                )
            if sc.spread_by_field in by_field:
                raise ValidationError(
                    f"duplicate spread constraint for {sc.spread_by_field}"
                )
            by_field[sc.spread_by_field] = sc
        if sc.max_groups and sc.max_groups < sc.min_groups:
            raise ValidationError("maxGroups must be >= minGroups")
        if sc.max_groups < 0 or sc.min_groups < 0:
            raise ValidationError("spread constraint groups must be >= 0")
    # a region/provider/zone constraint requires cluster-or-region selection
    # support (select_clusters.go:58)
    rs = pl.replica_scheduling
    if rs is not None:
        if rs.replica_scheduling_type not in ("", DUPLICATED, DIVIDED):
            raise ValidationError(
                f"invalid replicaSchedulingType {rs.replica_scheduling_type!r}"
            )
        if rs.replica_scheduling_type == DIVIDED and rs.replica_division_preference:
            if rs.replica_division_preference not in (AGGREGATED, WEIGHTED):
                raise ValidationError(
                    f"invalid replicaDivisionPreference "
                    f"{rs.replica_division_preference!r}"
                )
        wp = rs.weight_preference
        if wp is not None:
            for entry in wp.static_weight_list:
                if entry.weight < 1:
                    raise ValidationError("static weights must be >= 1")
            if wp.dynamic_weight and wp.dynamic_weight != "AvailableReplicas":
                raise ValidationError(
                    f"invalid dynamicWeight factor {wp.dynamic_weight!r}"
                )


def validate_propagation_policy(policy: PropagationPolicy) -> None:
    if not policy.spec.resource_selectors:
        raise ValidationError("resourceSelectors must not be empty")
    # kubebuilder enum on ActivationPreference (propagation_types.go:176)
    if getattr(policy.spec, "activation_preference", "") not in ("", "Lazy"):
        raise ValidationError(
            f"invalid activationPreference "
            f"{policy.spec.activation_preference!r} (must be Lazy or empty)"
        )
    validate_placement(policy.spec.placement)
    fo = policy.spec.failover
    if fo is not None and fo.application is not None:
        app = fo.application
        if app.decision_conditions_toleration_seconds < 0:
            raise ValidationError("tolerationSeconds must be >= 0")
        if app.purge_mode not in ("Immediately", "Graciously", "Never"):
            raise ValidationError(f"invalid purgeMode {app.purge_mode!r}")


def validate_override_policy(policy) -> None:
    for rule in policy.spec.override_rules:
        for po in rule.overriders.plaintext:
            if po.operator not in ("add", "remove", "replace"):
                raise ValidationError(f"invalid plaintext operator {po.operator!r}")
            if not po.path.startswith("/"):
                raise ValidationError("plaintext path must start with '/'")
        for io in rule.overriders.image_overrider:
            if io.component not in ("Registry", "Repository", "Tag"):
                raise ValidationError(f"invalid image component {io.component!r}")
        for fo in getattr(rule.overriders, "field_overrider", []):
            # one instance processes either JSON or YAML, never both
            # (override_types.go:270)
            if fo.json and fo.yaml:
                raise ValidationError(
                    "fieldOverrider carries either json or yaml operations, "
                    "not both"
                )
            if not fo.field_path.startswith("/"):
                raise ValidationError("fieldOverrider fieldPath must start with '/'")
            for op in fo.json + fo.yaml:
                if op.operator not in ("add", "remove", "replace"):
                    raise ValidationError(
                        f"invalid fieldOverrider operator {op.operator!r}"
                    )


def validate_federated_resource_quota(frq) -> None:
    for assignment in frq.spec.static_assignments:
        for res, v in assignment.hard.items():
            if v < 0:
                raise ValidationError("quota values must be >= 0")
            if res not in frq.spec.overall:
                raise ValidationError(
                    f"static assignment resource {res!r} missing from overall"
                )
    totals: dict[str, int] = {}
    for assignment in frq.spec.static_assignments:
        for res, v in assignment.hard.items():
            totals[res] = totals.get(res, 0) + v
    for res, total in totals.items():
        if total > frq.spec.overall.get(res, 0):
            raise ValidationError(
                f"static assignments for {res!r} exceed the overall quota"
            )
    # quota-shrink guard (the reference validates spec updates against
    # live usage): an update that CHANGES overall — spec.overall differs
    # from the last-reconciled status.overall — must not drop any tracked
    # resource below current status.overall_used. The status controller's
    # own writes always carry status.overall == spec.overall (it syncs
    # them in the same reconcile), so recording over-usage that predates a
    # quota (bindings bound before the FRQ existed) is never blocked.
    used = frq.status.overall_used or {}
    for res, limit in frq.spec.overall.items():
        if (
            frq.status.overall.get(res) != limit
            and used.get(res, 0) > limit
        ):
            raise ValidationError(
                f"cannot shrink overall[{res!r}] to {limit} below current "
                f"usage {used[res]}"
            )


def validate_resource_binding(rb) -> None:
    if rb.spec.replicas < 0:
        raise ValidationError("replicas must be >= 0")
    validate_placement(rb.spec.placement)


def validate_federated_hpa(hpa) -> None:
    if hpa.spec.min_replicas < 1:
        raise ValidationError("minReplicas must be >= 1")
    if hpa.spec.max_replicas < hpa.spec.min_replicas:
        raise ValidationError("maxReplicas must be >= minReplicas")
    if not hpa.spec.scale_target_ref.name:
        raise ValidationError("scaleTargetRef.name is required")
    for m in hpa.spec.metrics:
        if (
            m.target_average_utilization is not None
            and not 1 <= m.target_average_utilization <= 100
        ):
            raise ValidationError("targetAverageUtilization must be in [1, 100]")


def validate_cron_federated_hpa(cron) -> None:
    from ..utils.cron import _parse_field

    names = [r.name for r in cron.spec.rules]
    if len(names) != len(set(names)):
        raise ValidationError("rule names must be unique")
    for rule in cron.spec.rules:
        fields = rule.schedule.split()
        if len(fields) != 5:
            raise ValidationError(f"invalid cron schedule {rule.schedule!r}")
        try:
            for f, lo, hi in zip(fields, (0, 0, 1, 1, 0), (59, 23, 31, 12, 6)):
                _parse_field(f, lo, hi)
        except (ValueError, IndexError) as e:
            raise ValidationError(f"invalid cron schedule {rule.schedule!r}: {e}")
        if (
            rule.target_replicas is None
            and rule.target_min_replicas is None
            and rule.target_max_replicas is None
        ):
            raise ValidationError(
                f"rule {rule.name!r} must set targetReplicas or min/max bounds"
            )


def validate_multicluster_service(mcs) -> None:
    valid_types = {"CrossCluster", "LoadBalancer"}
    for t in mcs.spec.types:
        if t not in valid_types:
            raise ValidationError(f"invalid exposure type {t!r}")


def _validate_health_predicate(pred: dict) -> None:
    if "any" in pred:
        for sub in pred["any"]:
            _validate_health_predicate(sub)
        return
    if "condition" in pred or pred.get("observed_generation"):
        return
    if "path" not in pred:
        raise ValidationError(f"health predicate needs a path: {pred!r}")
    if pred.get("op", "==") not in ("==", "!=", ">=", "<=", "in", "exists"):
        raise ValidationError(f"invalid health op {pred.get('op')!r}")


def validate_interpreter_customization(cr) -> None:
    if not cr.target_api_version or not cr.target_kind:
        raise ValidationError("customization target apiVersion/kind required")
    for pred in cr.rules.health:
        _validate_health_predicate(pred)
    for fname, how in cr.rules.status_aggregation.items():
        if how not in ("sum", "max", "min", "last", "and", "or"):
            raise ValidationError(f"invalid aggregation {how!r} for {fname!r}")


SUPPORTED_INTERPRETER_OPERATIONS = {
    "*", "InterpretReplica", "ReviseReplica", "Retain", "AggregateStatus",
    "InterpretDependency", "InterpretStatus", "InterpretHealth",
}


def validate_interpreter_webhook_configuration(config) -> None:
    """configuration/validating.go: unique hook names, resolvable client
    config, known operations."""
    seen = set()
    for hook in config.webhooks:
        if not hook.name:
            raise ValidationError("webhook name is required")
        if hook.name in seen:
            raise ValidationError(f"duplicate webhook name {hook.name!r}")
        seen.add(hook.name)
        if not hook.client_config.url:
            raise ValidationError(f"webhook {hook.name!r} needs clientConfig.url")
        if not hook.rules:
            raise ValidationError(f"webhook {hook.name!r} needs at least one rule")
        for rule in hook.rules:
            bad = set(rule.operations) - SUPPORTED_INTERPRETER_OPERATIONS
            if bad:
                raise ValidationError(
                    f"webhook {hook.name!r}: unsupported operations {sorted(bad)}"
                )
            if not rule.api_versions or not rule.kinds:
                raise ValidationError(
                    f"webhook {hook.name!r}: rules need apiVersions and kinds"
                )


def validate_multicluster_ingress(mci) -> None:
    """multiclusteringress/validating.go: ingress rule sanity."""
    for rule in mci.spec.rules:
        for path in (rule.get("http") or {}).get("paths", []):
            # unset pathType defaults to ImplementationSpecific (k8s default)
            ptype = path.get("pathType") or "ImplementationSpecific"
            if ptype not in ("Exact", "Prefix", "ImplementationSpecific"):
                raise ValidationError(f"invalid pathType {ptype!r}")
            if ptype in ("Exact", "Prefix") and not str(
                path.get("path", "")
            ).startswith("/"):
                raise ValidationError("ingress path must be absolute")
            backend = path.get("backend") or {}
            if not (backend.get("service") or {}).get("name"):
                raise ValidationError("ingress backend service name required")


def validate_deletion_protection(obj) -> None:
    """resourcedeletionprotection/validating.go: deny Delete while the
    protection label is Always."""
    labels = getattr(obj.meta, "labels", None) or {}
    if labels.get(DELETION_PROTECTION_LABEL) == DELETION_PROTECTION_ALWAYS:
        raise ValidationError(
            "this resource is protected, remove the label "
            f"{DELETION_PROTECTION_LABEL} to delete it"
        )


def validate_workload_rebalancer(rebalancer) -> None:
    if not rebalancer.spec.workloads:
        raise ValidationError("workloads must not be empty")


def validate_work(work) -> None:
    ref = getattr(work.spec, "workload_template", None)
    if not work.spec.workload and not (ref is not None and ref.digest):
        # template-delta works carry (digest, patch) instead of a full
        # manifest — either representation satisfies the invariant
        raise ValidationError("work must carry at least one manifest")
    if work.spec.conflict_resolution not in ("Overwrite", "Abort"):
        raise ValidationError(
            f"invalid conflictResolution {work.spec.conflict_resolution!r}"
        )


def mutate_cluster(cluster) -> None:
    """Cluster defaulting (apis/cluster/mutation/mutation.go): when the
    CustomizedClusterResourceModeling gate is on, an empty resourceModels
    gets the nine default cpu/memory grades; declared models standardize
    (grade-sorted, first min 0, last max open)."""
    from ..api.cluster import default_resource_models, standardize_resource_models
    from ..utils.features import CUSTOMIZED_CLUSTER_RESOURCE_MODELING, feature_gate

    if not feature_gate.enabled(CUSTOMIZED_CLUSTER_RESOURCE_MODELING):
        return
    if not cluster.spec.resource_models:
        cluster.spec.resource_models = default_resource_models()
    else:
        standardize_resource_models(cluster.spec.resource_models)


def validate_cluster(cluster) -> None:
    """Cluster invariants (apis/cluster/validation/validation.go): DNS-ish
    name <= 48 chars, a supported sync mode, and a contiguous gapless model
    ladder (same resource set per grade, max > min, each min = previous
    max, first mins 0, last maxes MaxInt64). Runs after mutate_cluster, so
    standardized/defaulted models must pass."""
    import re

    from ..api.cluster import MAX_INT64

    name = cluster.meta.name
    if not name or len(name) > 48 or not re.fullmatch(
        r"[a-z0-9]([-a-z0-9]*[a-z0-9])?", name
    ):
        raise ValidationError(
            f"invalid cluster name {name!r} (DNS-1123 label, max 48 chars)"
        )
    if cluster.spec.sync_mode not in ("Push", "Pull"):
        raise ValidationError(
            f"invalid syncMode {cluster.spec.sync_mode!r} (Push or Pull)"
        )
    models = cluster.spec.resource_models
    for i, model in enumerate(models):
        if i and model.grade == models[i - 1].grade:
            raise ValidationError("model grades must be distinct")
        if i and len(models[i - 1].ranges) != len(model.ranges):
            raise ValidationError("models must cover the same resource count")
        for j, rng in enumerate(model.ranges):
            if rng.max <= rng.min:
                raise ValidationError("model range max must exceed min")
            if i == 0:
                if rng.min != 0:
                    raise ValidationError("first grade minimums must be 0")
            else:
                prev = models[i - 1].ranges[j]
                if prev.name != rng.name:
                    raise ValidationError(
                        "models must cover the same resources in order"
                    )
                if prev.max != rng.min:
                    raise ValidationError(
                        "model intervals must be contiguous and non-overlapping"
                    )
            if i == len(models) - 1 and rng.max != MAX_INT64:
                raise ValidationError("last grade maximums must be MaxInt64")


def default_admission_chain() -> AdmissionChain:
    """The full reference handler set (cmd/webhook/app/webhook.go:161-183;
    /convert is N/A — no CRD versioning in-proc)."""
    chain = AdmissionChain()
    chain.register_mutator("Cluster", mutate_cluster)
    chain.register_validator("Cluster", validate_cluster)
    for kind in ("PropagationPolicy", "ClusterPropagationPolicy"):
        chain.register_mutator(kind, mutate_propagation_policy)
        chain.register_validator(kind, validate_propagation_policy)
    chain.register_mutator("OverridePolicy", mutate_override_policy)
    for kind in ("OverridePolicy", "ClusterOverridePolicy"):
        chain.register_validator(kind, validate_override_policy)
    chain.register_validator("FederatedResourceQuota", validate_federated_resource_quota)
    for kind in ("ResourceBinding", "ClusterResourceBinding"):
        chain.register_mutator(kind, mutate_binding_permanent_id)
        chain.register_validator(kind, validate_resource_binding)
    chain.register_mutator("FederatedHPA", mutate_federated_hpa)
    chain.register_validator("FederatedHPA", validate_federated_hpa)
    chain.register_validator("CronFederatedHPA", validate_cron_federated_hpa)
    chain.register_mutator("MultiClusterService", mutate_multicluster_service)
    chain.register_validator("MultiClusterService", validate_multicluster_service)
    chain.register_validator("MultiClusterIngress", validate_multicluster_ingress)
    chain.register_validator(
        "ResourceInterpreterCustomization", validate_interpreter_customization
    )
    chain.register_validator(
        "ResourceInterpreterWebhookConfiguration",
        validate_interpreter_webhook_configuration,
    )
    chain.register_validator("WorkloadRebalancer", validate_workload_rebalancer)
    chain.register_mutator("Work", mutate_work)
    chain.register_validator("Work", validate_work)
    chain.register_delete_validator("*", validate_deletion_protection)
    return chain
