"""Admission chain: per-kind mutators then validators, run on store.apply."""

from __future__ import annotations

import uuid
from typing import Any, Callable, Optional

from ..api.policy import (
    DIVIDED,
    DUPLICATED,
    WEIGHTED,
    AGGREGATED,
    PropagationPolicy,
)

PERMANENT_ID_ANNOTATION = "policy.karmada.io/permanent-id"


class ValidationError(Exception):
    """Admission rejection (webhook validate deny)."""


Mutator = Callable[[Any], None]
Validator = Callable[[Any], None]


class AdmissionChain:
    def __init__(self) -> None:
        self._mutators: dict[str, list[Mutator]] = {}
        self._validators: dict[str, list[Validator]] = {}

    def register_mutator(self, kind: str, fn: Mutator) -> None:
        self._mutators.setdefault(kind, []).append(fn)

    def register_validator(self, kind: str, fn: Validator) -> None:
        self._validators.setdefault(kind, []).append(fn)

    def admit(self, kind: str, obj: Any) -> None:
        for fn in self._mutators.get(kind, []):
            fn(obj)
        for fn in self._validators.get(kind, []):
            fn(obj)


# --- mutators (defaulting; ref: pkg/webhook/*/mutating.go) -------------------


def mutate_propagation_policy(policy: PropagationPolicy) -> None:
    if PERMANENT_ID_ANNOTATION not in policy.meta.annotations:
        policy.meta.annotations[PERMANENT_ID_ANNOTATION] = str(uuid.uuid4())
    pl = policy.spec.placement
    for sc in pl.spread_constraints:
        if sc.min_groups <= 0:
            sc.min_groups = 1  # webhook defaults minGroups to 1
    if not policy.spec.scheduler_name:
        policy.spec.scheduler_name = "default-scheduler"
    if not policy.spec.conflict_resolution:
        policy.spec.conflict_resolution = "Abort"


# --- validators (ref: pkg/webhook/*/validating.go) ---------------------------


def validate_placement(pl) -> None:
    if pl is None:
        return
    if pl.cluster_affinity is not None and pl.cluster_affinities:
        raise ValidationError(
            "clusterAffinity and clusterAffinities are mutually exclusive"
        )
    names = [t.affinity_name for t in pl.cluster_affinities]
    if len(names) != len(set(names)):
        raise ValidationError("clusterAffinities names must be unique")
    if any(not n for n in names):
        raise ValidationError("clusterAffinities entries need affinityName")
    by_field = {}
    for sc in pl.spread_constraints:
        if sc.spread_by_field and sc.spread_by_label:
            raise ValidationError(
                "spreadByField and spreadByLabel are mutually exclusive"
            )
        if sc.spread_by_field:
            if sc.spread_by_field not in ("cluster", "zone", "region", "provider"):
                raise ValidationError(
                    f"invalid spreadByField {sc.spread_by_field!r}"
                )
            if sc.spread_by_field in by_field:
                raise ValidationError(
                    f"duplicate spread constraint for {sc.spread_by_field}"
                )
            by_field[sc.spread_by_field] = sc
        if sc.max_groups and sc.max_groups < sc.min_groups:
            raise ValidationError("maxGroups must be >= minGroups")
        if sc.max_groups < 0 or sc.min_groups < 0:
            raise ValidationError("spread constraint groups must be >= 0")
    # a region/provider/zone constraint requires cluster-or-region selection
    # support (select_clusters.go:58)
    rs = pl.replica_scheduling
    if rs is not None:
        if rs.replica_scheduling_type not in ("", DUPLICATED, DIVIDED):
            raise ValidationError(
                f"invalid replicaSchedulingType {rs.replica_scheduling_type!r}"
            )
        if rs.replica_scheduling_type == DIVIDED and rs.replica_division_preference:
            if rs.replica_division_preference not in (AGGREGATED, WEIGHTED):
                raise ValidationError(
                    f"invalid replicaDivisionPreference "
                    f"{rs.replica_division_preference!r}"
                )
        wp = rs.weight_preference
        if wp is not None:
            for entry in wp.static_weight_list:
                if entry.weight < 1:
                    raise ValidationError("static weights must be >= 1")
            if wp.dynamic_weight and wp.dynamic_weight != "AvailableReplicas":
                raise ValidationError(
                    f"invalid dynamicWeight factor {wp.dynamic_weight!r}"
                )


def validate_propagation_policy(policy: PropagationPolicy) -> None:
    if not policy.spec.resource_selectors:
        raise ValidationError("resourceSelectors must not be empty")
    validate_placement(policy.spec.placement)
    fo = policy.spec.failover
    if fo is not None and fo.application is not None:
        app = fo.application
        if app.decision_conditions_toleration_seconds < 0:
            raise ValidationError("tolerationSeconds must be >= 0")
        if app.purge_mode not in ("Immediately", "Graciously", "Never"):
            raise ValidationError(f"invalid purgeMode {app.purge_mode!r}")


def validate_override_policy(policy) -> None:
    for rule in policy.spec.override_rules:
        for po in rule.overriders.plaintext:
            if po.operator not in ("add", "remove", "replace"):
                raise ValidationError(f"invalid plaintext operator {po.operator!r}")
            if not po.path.startswith("/"):
                raise ValidationError("plaintext path must start with '/'")
        for io in rule.overriders.image_overrider:
            if io.component not in ("Registry", "Repository", "Tag"):
                raise ValidationError(f"invalid image component {io.component!r}")


def validate_federated_resource_quota(frq) -> None:
    for assignment in frq.spec.static_assignments:
        for res, v in assignment.hard.items():
            if v < 0:
                raise ValidationError("quota values must be >= 0")
            if res not in frq.spec.overall:
                raise ValidationError(
                    f"static assignment resource {res!r} missing from overall"
                )
    totals: dict[str, int] = {}
    for assignment in frq.spec.static_assignments:
        for res, v in assignment.hard.items():
            totals[res] = totals.get(res, 0) + v
    for res, total in totals.items():
        if total > frq.spec.overall.get(res, 0):
            raise ValidationError(
                f"static assignments for {res!r} exceed the overall quota"
            )


def validate_resource_binding(rb) -> None:
    if rb.spec.replicas < 0:
        raise ValidationError("replicas must be >= 0")
    validate_placement(rb.spec.placement)


def validate_federated_hpa(hpa) -> None:
    if hpa.spec.min_replicas < 1:
        raise ValidationError("minReplicas must be >= 1")
    if hpa.spec.max_replicas < hpa.spec.min_replicas:
        raise ValidationError("maxReplicas must be >= minReplicas")
    if not hpa.spec.scale_target_ref.name:
        raise ValidationError("scaleTargetRef.name is required")
    for m in hpa.spec.metrics:
        if (
            m.target_average_utilization is not None
            and not 1 <= m.target_average_utilization <= 100
        ):
            raise ValidationError("targetAverageUtilization must be in [1, 100]")


def validate_cron_federated_hpa(cron) -> None:
    from ..utils.cron import _parse_field

    names = [r.name for r in cron.spec.rules]
    if len(names) != len(set(names)):
        raise ValidationError("rule names must be unique")
    for rule in cron.spec.rules:
        fields = rule.schedule.split()
        if len(fields) != 5:
            raise ValidationError(f"invalid cron schedule {rule.schedule!r}")
        try:
            for f, lo, hi in zip(fields, (0, 0, 1, 1, 0), (59, 23, 31, 12, 6)):
                _parse_field(f, lo, hi)
        except (ValueError, IndexError) as e:
            raise ValidationError(f"invalid cron schedule {rule.schedule!r}: {e}")
        if (
            rule.target_replicas is None
            and rule.target_min_replicas is None
            and rule.target_max_replicas is None
        ):
            raise ValidationError(
                f"rule {rule.name!r} must set targetReplicas or min/max bounds"
            )


def validate_multicluster_service(mcs) -> None:
    valid_types = {"CrossCluster", "LoadBalancer"}
    for t in mcs.spec.types:
        if t not in valid_types:
            raise ValidationError(f"invalid exposure type {t!r}")


def validate_interpreter_customization(cr) -> None:
    if not cr.target_api_version or not cr.target_kind:
        raise ValidationError("customization target apiVersion/kind required")
    for pred in cr.rules.health:
        if pred.get("op", "==") not in ("==", ">=", "<="):
            raise ValidationError(f"invalid health op {pred.get('op')!r}")
    for fname, how in cr.rules.status_aggregation.items():
        if how not in ("sum", "max", "min"):
            raise ValidationError(f"invalid aggregation {how!r} for {fname!r}")


def validate_workload_rebalancer(rebalancer) -> None:
    if not rebalancer.spec.workloads:
        raise ValidationError("workloads must not be empty")


def validate_work(work) -> None:
    if not work.spec.workload:
        raise ValidationError("work must carry at least one manifest")
    if work.spec.conflict_resolution not in ("Overwrite", "Abort"):
        raise ValidationError(
            f"invalid conflictResolution {work.spec.conflict_resolution!r}"
        )


def default_admission_chain() -> AdmissionChain:
    chain = AdmissionChain()
    for kind in ("PropagationPolicy", "ClusterPropagationPolicy"):
        chain.register_mutator(kind, mutate_propagation_policy)
        chain.register_validator(kind, validate_propagation_policy)
    for kind in ("OverridePolicy", "ClusterOverridePolicy"):
        chain.register_validator(kind, validate_override_policy)
    chain.register_validator("FederatedResourceQuota", validate_federated_resource_quota)
    for kind in ("ResourceBinding", "ClusterResourceBinding"):
        chain.register_validator(kind, validate_resource_binding)
    chain.register_validator("FederatedHPA", validate_federated_hpa)
    chain.register_validator("CronFederatedHPA", validate_cron_federated_hpa)
    chain.register_validator("MultiClusterService", validate_multicluster_service)
    chain.register_validator(
        "ResourceInterpreterCustomization", validate_interpreter_customization
    )
    chain.register_validator("WorkloadRebalancer", validate_workload_rebalancer)
    chain.register_validator("Work", validate_work)
    return chain
