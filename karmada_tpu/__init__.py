"""karmada_tpu — a TPU-native multi-cluster orchestration framework.

A ground-up rebuild of the capabilities of Karmada (the CNCF multi-cloud
Kubernetes orchestrator, studied at /root/reference) with a TPU-first
architecture: the scheduler's Filter/Score/Select/AssignReplicas hot path is a
batched JAX kernel over a ``(bindings x clusters x resource-dims)`` tensor
program, while the control plane around it (store, controllers, estimators,
interpreter) is an idiomatic Python reconciliation runtime.

Layer map (mirrors SURVEY.md section 1):

- :mod:`karmada_tpu.api`        — typed data model (ref: pkg/apis/*)
- :mod:`karmada_tpu.utils`      — store/watch bus, workers, quantities
                                  (ref: pkg/util)
- :mod:`karmada_tpu.ops`        — pure jittable tensor kernels: bitset masks,
                                  the vectorized Dispenser, division strategies
- :mod:`karmada_tpu.scheduler`  — snapshot packing + plugin framework + the
                                  batched scheduling core (ref: pkg/scheduler)
- :mod:`karmada_tpu.estimator`  — general + accurate capacity estimators
                                  (ref: pkg/estimator)
- :mod:`karmada_tpu.models`     — cluster resource modeling / grade buckets
                                  (ref: pkg/modeling)
- :mod:`karmada_tpu.controllers`— propagation/status/failover reconcilers
                                  (ref: pkg/controllers, pkg/detector)
- :mod:`karmada_tpu.interpreter`— resource interpreter facade
                                  (ref: pkg/resourceinterpreter)
- :mod:`karmada_tpu.parallel`   — device-mesh sharding of the solver
- :mod:`karmada_tpu.refimpl`    — pure-Python oracle of the reference's
                                  division semantics (test baseline)
"""

__version__ = "0.1.0"

# Child-process platform policy, applied at the earliest possible import
# point: `python -m karmada_tpu.<component>` executes package __init__s
# BEFORE the entry module, and submodule imports materialize jax constants
# that would initialize the (single-client) accelerator backend. No-op
# unless the parent set KARMADA_TPU_PLATFORM (see utils/platform.py).
import os as _os

if _os.environ.get("KARMADA_TPU_PLATFORM"):
    from .utils.platform import apply_child_platform as _acp

    _acp()
