"""Bitset machinery for label/taint/GVK matching at tensor speed.

Label selectors, tolerations, and API enablement are the O(bindings x
clusters) constant factor of the reference's filter loop
(framework/plugins/*). Here every string universe is interned into a bit
vocabulary (label key=value pairs, label keys, taint triples, GVKs) and packed
into uint32 words, so a full selector evaluates as a handful of AND/OR/
popcount ops over ``[C, words]`` arrays — no string work on the hot path.

These helpers are backend-agnostic: they accept numpy or jax arrays (the
snapshot builder uses numpy once per snapshot; kernels can run them on
device).
"""

from __future__ import annotations

from typing import Iterable, Mapping, Sequence

import numpy as np

WORD = 32


class Vocab:
    """String -> bit-id interning table."""

    def __init__(self) -> None:
        self._ids: dict[str, int] = {}

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._ids)
            self._ids[s] = i
        return i

    def get(self, s: str) -> int | None:
        return self._ids.get(s)

    def __len__(self) -> int:
        return len(self._ids)

    def __contains__(self, s: str) -> bool:
        return s in self._ids

    @property
    def words(self) -> int:
        return max(1, (len(self._ids) + WORD - 1) // WORD)


def pack_bits(rows: Sequence[Iterable[int]], words: int) -> np.ndarray:
    """Pack per-row bit-id lists into uint32[rows, words]."""
    out = np.zeros((len(rows), words), dtype=np.uint32)
    for r, ids in enumerate(rows):
        for i in ids:
            out[r, i // WORD] |= np.uint32(1) << np.uint32(i % WORD)
    return out


def bits_from_ids(ids: Iterable[int], words: int) -> np.ndarray:
    """Pack one bit-id list into uint32[words]."""
    return pack_bits([list(ids)], words)[0]


def contains_all(bits, require) -> np.ndarray:
    """bool[...]: every bit of ``require`` present in ``bits``.
    bits: uint32[..., W]; require: uint32[W] (broadcast)."""
    return ((bits & require) == require).all(axis=-1)


# row_coupled: the graftlint-dep delta-safety declaration (row i of the
# output reads only row i of ``bits``) — certified against the jaxpr by
# IR006, see tools/graftlint/dep.py
contains_all.row_coupled = False


def intersects(bits, other) -> np.ndarray:
    """bool[...]: any common bit."""
    return ((bits & other) != 0).any(axis=-1)


intersects.row_coupled = False  # per-row word reduce; IR006-certified


def affinity_group_rank(term_masks: np.ndarray) -> np.ndarray:
    """int32[..., C] ordered-failover rank tensor: for each cluster, the
    index of the FIRST affinity term (ClusterAffinities fallback group)
    whose mask contains it, ``T`` where none does (scheduler.go:533-596's
    group order as data instead of control flow). ``term_masks``:
    bool[..., T, C]."""
    t = term_masks.shape[-2]
    idx = np.where(
        term_masks,
        np.arange(t, dtype=np.int32).reshape((t, 1)),
        np.int32(t),
    )
    return idx.min(axis=-2)


def first_fit_group(
    cand_tc: np.ndarray,  # bool[B, T, C] per-term candidate sets
    term_len: np.ndarray,  # int32[B] live terms per row (<= T)
    avail: np.ndarray,  # int64[B, C] merged estimator availability
    replicas: np.ndarray,  # int64[B]
    prev: np.ndarray,  # int64[B, C] previous placements
    dynamic: np.ndarray,  # bool[B] divided dynamic-family strategy
    fresh: np.ndarray,  # bool[B] reschedule-triggered
) -> tuple[np.ndarray, np.ndarray]:
    """Batched ordered-failover group selection: each row's FIRST term
    whose candidate set both exists and passes the divider's
    schedulability predicate — the exact cohort math of
    ``refimpl.divider_np.assign_batch_np`` (fresh credits prev, scale-down
    weighs FULL prev, scale-up targets the shortfall, steady no-ops), so
    selecting group t here and then solving once is placement-identical
    to solving groups 0..t in sequence and keeping the first success.

    Returns ``(rank int32[B], fit bool[B])``; rows where NO group fits get
    their LAST live term (its solve produces the failure the per-round
    loop would have reported). The T axis is a short host loop (T = max
    ClusterAffinities length, almost always <= 4) over fully-batched
    [B, C] reductions — O(B*T*C) adds, no [B, T, C] integer temporaries.
    """
    if isinstance(cand_tc, np.ndarray):
        return _first_fit_group_kernel(
            np, cand_tc, term_len, avail, replicas, prev, dynamic, fresh,
        )
    import jax.numpy as jnp  # device path: lazy so masks stays jax-free

    return _first_fit_group_kernel(
        jnp, cand_tc, term_len, avail, replicas, prev, dynamic, fresh,
    )


# the cohort selection consumes plane-merged availability: per-row over
# B, but changing any binding moves avail for every other row (the
# graftlint-dep plane channel; see tools/graftlint/dep.py)
first_fit_group.row_coupled = True


def _first_fit_group_kernel(
    xp, cand_tc, term_len, avail, replicas, prev, dynamic, fresh
):
    """Backend-generic body of :func:`first_fit_group` (xp is numpy for
    the snapshot path, jax.numpy under a trace)."""
    _b, t, _c = cand_tc.shape
    num = replicas.astype(xp.int64)
    prev_full_sum = prev.sum(axis=1)
    cand_any = cand_tc.any(axis=2)
    # per-term masked sums as a stack over the short static T axis (the
    # same O(B*T*C) adds as the old in-place fill, but expressible on
    # immutable device arrays)
    avail_sum = xp.stack(
        [xp.where(cand_tc[:, ti, :], avail, 0).sum(axis=1)
         for ti in range(t)],
        axis=1,
    )
    prev_sum = xp.stack(
        [xp.where(cand_tc[:, ti, :], prev, 0).sum(axis=1)
         for ti in range(t)],
        axis=1,
    )
    dyn = dynamic[:, None]
    fr = fresh[:, None]
    num_col = num[:, None]
    scale_down = dyn & ~fr & (prev_sum > num_col)
    scale_up = dyn & ~fr & (prev_sum < num_col)
    steady = dyn & ~fr & (prev_sum == num_col)
    target = xp.where(scale_up, num_col - prev_sum, num_col)
    w_sum = xp.where(
        fr,
        avail_sum + prev_sum,
        xp.where(scale_down, prev_full_sum[:, None], avail_sum),
    )
    unsched = dyn & ~steady & (w_sum < target)
    live = xp.arange(t, dtype=xp.int32)[None, :] < term_len[:, None]
    fit_t = cand_any & ~unsched & live
    fit = fit_t.any(axis=1)
    # first-fitting-group extraction: first-true-index over the T axis
    # (affinity_group_rank's primitive, inlined backend-generically)
    term_idx = xp.arange(t, dtype=xp.int32)[None, :]
    rank = xp.where(fit_t, term_idx, xp.int32(t)).min(axis=1)
    last = xp.maximum(term_len - 1, 0).astype(xp.int32)
    return xp.where(fit, rank, last).astype(xp.int32), fit


def label_pair(key: str, value: str) -> str:
    return f"{key}={value}"


def intern_labels(vocab: Vocab, key_vocab: Vocab, labels: Mapping[str, str]) -> tuple[list[int], list[int]]:
    """Intern a label map into (pair_ids, key_ids)."""
    pair_ids = [vocab.intern(label_pair(k, v)) for k, v in labels.items()]
    key_ids = [key_vocab.intern(k) for k in labels]
    return pair_ids, key_ids
