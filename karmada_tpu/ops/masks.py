"""Bitset machinery for label/taint/GVK matching at tensor speed.

Label selectors, tolerations, and API enablement are the O(bindings x
clusters) constant factor of the reference's filter loop
(framework/plugins/*). Here every string universe is interned into a bit
vocabulary (label key=value pairs, label keys, taint triples, GVKs) and packed
into uint32 words, so a full selector evaluates as a handful of AND/OR/
popcount ops over ``[C, words]`` arrays — no string work on the hot path.

These helpers are backend-agnostic: they accept numpy or jax arrays (the
snapshot builder uses numpy once per snapshot; kernels can run them on
device).
"""

from __future__ import annotations

from typing import Iterable, Mapping, Sequence

import numpy as np

WORD = 32


class Vocab:
    """String -> bit-id interning table."""

    def __init__(self) -> None:
        self._ids: dict[str, int] = {}

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self._ids)
            self._ids[s] = i
        return i

    def get(self, s: str) -> int | None:
        return self._ids.get(s)

    def __len__(self) -> int:
        return len(self._ids)

    def __contains__(self, s: str) -> bool:
        return s in self._ids

    @property
    def words(self) -> int:
        return max(1, (len(self._ids) + WORD - 1) // WORD)


def pack_bits(rows: Sequence[Iterable[int]], words: int) -> np.ndarray:
    """Pack per-row bit-id lists into uint32[rows, words]."""
    out = np.zeros((len(rows), words), dtype=np.uint32)
    for r, ids in enumerate(rows):
        for i in ids:
            out[r, i // WORD] |= np.uint32(1) << np.uint32(i % WORD)
    return out


def bits_from_ids(ids: Iterable[int], words: int) -> np.ndarray:
    """Pack one bit-id list into uint32[words]."""
    return pack_bits([list(ids)], words)[0]


def contains_all(bits, require) -> np.ndarray:
    """bool[...]: every bit of ``require`` present in ``bits``.
    bits: uint32[..., W]; require: uint32[W] (broadcast)."""
    return ((bits & require) == require).all(axis=-1)


def intersects(bits, other) -> np.ndarray:
    """bool[...]: any common bit."""
    return ((bits & other) != 0).any(axis=-1)


def label_pair(key: str, value: str) -> str:
    return f"{key}={value}"


def intern_labels(vocab: Vocab, key_vocab: Vocab, labels: Mapping[str, str]) -> tuple[list[int], list[int]]:
    """Intern a label map into (pair_ids, key_ids)."""
    pair_ids = [vocab.intern(label_pair(k, v)) for k, v in labels.items()]
    key_ids = [key_vocab.intern(k) for k in labels]
    return pair_ids, key_ids
