"""Unified replica-assignment kernel: all four strategies as one tensor op.

The reference dispatches through assignFuncMap (core/assignment.go:31-38) into
per-strategy Go loops. On TPU every strategy reduces to ONE largest-remainder
dispense with strategy-dependent (target, weights, lastReplicas, init):

- Duplicated  (assignment.go:176-182): broadcast, no dispense
- StaticWeight (assignment.go:194-206): target=N, w=rule weights, init=0
- DynamicWeight steady scale-up (division_algorithm.go:119-128):
  target=N-assigned, w=availability, init=previous
- DynamicWeight steady scale-down (division_algorithm.go:101-117):
  target=N, w=FULL previous result, init=0
- Fresh (division_algorithm.go:130-152): target=N, w=availability+credited
  previous, init=0
- Aggregated (division_algorithm.go:80-90 + assignment.go:146-173): same as
  the dynamic modes but with weights masked to the minimal prefix of clusters
  ordered (previously-used desc, availability desc, index asc) whose
  cumulative availability covers the target

so the whole batch runs as two fused sorts + elementwise ops over [B, C]
arrays — no per-binding control flow, no host round-trips. Branch selection
is data (jnp.where over cohort masks), exactly the "batch by branch" plan of
SURVEY.md section 7.

Inputs are dense per-chunk arrays; karmada_tpu.scheduler packs them from API
objects and unpacks results.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .dispense import acc_dtype, take_by_weight, take_by_weight_fast

# Strategy codes — shared with refimpl.divider
DUPLICATED = 0
STATIC_WEIGHT = 1
DYNAMIC_WEIGHT = 2
AGGREGATED = 3


class DivideResult(NamedTuple):
    assignment: jnp.ndarray  # int32[B, C] replicas per cluster
    unschedulable: jnp.ndarray  # bool[B] — available < target (FitError)


def _aggregated_prefix_mask(
    weights: jnp.ndarray,  # int32[C] availability in this mode
    is_prev: jnp.ndarray,  # bool[C] previously-scheduled (>0 replicas)
    target: jnp.ndarray,  # int32 scalar
    wide: bool = True,  # static: int64 cumsum (False = proven-int32)
    w_bits: int | None = None,  # static: weights < 2^w_bits -> packed sort
) -> jnp.ndarray:
    """bool[C]: minimal prefix of (prev desc, avail desc, idx asc) order whose
    cumulative availability reaches ``target``.

    Matches resortAvailableClusters + the prefix loop: the availability sort
    is replicas-desc (division_algorithm.go:31-36) and the resort is a stable
    partition by previously-used (assignment.go:146-173) — together one
    3-key sort.

    Scatter-free: the kept set is a prefix of the sorted order, and the
    (prev, weight, idx) key is a strict total order, so "position <= cutoff"
    is equivalent to an elementwise lexicographic compare against the key
    tuple gathered at the cutoff position.
    """
    c = weights.shape[0]
    idx = jnp.arange(c, dtype=jnp.int32)
    acc = acc_dtype(wide)
    prev_key = jnp.where(is_prev, 0, 1).astype(jnp.int32)
    if w_bits is not None:
        # packed path (host-proven weights < 2^w_bits): the (prev, -w, idx)
        # order fits one int32 key — prev takes 1 bit, so any engine `fast`
        # layout (w_bits + l_bits + i_bits <= 31, l_bits >= 1) fits. A
        # single-key sort roughly halves the sort cost of the 3-key form.
        i_bits = max(1, (c - 1).bit_length())
        assert 1 + w_bits + i_bits <= 31, (w_bits, i_bits)
        wmax = (1 << w_bits) - 1
        key = (
            (prev_key << (w_bits + i_bits))
            | ((wmax - weights) << i_bits)
            | idx
        )
        k_s = lax.sort(key, is_stable=False)
        w_sorted = wmax - ((k_s >> i_bits) & wmax)
        cum_before = jnp.cumsum(w_sorted) - w_sorted
        n_keep = jnp.sum((cum_before < target).astype(jnp.int32))
        pos = jnp.clip(n_keep - 1, 0, c - 1)
        return (key <= k_s[pos]) & (n_keep > 0)
    p_s, nw_s, i_s = lax.sort(
        (prev_key, -weights, idx), num_keys=3, is_stable=False
    )
    cum_before = jnp.cumsum((-nw_s).astype(acc)) + nw_s.astype(acc)
    # cutoff = last position whose preceding cumulative sum is < target
    n_keep = jnp.sum((cum_before < target.astype(acc)).astype(jnp.int32))
    pos = jnp.clip(n_keep - 1, 0, c - 1)
    thr_p, thr_w, thr_i = p_s[pos], -nw_s[pos], i_s[pos]
    le_thr = (prev_key < thr_p) | (
        (prev_key == thr_p)
        & ((weights > thr_w) | ((weights == thr_w) & (idx <= thr_i)))
    )
    return le_thr & (n_keep > 0)


def _divide_one(
    strategy: jnp.ndarray,  # int32 scalar code
    replicas: jnp.ndarray,  # int32 scalar N
    candidates: jnp.ndarray,  # bool[C] post-filter feasibility
    static_w: jnp.ndarray,  # int32[C] rule-matched static weights (0 off-list)
    avail: jnp.ndarray,  # int32[C] estimator availability (candidates only)
    prev: jnp.ndarray,  # int32[C] full previous assignment (spec.clusters)
    fresh: jnp.ndarray,  # bool scalar — reschedule triggered (Fresh mode)
    has_aggregated: bool = True,  # static: chunk contains Aggregated bindings
    wide: bool = True,  # static: int64 accumulation (False = proven-int32)
    fast: tuple | None = None,  # static (w_bits, l_bits, k_top, div_f32):
    # packed-key top_k dispense for host-proven small ranges (see
    # take_by_weight_fast); requires wide=False bounds to hold a fortiori
    want_sites: bool = False,  # static: also return the dispense top-k site
    # indices (requires fast; every non-previous placed cluster is in them
    # when k_top >= num — see take_by_weight_fast)
) -> tuple[jnp.ndarray, ...]:
    acc = acc_dtype(wide)
    c = candidates.shape[0]
    prev_cand = jnp.where(candidates, prev, 0)  # buildScheduledClusters
    assigned = jnp.sum(prev_cand)
    avail = jnp.where(candidates, avail, 0)

    is_dup = strategy == DUPLICATED
    is_static = strategy == STATIC_WEIGHT
    is_dynamic = (strategy == DYNAMIC_WEIGHT) | (strategy == AGGREGATED)

    # --- dynamic cohorts ---------------------------------------------------
    scale_down = is_dynamic & ~fresh & (assigned > replicas)
    scale_up = is_dynamic & ~fresh & (assigned < replicas)
    steady_noop = is_dynamic & ~fresh & (assigned == replicas)
    is_fresh = is_dynamic & fresh

    target_dyn = jnp.where(scale_up, replicas - assigned, replicas)
    w_dyn = jnp.where(
        is_fresh,
        avail + prev_cand,
        jnp.where(scale_down, prev, avail),
    ).astype(jnp.int32)
    # init/last only exist for scale-up (init = previous scheduled clusters)
    init_dyn = jnp.where(scale_up, prev_cand, 0)
    last_dyn = init_dyn

    # availability check precedes division (division_algorithm.go:76-78)
    unschedulable = is_dynamic & ~steady_noop & (
        jnp.sum(w_dyn.astype(acc)) < target_dyn.astype(acc)
    )

    # aggregated prefix restriction; prior only exists in steady scale-up.
    # The prefix sort is skipped entirely (statically) for chunks without
    # Aggregated bindings — one of the two kernel sorts disappears.
    if has_aggregated:
        is_prev_mask = (prev_cand > 0) & scale_up
        # the prefix sort packs (prev-bit, weight, index) into one int32;
        # usable only when that triple fits (the dispense key may fit via
        # the no-idx two-stage mode while this one does not)
        agg_w_bits = None
        if fast is not None and 1 + fast[0] + max(1, (c - 1).bit_length()) <= 31:
            agg_w_bits = fast[0]
        keep = _aggregated_prefix_mask(
            w_dyn, is_prev_mask, target_dyn, wide, agg_w_bits,
        )
        w_dyn = jnp.where(
            (strategy == AGGREGATED) & keep | (strategy != AGGREGATED), w_dyn, 0
        )

    # --- static weights ----------------------------------------------------
    sw = jnp.where(candidates, static_w, 0)
    # all-zero weights -> every candidate gets weight 1 (division_algorithm.go:63-70)
    sw = jnp.where(jnp.sum(sw) > 0, sw, candidates.astype(jnp.int32))
    last_static = jnp.where(candidates, prev, 0)

    # --- unified dispense --------------------------------------------------
    num = jnp.where(is_static, replicas, target_dyn).astype(jnp.int32)
    w = jnp.where(is_static, sw, w_dyn)
    last = jnp.where(is_static, last_static, last_dyn)
    init = jnp.where(is_static, 0, init_dyn)
    w = jnp.where(is_dup | steady_noop | unschedulable, 0, w)  # no dispense

    sites = None
    if fast is not None:
        out = take_by_weight_fast(
            num, w, last, init, *fast, return_sites=want_sites
        )
        if want_sites:
            out, sites = out
    else:
        assert not want_sites, "want_sites requires the fast dispense"
        out = take_by_weight(num, w, last, init, wide)

    out = jnp.where(steady_noop, prev_cand, out)
    out = jnp.where(is_dup, jnp.where(candidates, replicas, 0), out)
    out = jnp.where(unschedulable, 0, out)
    # a zero-replica binding assigns all candidates with replicas 0 upstream
    out = jnp.where(replicas == 0, jnp.zeros((c,), jnp.int32), out)
    if want_sites:
        return out, unschedulable, sites
    return out, unschedulable


_batch_variants: dict = {}


def _divide_batch(
    strategy, replicas, candidates, static_w, avail, prev, fresh,
    has_aggregated=True, wide=True, fast=None, want_sites=False,
):
    key = (has_aggregated, wide, fast, want_sites)
    fn = _batch_variants.get(key)
    if fn is None:
        fn = jax.vmap(
            partial(
                _divide_one,
                has_aggregated=has_aggregated, wide=wide, fast=fast,
                want_sites=want_sites,
            ),
            in_axes=(0, 0, 0, 0, 0, 0, 0),
        )
        _batch_variants[key] = fn
    return fn(strategy, replicas, candidates, static_w, avail, prev, fresh)


@partial(jax.jit, static_argnames=("has_aggregated", "wide", "fast"))
def divide_replicas(
    strategy: jnp.ndarray,  # int32[B]
    replicas: jnp.ndarray,  # int32[B]
    candidates: jnp.ndarray,  # bool[B, C]
    static_w: jnp.ndarray,  # int32[B, C]
    avail: jnp.ndarray,  # int32[B, C]
    prev: jnp.ndarray,  # int32[B, C]
    fresh: jnp.ndarray,  # bool[B]
    has_aggregated: bool = True,
    wide: bool = True,
    fast: tuple | None = None,
) -> DivideResult:
    """Batched AssignReplicas over a binding chunk. Static specializations
    the packing layer selects from host-known bounds:
    - ``has_aggregated=False`` when the chunk has no Aggregated bindings —
      skips the prefix sort entirely;
    - ``wide=False`` when weight x replica products and availability sums
      provably fit int32 (halves the integer-math cost);
    - ``fast=(w_bits, l_bits, k_top, div_f32)`` when weights/lastReplicas
      fit a packed 31-bit key and k_top >= min(max replicas, C) — replaces
      the dispense sort with a packed-key top_k and (div_f32) the integer
      floor-div with an exact f32 reciprocal (~10x cheaper dispense)."""
    out, unsched = _divide_batch(
        strategy, replicas, candidates, static_w, avail, prev, fresh,
        has_aggregated, wide, fast,
    )
    return DivideResult(assignment=out, unschedulable=unsched)


# row_coupled: the graftlint-dep delta-safety declaration — the batch is
# a vmap of the per-binding _divide_one (its sorts/cumsums run over the
# cluster axis, never across bindings); IR006-proven against the jaxpr,
# see tools/graftlint/dep.py
divide_replicas.row_coupled = False
