"""Unified replica-assignment kernel: all four strategies as one tensor op.

The reference dispatches through assignFuncMap (core/assignment.go:31-38) into
per-strategy Go loops. On TPU every strategy reduces to ONE largest-remainder
dispense with strategy-dependent (target, weights, lastReplicas, init):

- Duplicated  (assignment.go:176-182): broadcast, no dispense
- StaticWeight (assignment.go:194-206): target=N, w=rule weights, init=0
- DynamicWeight steady scale-up (division_algorithm.go:119-128):
  target=N-assigned, w=availability, init=previous
- DynamicWeight steady scale-down (division_algorithm.go:101-117):
  target=N, w=FULL previous result, init=0
- Fresh (division_algorithm.go:130-152): target=N, w=availability+credited
  previous, init=0
- Aggregated (division_algorithm.go:80-90 + assignment.go:146-173): same as
  the dynamic modes but with weights masked to the minimal prefix of clusters
  ordered (previously-used desc, availability desc, index asc) whose
  cumulative availability covers the target

so the whole batch runs as two fused sorts + elementwise ops over [B, C]
arrays — no per-binding control flow, no host round-trips. Branch selection
is data (jnp.where over cohort masks), exactly the "batch by branch" plan of
SURVEY.md section 7.

Inputs are dense per-chunk arrays; karmada_tpu.scheduler packs them from API
objects and unpacks results.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .dispense import take_by_weight

# Strategy codes — shared with refimpl.divider
DUPLICATED = 0
STATIC_WEIGHT = 1
DYNAMIC_WEIGHT = 2
AGGREGATED = 3


class DivideResult(NamedTuple):
    assignment: jnp.ndarray  # int32[B, C] replicas per cluster
    unschedulable: jnp.ndarray  # bool[B] — available < target (FitError)


def _aggregated_prefix_mask(
    weights: jnp.ndarray,  # int32[C] availability in this mode
    is_prev: jnp.ndarray,  # bool[C] previously-scheduled (>0 replicas)
    target: jnp.ndarray,  # int32 scalar
) -> jnp.ndarray:
    """bool[C]: minimal prefix of (prev desc, avail desc, idx asc) order whose
    cumulative availability reaches ``target``.

    Matches resortAvailableClusters + the prefix loop: the availability sort
    is replicas-desc (division_algorithm.go:31-36) and the resort is a stable
    partition by previously-used (assignment.go:146-173) — together one
    3-key sort.
    """
    c = weights.shape[0]
    idx = jnp.arange(c, dtype=jnp.int32)
    _, _, _, perm = lax.sort(
        (jnp.where(is_prev, 0, 1).astype(jnp.int32), -weights, idx, idx),
        num_keys=3,
        is_stable=False,
    )
    w_sorted = weights[perm]
    cum = jnp.cumsum(w_sorted.astype(jnp.int64))
    # keep positions up to and including the first where cum >= target
    reached_before = jnp.concatenate(
        [jnp.zeros((1,), jnp.int64), cum[:-1]]
    ) >= target.astype(jnp.int64)
    keep_sorted = ~reached_before
    keep = jnp.zeros((c,), bool).at[perm].set(keep_sorted)
    return keep


def _divide_one(
    strategy: jnp.ndarray,  # int32 scalar code
    replicas: jnp.ndarray,  # int32 scalar N
    candidates: jnp.ndarray,  # bool[C] post-filter feasibility
    static_w: jnp.ndarray,  # int32[C] rule-matched static weights (0 off-list)
    avail: jnp.ndarray,  # int32[C] estimator availability (candidates only)
    prev: jnp.ndarray,  # int32[C] full previous assignment (spec.clusters)
    fresh: jnp.ndarray,  # bool scalar — reschedule triggered (Fresh mode)
    has_aggregated: bool = True,  # static: chunk contains Aggregated bindings
) -> tuple[jnp.ndarray, jnp.ndarray]:
    c = candidates.shape[0]
    prev_cand = jnp.where(candidates, prev, 0)  # buildScheduledClusters
    assigned = jnp.sum(prev_cand)
    avail = jnp.where(candidates, avail, 0)

    is_dup = strategy == DUPLICATED
    is_static = strategy == STATIC_WEIGHT
    is_dynamic = (strategy == DYNAMIC_WEIGHT) | (strategy == AGGREGATED)

    # --- dynamic cohorts ---------------------------------------------------
    scale_down = is_dynamic & ~fresh & (assigned > replicas)
    scale_up = is_dynamic & ~fresh & (assigned < replicas)
    steady_noop = is_dynamic & ~fresh & (assigned == replicas)
    is_fresh = is_dynamic & fresh

    target_dyn = jnp.where(scale_up, replicas - assigned, replicas)
    w_dyn = jnp.where(
        is_fresh,
        avail + prev_cand,
        jnp.where(scale_down, prev, avail),
    ).astype(jnp.int32)
    # init/last only exist for scale-up (init = previous scheduled clusters)
    init_dyn = jnp.where(scale_up, prev_cand, 0)
    last_dyn = init_dyn

    # availability check precedes division (division_algorithm.go:76-78)
    unschedulable = is_dynamic & ~steady_noop & (
        jnp.sum(w_dyn.astype(jnp.int64)) < target_dyn.astype(jnp.int64)
    )

    # aggregated prefix restriction; prior only exists in steady scale-up.
    # The prefix sort is skipped entirely (statically) for chunks without
    # Aggregated bindings — one of the two kernel sorts disappears.
    if has_aggregated:
        is_prev_mask = (prev_cand > 0) & scale_up
        keep = _aggregated_prefix_mask(w_dyn, is_prev_mask, target_dyn)
        w_dyn = jnp.where(
            (strategy == AGGREGATED) & keep | (strategy != AGGREGATED), w_dyn, 0
        )

    # --- static weights ----------------------------------------------------
    sw = jnp.where(candidates, static_w, 0)
    # all-zero weights -> every candidate gets weight 1 (division_algorithm.go:63-70)
    sw = jnp.where(jnp.sum(sw) > 0, sw, candidates.astype(jnp.int32))
    last_static = jnp.where(candidates, prev, 0)

    # --- unified dispense --------------------------------------------------
    num = jnp.where(is_static, replicas, target_dyn).astype(jnp.int32)
    w = jnp.where(is_static, sw, w_dyn)
    last = jnp.where(is_static, last_static, last_dyn)
    init = jnp.where(is_static, 0, init_dyn)
    w = jnp.where(is_dup | steady_noop | unschedulable, 0, w)  # no dispense

    out = take_by_weight(num, w, last, init)

    out = jnp.where(steady_noop, prev_cand, out)
    out = jnp.where(is_dup, jnp.where(candidates, replicas, 0), out)
    out = jnp.where(unschedulable, 0, out)
    # a zero-replica binding assigns all candidates with replicas 0 upstream
    out = jnp.where(replicas == 0, jnp.zeros((c,), jnp.int32), out)
    return out, unschedulable


_divide_batch = jax.vmap(
    _divide_one, in_axes=(0, 0, 0, 0, 0, 0, 0, None)
)


@partial(jax.jit, static_argnames=("has_aggregated",))
def divide_replicas(
    strategy: jnp.ndarray,  # int32[B]
    replicas: jnp.ndarray,  # int32[B]
    candidates: jnp.ndarray,  # bool[B, C]
    static_w: jnp.ndarray,  # int32[B, C]
    avail: jnp.ndarray,  # int32[B, C]
    prev: jnp.ndarray,  # int32[B, C]
    fresh: jnp.ndarray,  # bool[B]
    has_aggregated: bool = True,
) -> DivideResult:
    """Batched AssignReplicas over a binding chunk. Pass
    ``has_aggregated=False`` (static) when the chunk is known to contain no
    Aggregated-strategy bindings to skip the prefix sort."""
    out, unsched = _divide_batch(
        strategy, replicas, candidates, static_w, avail, prev, fresh,
        has_aggregated,
    )
    return DivideResult(assignment=out, unschedulable=unsched)
