"""Vectorized largest-remainder dispenser — the innermost division kernel.

Tensorization of Dispenser.TakeByWeight (ref:
pkg/util/helper/binding.go:112-144) with the deterministic total order
(weight desc, lastReplicas desc, cluster-index asc; see
karmada_tpu.refimpl.divider for the tie-break note).

Shapes: one binding owns a length-C vector over the cluster axis; the batch
kernels vmap over the binding axis. Everything is static-shaped and
jit-friendly.

TPU-shaping notes:
- The remainder hand-out does NOT scatter a permutation back: the +1 bonus
  goes to the lexicographically-largest ``remain`` clusters, and because the
  (weight, last, index) key is a strict total order the bonus set is exactly
  "key >= key of the remain-th sorted element". One keys-only ``lax.sort``
  followed by a [B] gather of the threshold tuple and an elementwise
  3-way lexicographic compare replaces sort+scatter — the scatter was as
  expensive as the sort itself on TPU.
- ``wide=False`` selects an all-int32 kernel for workloads whose
  weight x replica products provably fit in 31 bits (the packing layer
  checks ``max(weights) * num <= INT32_MAX`` and ``sum(weights)`` bounds
  host-side). int64 on TPU is emulated 32-bit pairs; the narrow path
  roughly halves the kernel's ALU + memory traffic.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

# Accumulator dtypes for the dispense/divide integer math, single-sourced
# so the wide/narrow selection can never drift between kernels (the same
# ``wide`` static must mean the same arithmetic in take_by_weight,
# _aggregated_prefix_mask and _divide_one) and pinned as EXPLICIT dtypes:
# a weak-typed scalar in an accumulator expression would re-promote under
# jax.config drift, which graftlint IR001 machine-checks against. ACC_WIDE
# must stay in parity with the numpy reference's accumulator
# (refimpl/divider_np.py ACC_NP) — identical placements require both
# sides to agree on the overflow-free integer range (asserted by
# tests/test_graftlint_ir.py::test_acc_dtype_parity).
ACC_WIDE = jnp.int64
ACC_NARROW = jnp.int32


def acc_dtype(wide: bool):
    """The accumulator dtype selected by a kernel's ``wide`` static."""
    return ACC_WIDE if wide else ACC_NARROW


def take_by_weight(
    num: jnp.ndarray,  # int32 scalar: replicas to dispense
    weights: jnp.ndarray,  # int32[C], >= 0 (0 = excluded from dispensing)
    last: jnp.ndarray,  # int32[C], previous replicas (tie-break inertia)
    init: jnp.ndarray,  # int32[C], initial result merged into the output
    wide: bool = True,  # static: int64 accumulation (False = proven-int32 fast path)
) -> jnp.ndarray:
    """Returns int32[C] replica assignment == Dispenser result.

    floor_i = w_i * num // sum(w); the remainder is handed out one replica at
    a time in (weight desc, last desc, index asc) order. A zero weight sum
    returns ``init`` unchanged (binding.go:117-120).
    """
    c = weights.shape[0]
    idx = jnp.arange(c, dtype=jnp.int32)
    acc = acc_dtype(wide)

    total = jnp.sum(weights.astype(acc))
    safe_total = jnp.maximum(total, 1)
    floors = (weights.astype(acc) * num.astype(acc) // safe_total).astype(
        jnp.int32
    )
    remain = num - jnp.sum(floors)

    # keys-only sort; the bonus set is a lexicographic threshold compare.
    # remain < #nonzero-weights <= C always (largest-remainder property),
    # so position remain-1 is in range whenever remain > 0.
    w_s, l_s, i_s = lax.sort((-weights, -last, idx), num_keys=3, is_stable=False)
    pos = jnp.clip(remain - 1, 0, c - 1)
    thr_w, thr_l, thr_i = -w_s[pos], -l_s[pos], i_s[pos]
    ge_thr = (weights > thr_w) | (
        (weights == thr_w)
        & ((last > thr_l) | ((last == thr_l) & (idx <= thr_i)))
    )
    bonus = (ge_thr & (remain > 0)).astype(jnp.int32)

    dispensed = jnp.where(total > 0, floors + bonus, 0)
    return init + dispensed


# row_coupled: the graftlint-dep delta-safety declarations — unbatched,
# every vector lives over the cluster axis C and the batched form is a
# vmap (one binding per row, no cross-binding flow); IR006-proven, see
# tools/graftlint/dep.py
take_by_weight.row_coupled = False


def take_by_weight_fast(
    num: jnp.ndarray,  # int32 scalar
    weights: jnp.ndarray,  # int32[C], >= 0, < 2^w_bits
    last: jnp.ndarray,  # int32[C], >= 0, < 2^l_bits
    init: jnp.ndarray,  # int32[C]
    w_bits: int,  # static: bits(max weight); w_bits+l_bits+bits(C-1) <= 31
    l_bits: int,  # static: bits(max last)
    k_top: int,  # static: >= min(max num, C) — bounds the remainder rank
    div_f32: bool,  # static: max(weights)*num < 2^24 (exact f32 products)
    with_idx: bool = True,  # static: cluster index fits the packed key
    # NOTE: lax.approx_max_k at recall_target=1.0 was evaluated here as a
    # ~2.5x-cheaper top_k over an order-preserving int->float bitcast and
    # REJECTED: randomized fuzz on the v5e found 12/60 instances where its
    # returned list differs from exact top_k (duplicated winners from the
    # partial reduction) — identical placements are non-negotiable.
    return_sites: bool = False,  # static: also return the top-k site indices
) -> jnp.ndarray:
    """``take_by_weight`` specialized for host-proven small ranges.

    Two TPU-shaping substitutions, both exact under the static gates the
    packing layer checks before choosing this path:
    - the (weight desc, last desc, index asc) order packs into ONE int32 key
      (strict total order), and the remainder bonus only needs the key of
      rank ``remain`` <= num <= k_top, so a ``lax.top_k`` over the packed key
      + one elementwise compare replaces the full 3-key sort (~10x cheaper
      at 5k clusters);
    - integer floor division lowers to slow emulation on the VPU; with
      products < 2^24 the f32 reciprocal is exact after one +-1 fixup.

    ``with_idx=False`` handles fleets where the (weight, last, index) triple
    does not fit one int32 (w_bits + l_bits + bits(C-1) > 31 but
    w_bits + l_bits <= 31): the key packs only (weight, last), the bonus
    threshold comes from its top_k, and the index tie-break among
    threshold-equal clusters is recovered exactly with a second top_k over
    the (negated) indices of the tie set — the remain - #{key > thr}
    tie winners are precisely the lowest-indexed ties. Two [C] top_ks
    instead of one still beat the full 3-key sort.

    With ``return_sites`` the kernel also returns the int32[k_top] cluster
    indices of the top-k keys (recovered from the packed key, or the top_k
    index output in the no-idx mode). When ``k_top >= num`` every cluster
    the dispense can touch is in this set: floors_i > 0 implies
    w_i >= total/num, and at most num clusters satisfy that, so all of them
    (and every bonus site) rank inside the top num <= k_top keys — for the
    no-idx mode the winning ties are the lowest-indexed ones, exactly the
    ones lax.top_k keeps first. Compaction layers exploit this to avoid a
    full-width scan of the result (the basis of the fleet result stream,
    scheduler/fleet.py).
    """
    c = weights.shape[0]
    i_bits = max(1, (c - 1).bit_length())
    if with_idx:
        assert w_bits + l_bits + i_bits <= 31, (w_bits, l_bits, i_bits)
    else:
        assert w_bits + l_bits <= 31, (w_bits, l_bits)
    idx = jnp.arange(c, dtype=jnp.int32)

    total = jnp.sum(weights)
    safe_total = jnp.maximum(total, 1)
    if div_f32:
        prod = weights * num  # < 2^24, exact in f32
        q = (prod.astype(jnp.float32) / safe_total.astype(jnp.float32)).astype(
            jnp.int32
        )
        r = prod - q * safe_total  # |q*total - prod| <= total => int32-safe
        floors = q + jnp.where(r >= safe_total, 1, 0) - jnp.where(r < 0, 1, 0)
    else:
        floors = weights * num // safe_total
    remain = num - jnp.sum(floors)

    k_top = min(k_top, c)  # callers size k_top from replicas; small fleets clamp
    sites = None
    if with_idx:
        key = (weights << (l_bits + i_bits)) | (last << i_bits) | (c - 1 - idx)
        if not return_sites:
            # the bonus set is exactly {key >= (remain-th largest key)}, and
            # because the packed key is a strict total order that threshold
            # is found EXACTLY by a 31-step binary search over the key space
            # (count of keys >= mid is monotone) — measured ~5x cheaper than
            # lax.top_k on the v5e at C=5k, and bit-for-bit identical
            hi_bits = w_bits + l_bits + i_bits

            def srch(_, lohi):
                lo, hi = lohi
                # upper mid via hi - (hi-lo)//2: lo + (hi-lo+1) overflows
                # int32 when the key space spans the full 31 bits
                mid = hi - (hi - lo) // 2
                cnt = jnp.sum((key >= mid).astype(jnp.int32))
                ge = cnt >= jnp.maximum(remain, 1)
                return jnp.where(ge, mid, lo), jnp.where(ge, hi, mid - 1)

            thr, _ = lax.fori_loop(
                0,
                hi_bits + 1,
                srch,
                (jnp.int32(0), jnp.int32((1 << hi_bits) - 1)),
            )
            bonus = ((key >= thr) & (remain > 0)).astype(jnp.int32)
        else:
            top_vals = lax.top_k(key, k_top)[0]
            pos = jnp.clip(remain - 1, 0, k_top - 1)
            thr = top_vals[pos]
            bonus = ((key >= thr) & (remain > 0)).astype(jnp.int32)
            sites = (c - 1) - (top_vals & ((1 << i_bits) - 1))
    else:
        key = (weights << l_bits) | last
        top_vals, top_pos = lax.top_k(key, k_top)
        pos = jnp.clip(remain - 1, 0, k_top - 1)
        thr = top_vals[pos]
        n_gt = jnp.sum((key > thr).astype(jnp.int32))
        n_tie_win = remain - n_gt  # >= 1 whenever remain > 0
        tie = key == thr
        tie_key = jnp.where(tie, -idx, jnp.int32(-(1 << 30)))
        tie_top = lax.top_k(tie_key, k_top)[0]
        idx_cut = -tie_top[jnp.clip(n_tie_win - 1, 0, k_top - 1)]
        bonus = (
            ((key > thr) | (tie & (idx <= idx_cut) & (n_tie_win > 0)))
            & (remain > 0)
        ).astype(jnp.int32)
        if return_sites:
            sites = top_pos.astype(jnp.int32)

    dispensed = jnp.where(total > 0, floors + bonus, 0)
    out = init + dispensed
    if return_sites:
        return out, sites
    return out


take_by_weight_fast.row_coupled = False  # same C-axis-only math as above


# Batched over bindings: num[B], weights[B,C], last[B,C], init[B,C] -> [B,C]
_tbw_batch = {
    w: jax.vmap(partial(take_by_weight, wide=w), in_axes=(0, 0, 0, 0))
    for w in (False, True)
}


def take_by_weight_batch(num, weights, last, init, wide: bool = True):
    return _tbw_batch[bool(wide)](num, weights, last, init)


take_by_weight_batch.row_coupled = False  # vmap of the per-binding kernel
