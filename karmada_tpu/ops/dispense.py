"""Vectorized largest-remainder dispenser — the innermost division kernel.

Tensorization of Dispenser.TakeByWeight (ref:
pkg/util/helper/binding.go:112-144) with the deterministic total order
(weight desc, lastReplicas desc, cluster-index asc; see
karmada_tpu.refimpl.divider for the tie-break note).

Shapes: one binding owns a length-C vector over the cluster axis; the batch
kernels vmap over the binding axis. Everything is static-shaped and
jit-friendly; a single ``lax.sort`` with three keys realizes the
lexicographic order (TPU-native: one fused sort, no host control flow).

int64 is used only where products can overflow int32
(weight * num_replicas and availability cumsums); storage stays int32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def take_by_weight(
    num: jnp.ndarray,  # int32 scalar: replicas to dispense
    weights: jnp.ndarray,  # int32[C], >= 0 (0 = excluded from dispensing)
    last: jnp.ndarray,  # int32[C], previous replicas (tie-break inertia)
    init: jnp.ndarray,  # int32[C], initial result merged into the output
) -> jnp.ndarray:
    """Returns int32[C] replica assignment == Dispenser result.

    floor_i = w_i * num // sum(w); the remainder is handed out one replica at
    a time in (weight desc, last desc, index asc) order. A zero weight sum
    returns ``init`` unchanged (binding.go:117-120).
    """
    c = weights.shape[0]
    idx = jnp.arange(c, dtype=jnp.int32)

    total = jnp.sum(weights.astype(jnp.int64))
    safe_total = jnp.maximum(total, 1)
    floors64 = weights.astype(jnp.int64) * num.astype(jnp.int64) // safe_total
    floors = floors64.astype(jnp.int32)
    remain = num - jnp.sum(floors).astype(jnp.int32)

    # one fused lexicographic sort; payload = original index
    _, _, _, perm = lax.sort(
        (-weights, -last, idx, idx), num_keys=3, is_stable=False
    )
    # +1 to the first `remain` clusters in sort order
    bonus_sorted = (jnp.arange(c, dtype=jnp.int32) < remain).astype(jnp.int32)
    bonus = jnp.zeros((c,), jnp.int32).at[perm].set(bonus_sorted)

    dispensed = jnp.where(total > 0, floors + bonus, 0)
    return init + dispensed


# Batched over bindings: num[B], weights[B,C], last[B,C], init[B,C] -> [B,C]
take_by_weight_batch = jax.vmap(take_by_weight, in_axes=(0, 0, 0, 0))
