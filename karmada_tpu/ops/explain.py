"""Placement-provenance kernels: batched "why" as tensor reductions.

Ref: the reference scheduler explains a placement through per-binding
``Scheduled`` conditions and filter-stage events emitted from host
control flow (generic_scheduler.go's Filter/Score/Select/AssignReplicas
pipeline, scheduler.go:827-919). Our pipeline runs those stages as
batched tensor programs, so per-binding host bookkeeping would cost more
than the solve; instead the whole wave's provenance computes as ONE
extra armed-only dispatch per pass (disarmed = one ``is None`` check in
the engine, the PR 7/8 pattern):

- ``explain_pass`` — a packed per-binding x per-cluster EXCLUSION
  BITMASK, one bit per decision stage in
  ``utils.reasons.STAGE_REASONS`` order (affinity/group rank,
  taints/NoExecute, API enablement, estimator availability, quota
  cluster cap, quota admission, spread constraint), plus a per-binding
  top-k candidate summary (cluster, availability, credited prev, final
  assignment, that cluster's mask byte) ranked by (assigned desc,
  availability desc, index asc).

The stage masks arrive COMPOSED (already-placed leniency folded, the
selected affinity group's term, the spread selection) — composition is
the engine's packing layer (TensorScheduler._pack_explain), exactly as
the solve kernels receive composed feasibility. The numpy oracle
(refimpl/explain_np.py) re-derives the same bits from the reference
per-binding/per-cluster decision semantics, sharing no code with this
kernel, and is asserted bit-identical across the bucket grid, padded
tails and mesh 1/2/4/8.

Pure integer math (no float64, no host round-trips, no captured consts —
graftlint IR001-IR005 audit via the entry-point registry). ``mesh``
shards the binding axis over "b" exactly like the fleet kernels; the
mesh static is part of the compile identity and manifest records carry
it as the canonical shape.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils.reasons import STAGE_REASONS

#: exclusion-bit positions, derived from the taxonomy's canonical stage
#: order — the registry (utils/reasons.py) is the single source; these
#: names exist so kernel code reads as bits, not magic indices
BIT_AFFINITY = STAGE_REASONS.index("AffinityMismatch")
BIT_TAINT = STAGE_REASONS.index("TaintUntolerated")
BIT_API = STAGE_REASONS.index("ApiNotEnabled")
BIT_AVAILABILITY = STAGE_REASONS.index("NoAvailableReplicas")
BIT_QUOTA_CAP = STAGE_REASONS.index("QuotaCapExceeded")
BIT_QUOTA_ADMIT = STAGE_REASONS.index("QuotaExceeded")
BIT_SPREAD = STAGE_REASONS.index("SpreadConstraintUnsatisfied")
BIT_PREEMPTED = STAGE_REASONS.index("PreemptedByHigherPriority")
N_STAGES = len(STAGE_REASONS)
assert N_STAGES <= 8, "exclusion mask is one uint8 per cell"

#: top-k summary column layout (int32[B, K, TOPK_COLS])
TOPK_COLS = 5  # cluster index, avail, prev, assigned, mask byte


@partial(jax.jit, static_argnames=("k", "mesh", "shard_c"))
def explain_pass(
    aff_ok,  # bool[B, C]: in the SELECTED affinity group's mask
    taint_ok,  # bool[B, C]: taints tolerated (leniency + eviction folded)
    api_ok,  # bool[B, C]: API/GVK enabled (leniency folded)
    spread_ok,  # bool[B, C]: spread fields pass + spread selection keeps it
    avail,  # int32[B, C]: merged estimator availability (pre-cap)
    caps,  # int32[B, C]: quota cluster-cap estimate (MAX_INT32 = no cap)
    admitted,  # bool[B]: survived batched quota admission
    dynamic,  # bool[B]: dynamic-weight strategy family (consults avail)
    replicas,  # int32[B]
    assignment,  # int32[B, C]: the pass's final assignment
    prev,  # int32[B, C]: credited previous placements
    preempted,  # bool[B, C]: active preemption-eviction task from cluster
    *,
    k: int,
    mesh=None,  # jax.sharding.Mesh with axes ("b", "c") — None = single-device
    shard_c: bool = False,
):
    """One armed-only provenance dispatch over a padded chunk. Returns
    ``(mask uint8[B, C], topk int32[B, K, TOPK_COLS])``. Padding rows
    (replicas == 0, all-False masks) decode as fully-excluded and are
    sliced off by the capture layer."""
    b, c = aff_ok.shape
    assert k <= c, (k, c)
    c_ax = "c" if (mesh is not None and shard_c) else None

    def shard(a, *axes):
        if mesh is None:
            return a
        return lax.with_sharding_constraint(
            a, NamedSharding(mesh, P(*axes))
        )

    aff_ok = shard(aff_ok, "b", c_ax)
    taint_ok = shard(taint_ok, "b", c_ax)
    api_ok = shard(api_ok, "b", c_ax)
    spread_ok = shard(spread_ok, "b", c_ax)
    avail = shard(avail, "b", c_ax)
    caps = shard(caps, "b", c_ax)
    assignment = shard(assignment, "b", c_ax)
    prev = shard(prev, "b", c_ax)
    preempted = shard(preempted, "b", c_ax)
    admitted = shard(admitted, "b")
    dynamic = shard(dynamic, "b")
    replicas = shard(replicas, "b")

    def bit(cond, i: int):
        return jnp.where(cond, jnp.uint8(1 << i), jnp.uint8(0))

    # availability stages only speak for strategies that consult the
    # estimator merge (Duplicated places everywhere feasible) and for
    # actual workloads (replicas > 0)
    consults = (dynamic & (replicas > 0))[:, None]
    mask = (
        bit(~aff_ok, BIT_AFFINITY)
        | bit(~taint_ok, BIT_TAINT)
        | bit(~api_ok, BIT_API)
        | bit(consults & (avail <= 0), BIT_AVAILABILITY)
        | bit(consults & (caps <= 0), BIT_QUOTA_CAP)
        | bit(~admitted[:, None], BIT_QUOTA_ADMIT)
        | bit(~spread_ok, BIT_SPREAD)
        # a victim's evicted-from clusters carry their own bit beside the
        # folded taint/NoExecute stage, so the decision chain names
        # preemption rather than a generic untolerated taint
        | bit(preempted, BIT_PREEMPTED)
    )

    # top-k candidates by (assigned desc, avail desc, index asc): the
    # mixed-radix key packs both into one int64 — assigned < 2^31 and
    # avail+1 in [0, 2^31] keep the product under 2^63; lax.top_k breaks
    # ties toward the lower index, the reference's stable order
    key = assignment.astype(jnp.int64) * jnp.int64(1 << 32) + (
        avail.astype(jnp.int64) + 1
    )
    _vals, idx = lax.top_k(key, k)
    take = lambda a: jnp.take_along_axis(a, idx, axis=1)
    topk = jnp.stack(
        [
            idx.astype(jnp.int32),
            take(avail).astype(jnp.int32),
            take(prev).astype(jnp.int32),
            take(assignment).astype(jnp.int32),
            take(mask).astype(jnp.int32),
        ],
        axis=-1,
    )
    return mask, topk


# row_coupled: the graftlint-dep delta-safety declaration — the stage
# masks are element-wise bit-ors and the top-k summary ranks over the
# CLUSTER axis within each row; IR006-proven row-independent, see
# tools/graftlint/dep.py
explain_pass.row_coupled = False


def topk_width(c: int, k: int = 8) -> int:
    """The kernel's static ``k`` for a ``c``-cluster snapshot: the
    requested width clamped to the cluster count (one trace per (padded
    B, C, k) bucket)."""
    return max(1, min(int(k), int(c)))
