"""Preemption kernels: plane-wide victim selection as ONE tensor op.

Ref: the reference schedules under sufficiency — priority exists on
PropagationPolicy (policy.go getHighestPriorityPropagationPolicy) but
orders only policy MATCHING; no reference deployment preempts at the
binding tier. The scarcity plane (ISSUE 14 / ROADMAP item 4) closes that
gap the repo way: when a high-priority wave cannot fit, the whole
plane's victim selection runs as one batched kernel — the
cohort-predicate style of ``ops.masks.first_fit_group`` — instead of a
per-binding host loop, and victims route through PR 7's graceful-
eviction machinery (condition -> taint -> NoExecute path).

THE selection rule (the numpy oracle ``refimpl/preempt_np.py``
implements it as the reference would — a sequential loop over victims
maintaining per-class unmet demand — sharing no code with this kernel):

- Demanders are bindings with ``priority > 0`` whose solve answered
  "available replicas are not enough"; each contributes
  ``shortfall x per-replica request`` of unmet demand to its priority
  class.
- Candidate victims are BOUND bindings; a victim may only serve demand
  from classes STRICTLY above its own priority (never equal-or-higher —
  a priority-10 binding is never displaced for another priority-10).
- Victims are taken lowest priority first; within a class, largest
  displacement weight (total assigned replicas) first — covering the
  demand with the FEWEST displacements — with arrival order (row index)
  as the final tiebreak. Whole bindings are displaced (the graceful-
  eviction unit), so freed capacity is the victim's full assignment.
- A victim is selected iff, at its place in that order, SOME resource
  dim it frees still has unmet demand from the classes above it. The
  batched form is a prefix cumsum: selected(v) iff
  ``exists r: freed[v,r] > 0 and cum_excl[v,r] < demand_gt(prio_v)[r]``
  where ``cum_excl`` sums freed capacity over ALL earlier victims in
  the sort order. The full prefix equals the selected-only prefix: an
  unselected victim only inflates dims whose demand the prefix already
  met, and met dims stay met (cumsum is nondecreasing) — the same
  holds-its-place-in-line algebra as ``quota_admit``'s FIFO prefix.

The kernel returns the victim mask plus the per-cluster freed-capacity
tensor ``[C, R]`` (victim assignment x per-replica request, summed over
selected victims) — the engine min-merges it back into availability and
re-solves the demanders IN THE SAME PASS, so a scarcity storm costs one
extra batched solve, not a settle round-trip.

Pure integer math (no float64, no host round-trips, no captured consts
— graftlint IR001-IR005 audit via the entry-point registry). ``mesh``
shards the binding axis over "b" exactly like the fleet kernels; the
mesh static is part of the compile identity. Demand/freed rows are
clamped by the packing layer (``ops.quota.DEMAND_CLAMP``) so a plane-
wide cumsum can never overflow int64 — ``preempt_select`` asserts the
same row bound ``quota_admit`` does.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .quota import MAX_ADMIT_ROWS

#: priority values must fit the packed sort key beside the displacement
#: weight and row index: prio in [0, 2^20), weight < 2^20, B <= 2^17
MAX_PRIORITY = (1 << 20) - 1
MAX_WEIGHT = (1 << 20) - 1


@partial(jax.jit, static_argnames=("mesh",))
def preempt_select(
    prio,  # int32[B]: per-binding priority class
    demand,  # int64[B, R]: unmet demand (0 for non-demanders; clamped)
    freed,  # int64[B, R]: capacity a victim would free (0 otherwise)
    victim_ok,  # bool[B]: eligible victim (bound, not itself a demander)
    weight,  # int32[B]: displacement weight (total assigned replicas)
    assigned,  # int32[B, C]: current per-cluster assignment
    requests,  # int64[B, R]: per-replica requests
    *,
    mesh=None,  # jax.sharding.Mesh with axes ("b", "c") — None = single
):
    """ONE plane-wide victim selection. Returns ``(victims bool[B],
    freed_caps int64[C, R])``. Rows that are neither demanders nor
    eligible victims (padding included: all-zero rows) select nothing
    and free nothing."""
    b, r = demand.shape
    assert b <= MAX_ADMIT_ROWS, (b, MAX_ADMIT_ROWS)

    def shard(a, *axes):
        if mesh is None:
            return a
        return lax.with_sharding_constraint(a, NamedSharding(mesh, P(*axes)))

    def repl(a):
        """Replicate a global-scan input: the sorts/cumsums below are
        plane-wide compactions, and the CPU SPMD partitioner miscompiles
        prefix scans whose inputs inherit row sharding (the PR 9 guard —
        fleet.py wire builds carry the same constraint)."""
        if mesh is None:
            return a
        return lax.with_sharding_constraint(
            a, NamedSharding(mesh, P(*((None,) * a.ndim)))
        )

    prio = shard(prio, "b")
    demand = shard(demand, "b", None)
    freed = shard(freed, "b", None)
    victim_ok = shard(victim_ok, "b")
    weight = shard(weight, "b")
    assigned = shard(assigned, "b", None)
    requests = shard(requests, "b", None)

    # --- demand by priority class, as a descending-priority prefix sum:
    # demand_gt(q) = total demand of rows with prio > q. Sorting rows by
    # prio DESC and cumsumming demand gives, at each sorted position,
    # the demand of every strictly-higher class up to prio ties; the
    # per-victim lookup below binary-searches the first position whose
    # prio <= q, whose EXCLUSIVE cumsum is exactly demand_gt(q).
    p64 = prio.astype(jnp.int64)
    idx64 = jnp.arange(b, dtype=jnp.int64)
    d_order = jnp.argsort(repl(-(p64 * b) - (b - 1 - idx64)))
    d_prio = repl(p64[d_order])
    d_demand = repl(demand[d_order])
    d_cum = jnp.cumsum(d_demand, axis=0)
    d_cum_excl = d_cum - d_demand

    # --- victim sort: (prio asc, weight desc, index asc) packed into one
    # int64 key; ineligible rows sort to the far end via a prio above
    # every real class
    w64 = jnp.clip(weight.astype(jnp.int64), 0, MAX_WEIGHT)
    v_prio = jnp.where(victim_ok, p64, jnp.int64(MAX_PRIORITY + 1))
    v_key = (
        v_prio * ((MAX_WEIGHT + 1) * b)
        + (MAX_WEIGHT - w64) * b
        + idx64
    )
    v_order = jnp.argsort(repl(v_key))
    v_freed = repl(freed[v_order])
    v_cum = jnp.cumsum(v_freed, axis=0)
    v_cum_excl = v_cum - v_freed
    v_ok = victim_ok[v_order]
    v_p = p64[v_order]

    # demand_gt(prio_v): first descending-prio position with prio <= q is
    # found by searching the NEGATED (ascending) key space
    pos = jnp.searchsorted(-d_prio, -v_p, side="left")
    d_gt = d_cum_excl[jnp.minimum(pos, b - 1)]
    d_gt = jnp.where((pos < b)[:, None], d_gt, d_cum[b - 1])

    sel_sorted = v_ok & (
        (v_freed > 0) & (v_cum_excl < d_gt)
    ).any(axis=1)
    victims = jnp.zeros((b,), bool).at[v_order].set(sel_sorted)

    # freed capacity lands on the victims' clusters: one [B,C]x[B,R]
    # contraction — int64 to keep exact integer semantics
    sel_assigned = jnp.where(victims[:, None], assigned, 0).astype(jnp.int64)
    freed_caps = jnp.einsum(
        "bc,br->cr", sel_assigned, requests,
        preferred_element_type=jnp.int64,
    )
    if mesh is not None:
        freed_caps = lax.with_sharding_constraint(
            freed_caps, NamedSharding(mesh, P(None, None))
        )
    return victims, freed_caps


# row_coupled: the graftlint-dep delta-safety declaration — victim
# selection is cross-row by design (plane-wide priority sorts and
# cumulative freed-capacity scans over B, plus the row-contracting
# freed-caps einsum); never delta-replayable. IR006 verifies the
# coupling is still present, see tools/graftlint/dep.py
preempt_select.row_coupled = True
