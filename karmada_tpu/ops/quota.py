"""Quota-enforcement kernels: FederatedResourceQuota as tensor constraints.

Ref: pkg/apis/policy/v1alpha1/federatedresourcequota_types.go (the API),
pkg/controllers/federatedresourcequota/ (status accounting) and the
estimator-side ResourceQuota plugin (plugins/resourcequota/resourcequota.go).
The reference enforces quota per binding in host control flow; here the
whole wave admits as ONE batched kernel so a storm of bindings in quota'd
namespaces costs mask ops inside the existing batched solve, never a
per-binding host loop.

Two kernel families:

- ``quota_admit`` — namespace-segment cumulative admission. Bindings are
  sorted (stably) by namespace id with arrival order preserved inside each
  segment, per-binding demand ``[B, R]`` is cumsummed along each namespace
  segment, and a binding is admitted iff its inclusive cumulative demand
  fits the namespace's remaining quota on EVERY dimension. Admission is
  therefore FIFO inside a wave: first-come wins, and a denied binding's
  demand still holds its place in line (a later, smaller binding cannot
  leapfrog it within the wave). This is deliberate — the FIFO-prefix rule
  is associative-scan-free batched math, deterministic, starvation-free
  for large requests, and self-correcting across waves: the usage
  controller recomputes ``overall_used`` from what actually BOUND, so a
  denied binding never consumes quota durably and retries on the next
  quota generation. The numpy oracle (refimpl/quota_np.py) implements the
  same rule as a plain sequential loop, sharing no code with this kernel.

- ``quota_cluster_caps`` — per-cluster static-assignment caps.
  ``spec.static_assignments`` hard limits pack as an ``[N, C, R]`` tensor;
  a binding in a capped namespace has its per-cluster availability ceiling
  ``min over requested dims of floor(cap / request)``. The result is an
  ESTIMATOR-SHAPED answer (int32[B, C], MAX_INT32 = no constraint) that
  the engine min-merges into the divide kernel's availability exactly like
  any other estimate — the cap IS one more estimator in the merge.

Pure integer math throughout (no float64, no host round-trips, no captured
consts — graftlint IR001-IR005 audit these via the entry-point registry).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

#: per-dimension "no limit" sentinel in the remaining/caps tensors. Chosen
#: far above any real quota but with headroom below int64 overflow: a wave
#: cumsum adds at most B * max-demand on top during comparison prep, and
#: demands are clamped to DEMAND_CLAMP by the packing layer.
UNLIMITED = 2**62

#: per-binding per-dimension demand clamp applied by packing layers so a
#: B-row cumsum can never overflow int64: with B <= 2^17 rows (the
#: scheduler's batch cap is 131072) the worst cumsum is 2^44 * 2^17 =
#: 2^61 < UNLIMITED < 2^63. quota_admit asserts the row bound at trace
#: time.
DEMAND_CLAMP = 2**44
MAX_ADMIT_ROWS = 1 << 17

MAX_INT32 = 2**31 - 1


@jax.jit
def quota_admit(
    ns_ids: jnp.ndarray,  # int32[B]: namespace id, -1 = not quota'd
    demand: jnp.ndarray,  # int64[B, R]: delta demand (>= 0, clamped)
    remaining: jnp.ndarray,  # int64[N, R]: limit - used (UNLIMITED = no cap)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """FIFO cumulative admission for one wave.

    Returns ``(admitted bool[B], wave_used int64[N, R])`` where
    ``wave_used`` is the admitted demand summed per namespace — the
    wave's provisional usage, before the status controller recomputes
    from bound bindings. Rows with ``ns_ids < 0`` are always admitted and
    contribute nothing. Arrival order is the row order: the sort key is
    ``ns * B + row`` so the namespace grouping is stable by construction.
    """
    b = ns_ids.shape[0]
    # static-shape bound, checked at trace time: the DEMAND_CLAMP
    # overflow headroom holds only up to this many rows per wave
    assert b <= MAX_ADMIT_ROWS, (b, MAX_ADMIT_ROWS)
    n, r = remaining.shape
    ns_safe = jnp.where(ns_ids < 0, jnp.int32(n), ns_ids)
    key = ns_safe.astype(jnp.int64) * b + jnp.arange(b, dtype=jnp.int64)
    order = jnp.argsort(key)
    ns_s = ns_safe[order]
    d_s = demand[order]
    cum = jnp.cumsum(d_s, axis=0)
    cum_excl = cum - d_s
    first = jnp.concatenate(
        [jnp.ones((1,), bool), ns_s[1:] != ns_s[:-1]]
    )
    # segment base = the exclusive cumsum at each segment's first row,
    # propagated forward. cum_excl is nondecreasing (demand >= 0), so a
    # running max over (first ? cum_excl : -1) IS the latest segment base.
    seg_base = jnp.where(first[:, None], cum_excl, jnp.int64(-1))
    base = lax.cummax(seg_base, axis=0)
    seg_cum = cum - base
    rem_pad = jnp.concatenate(
        [remaining, jnp.full((1, r), jnp.int64(UNLIMITED))], axis=0
    )
    ok = (seg_cum <= rem_pad[ns_s]).all(axis=1)
    admitted = jnp.zeros((b,), bool).at[order].set(ok)
    wave_used = (
        jnp.zeros((n + 1, r), jnp.int64)
        .at[ns_s]
        .add(jnp.where(ok[:, None], d_s, 0))
    )
    return admitted, wave_used[:n]


# row_coupled: the graftlint-dep delta-safety declaration — FIFO
# admission is cross-row by design (the plane-wide argsort/cumsum over B
# and the per-namespace running max); never delta-replayable. IR006
# verifies the coupling is still present, see tools/graftlint/dep.py
quota_admit.row_coupled = True


def _cluster_caps_kernel(xp, caps, ns_rows, requests):
    """Shared body of the static-assignment cap estimate: ONE body serves
    both array modules (jit kernel + numpy mirror) so the host and device
    paths are bit-identical by construction — the ``_node_sum_kernel``
    pattern from estimator/accurate.py. ``caps`` is int64[N, C, R] with
    UNLIMITED where uncapped; rows with ``ns_rows < 0`` answer MAX_INT32
    everywhere (no constraint)."""
    r_dims = requests.shape[-1]
    rows = xp.where(ns_rows < 0, 0, ns_rows)
    cap_b = caps[rows]  # [B, C, R]
    best = xp.full(
        (requests.shape[0], caps.shape[1]), xp.int64(2**62)
    )
    for r in range(r_dims):  # R is small and static; unrolled under jit
        req_r = requests[:, r][:, None]  # [B, 1]
        cap_r = cap_b[:, :, r]
        ratio = cap_r // xp.maximum(req_r, 1)
        # an UNLIMITED cap must never constrain, even for huge requests
        ratio = xp.where(cap_r >= xp.int64(UNLIMITED), xp.int64(2**62), ratio)
        best = xp.where(req_r > 0, xp.minimum(best, ratio), best)
    out = xp.minimum(best, xp.int64(MAX_INT32)).astype(xp.int32)
    return xp.where(ns_rows[:, None] < 0, xp.int32(MAX_INT32), out)


def cluster_caps_np(caps, ns_rows, requests) -> np.ndarray:
    """Numpy instantiation for the host-small path (same body as the jit
    kernel; asserted bit-identical in tests/test_ops_quota.py)."""
    return _cluster_caps_kernel(
        np, np.asarray(caps), np.asarray(ns_rows), np.asarray(requests)
    )


@jax.jit
def quota_cluster_caps(
    caps: jnp.ndarray,  # int64[N, C, R]: static-assignment hard caps
    ns_rows: jnp.ndarray,  # int32[B]: cap-table row, -1 = uncapped
    requests: jnp.ndarray,  # int64[B, R]: per-replica requests
) -> jnp.ndarray:
    """int32[B, C] max replicas each cluster's namespace slice admits
    (MAX_INT32 = no constraint) — estimator-shaped, min-merged into the
    divide kernel's availability by the engine."""
    return _cluster_caps_kernel(jnp, caps, ns_rows, requests)


quota_cluster_caps.row_coupled = False  # row b reads caps[ns_rows[b]] only
