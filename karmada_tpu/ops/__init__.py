"""Pure jittable tensor kernels for the scheduling hot path.

x64 is enabled process-wide: replica-division arithmetic (weight*replicas
products, availability cumsums) exceeds int32, and exact integer semantics
are required for the identical-placement guarantee. Storage arrays stay
int32; only the overflow-prone intermediates widen (TPU emulates int64 at a
small cost that is negligible next to the kernel's sorts).

NOTE this is a deliberate process-global choice: karmada_tpu owns its
control-plane process (scheduler/bench/controllers), and the scoped
alternatives (jax.experimental.enable_x64 contexts) interact badly with jit
caching. Guest applications embedding this package alongside float32 jax
models should run the solver in its own process (the gRPC sidecar deployment
shape of SURVEY.md section 2.2) rather than in-process.
"""

import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: policy lives in utils.compilecache
# (one resolution point shared with the prewarm subsystem and the warmup
# CLI — the manifest must sit beside the cache its records replay into).
# Opt out / relocate with JAX_COMPILATION_CACHE_DIR ("" disables).
from ..utils.compilecache import enable as _enable_compile_cache  # noqa: E402

_enable_compile_cache()

from .dispense import (  # noqa: E402,F401
    take_by_weight,
    take_by_weight_batch,
    take_by_weight_fast,
)
from .divide import (  # noqa: E402,F401
    AGGREGATED,
    DUPLICATED,
    DYNAMIC_WEIGHT,
    STATIC_WEIGHT,
    DivideResult,
    divide_replicas,
)
from .estimate import (  # noqa: E402,F401
    gather_profile_rows,
    general_estimate,
    general_estimate_interned,
    merge_estimates,
)
from .explain import (  # noqa: E402,F401
    explain_pass,
)
from .preempt import (  # noqa: E402,F401
    preempt_select,
)
from .quota import (  # noqa: E402,F401
    quota_admit,
    quota_cluster_caps,
)
from . import masks  # noqa: E402,F401
