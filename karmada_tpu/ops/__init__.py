"""Pure jittable tensor kernels for the scheduling hot path.

x64 is enabled process-wide: replica-division arithmetic (weight*replicas
products, availability cumsums) exceeds int32, and exact integer semantics
are required for the identical-placement guarantee. Storage arrays stay
int32; only the overflow-prone intermediates widen (TPU emulates int64 at a
small cost that is negligible next to the kernel's sorts).

NOTE this is a deliberate process-global choice: karmada_tpu owns its
control-plane process (scheduler/bench/controllers), and the scoped
alternatives (jax.experimental.enable_x64 contexts) interact badly with jit
caching. Guest applications embedding this package alongside float32 jax
models should run the solver in its own process (the gRPC sidecar deployment
shape of SURVEY.md section 2.2) rather than in-process.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: the tunneled TPU backend charges
# 20-40 s per fresh trace, and the engine's static specializations (chunk
# counts, kernel variants, entry-buffer sizes) legitimately produce several
# traces per workload shape. Caching across processes makes bench reruns and
# control-plane restarts pay compile cost once. Opt out / relocate with
# JAX_COMPILATION_CACHE_DIR ("" disables).
_cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
if _cache_dir is None:
    # repo checkout: cache beside the package; installed package (parent
    # dir not writable, e.g. site-packages): fall back to the user cache
    _repo_parent = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if os.access(_repo_parent, os.W_OK):
        _cache_dir = os.path.join(_repo_parent, ".jax_cache")
    else:
        _cache_dir = os.path.join(
            os.path.expanduser("~"), ".cache", "karmada_tpu", "jax"
        )
if _cache_dir:
    # partition by platform set: a tunneled accelerator backend compiles on
    # the REMOTE host and caches CPU AOT artifacts built for that machine's
    # CPU features — a local CPU process loading them gets machine-feature
    # mismatch warnings at best and SIGILL at worst (observed killing
    # localup children mid-suite). Read the CONFIGURED platform list (the
    # sitecustomize sets it programmatically, callers may too — the env
    # var alone is not authoritative); every distinct set gets its own
    # cache root. JAX_COMPILATION_CACHE_DIR overrides skip this.
    if os.environ.get("JAX_COMPILATION_CACHE_DIR") is None:
        try:
            _plat = jax.config.jax_platforms
        except Exception:  # noqa: BLE001 — knob missing in this jax
            _plat = None
        _plat = _plat or os.environ.get("JAX_PLATFORMS") or "default"
        _cache_dir = os.path.join(
            _cache_dir, _plat.replace(",", "_") or "default"
        )
    try:
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # older jax without the knob: run uncached
        pass

from .dispense import (  # noqa: E402,F401
    take_by_weight,
    take_by_weight_batch,
    take_by_weight_fast,
)
from .divide import (  # noqa: E402,F401
    AGGREGATED,
    DUPLICATED,
    DYNAMIC_WEIGHT,
    STATIC_WEIGHT,
    DivideResult,
    divide_replicas,
)
from .estimate import (  # noqa: E402,F401
    gather_profile_rows,
    general_estimate,
    general_estimate_interned,
    merge_estimates,
)
from . import masks  # noqa: E402,F401
