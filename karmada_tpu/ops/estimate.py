"""Capacity estimation kernels: MaxAvailableReplicas as batched integer math.

General estimator (ref: pkg/estimator/client/general.go:96-196): per cluster,
available = allocatable - allocated - allocating; max replicas = min over
requested resource dims of floor(available / request), min'ed with the
allowed-pod headroom. Each replica occupies one pod, so the pods dimension
carries an implicit request of 1 — which reproduces getAllowedPodNumber
(general.go:96-114) as just another dimension.

The node/model-grade variants live in karmada_tpu.estimator; they produce the
same ``[B, C]`` availability matrix and are min-merged by
``merge_estimates`` (ref: pkg/scheduler/core/util.go:54-104).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MAX_INT32 = jnp.int32(2**31 - 1)
UNAUTHENTIC = jnp.int32(-1)  # estimator "no answer" (client/interface.go:30)


@jax.jit
def general_estimate(
    available_cap: jnp.ndarray,  # int64[C, R]: allocatable-allocated-allocating
    requests: jnp.ndarray,  # int64[B, R]: per-replica requests (0 = not requested)
) -> jnp.ndarray:
    """int32[B, C] max available replicas (>= 0); MAX_INT32 when the binding
    requests nothing at all (best-effort) — callers clamp the sentinel."""
    cap = jnp.maximum(available_cap, 0)  # negative available -> 0 replicas
    r_dims = requests.shape[-1]
    best = jnp.full(requests.shape[:-1] + (cap.shape[0],), jnp.int64(2**31 - 1))
    for r in range(r_dims):  # R is small and static; unrolled under jit
        req_r = requests[..., r][..., None]  # [B, 1]
        ratio = cap[None, :, r] // jnp.maximum(req_r, 1)
        best = jnp.where(req_r > 0, jnp.minimum(best, ratio), best)
    return jnp.minimum(best, jnp.int64(2**31 - 1)).astype(jnp.int32)


# row_coupled: the graftlint-dep delta-safety declarations — request row
# b reads only requests[b] (the cap table is replicated state), so the
# estimator family is certified delta_safe (IR006-proven against the
# jaxpr; see tools/graftlint/dep.py)
general_estimate.row_coupled = False


def gather_profile_rows(
    table: jnp.ndarray,  # int32[U, C]
    idx: jnp.ndarray,  # int32[B]
) -> jnp.ndarray:
    """int32[B, C] = table[idx], expressed as a one-hot matmul.

    HISTORY: on the round-1/2 tunneled-backend toolchain a direct row
    gather with a [B]-sized index vector hung XLA compilation inside
    lax.scan; this MXU formulation was the workaround. Round-3 re-probes
    (inside lax.scan, chunk=4096, U=2..3500) show plain gathers now
    compile cleanly and run ~2.4x faster at large U, so the fleet solve
    (scheduler/fleet.py) uses plain gathers. This helper is retained for
    callers that want the matmul form (and as the fallback should a future
    toolchain regress); the 16-bit split keeps the selection exact for
    EVERY int32 value (sentinels included) — each half fits f32's mantissa
    and a one-hot row selects a single entry, so there is no accumulation
    error."""
    u = table.shape[0]
    onehot = jax.nn.one_hot(idx, u, dtype=jnp.float32)  # [B, U]
    # 16-bit split keeps every int32 exact in f32 (each half < 2^16 and the
    # one-hot rows select a single entry, so no accumulation error); the
    # arithmetic shift keeps negative sentinels (-1 no-answer) intact
    lo = (table & 0xFFFF).astype(jnp.float32)
    hi = (table >> 16).astype(jnp.float32)
    # HIGHEST precision: the TPU MXU's default bf16 passes would round the
    # 16-bit halves (8-bit mantissa); full-f32 passes keep them exact
    lo_g = jnp.einsum(
        "bu,uc->bc", onehot, lo, precision=jax.lax.Precision.HIGHEST
    ).astype(jnp.int32)
    hi_g = jnp.einsum(
        "bu,uc->bc", onehot, hi, precision=jax.lax.Precision.HIGHEST
    ).astype(jnp.int32)
    return (hi_g << 16) | lo_g


gather_profile_rows.row_coupled = False  # row b reads table[idx[b]] only


@jax.jit
def general_estimate_interned(
    available_cap: jnp.ndarray,  # int64[C, R]
    profiles: jnp.ndarray,  # int64[U, R]: unique request rows
    prof_idx: jnp.ndarray,  # int32[B]: row i uses profiles[prof_idx[i]]
) -> jnp.ndarray:
    """int32[B, C] — ``general_estimate`` with request-profile interning.

    Real fleets carry few unique ReplicaRequirements (a handful of resource
    T-shirt sizes), so the [B, C, R] integer divisions collapse to [U, C]
    followed by a row gather: the estimator cost becomes O(U x C) instead of
    O(B x C), the single biggest win for the 100k-binding hot path. The
    packing layer produces (profiles, prof_idx) via np.unique over request
    rows — exact, no semantic change (general.go:156-196 per-row math is
    unchanged)."""
    per_profile = general_estimate(available_cap, profiles)  # [U, C]
    return gather_profile_rows(per_profile, prof_idx)


general_estimate_interned.row_coupled = False  # per-row profile lookup


@jax.jit
def merge_estimates(
    replicas: jnp.ndarray,  # int32[B]
    estimates: tuple[jnp.ndarray, ...],  # each int32[B, C]; -1 = no answer
) -> jnp.ndarray:
    """core/util.go:54-104: min across estimators ignoring UNAUTHENTIC,
    then clamp an untouched MAX_INT32 sentinel to spec.Replicas, and
    short-circuit zero-replica (non-workload) bindings to the sentinel path."""
    b = replicas.shape[0]
    c = estimates[0].shape[1]
    out = jnp.full((b, c), MAX_INT32)
    for est in estimates:
        out = jnp.where(est == UNAUTHENTIC, out, jnp.minimum(out, est))
    out = jnp.where(replicas[:, None] == 0, MAX_INT32, out)
    return jnp.where(out == MAX_INT32, replicas[:, None], out)


merge_estimates.row_coupled = False  # element-wise min across estimators
