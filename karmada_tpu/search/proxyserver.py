"""Aggregated-apiserver proxy passthrough: a real HTTP server for
``/apis/cluster.karmada.io/v1alpha1/clusters/{name}/proxy/{path}``.

Ref: pkg/registry/cluster/storage/proxy.go:41-102 (the Connecter serving
the proxy subresource per member cluster) + the unified-auth flow: the
caller authenticates to the karmada control plane, and the proxied member
request carries Impersonate-User / Impersonate-Group headers so the member
enforces the CALLER's identity, not the plane's credentials (the
impersonation-based unified auth the reference builds from aggregated
RBAC). Streaming passes through: log follow responses are chunked as lines
arrive, not buffered (the reference pipes the member response body).

Transport is plain HTTP here (the in-proc plane has no PKI by default);
member routing translates the proxied kube REST path onto the
MemberCluster seam — a real deployment swaps that for the member's
apiserver endpoint, keeping this server's auth/impersonation/streaming
shell.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..interpreter.webhook import resource_to_dict
from ..utils.member import MemberClientRegistry, UnreachableError

PROXY_RE = re.compile(
    r"^/apis/cluster\.karmada\.io/v1alpha1/clusters/(?P<cluster>[^/]+)/proxy"
    r"(?P<path>/.*)?$"
)
# member-side kube REST paths the in-proc seam can serve
POD_LOG_RE = re.compile(
    r"^/api/v1/namespaces/(?P<ns>[^/]+)/pods/(?P<name>[^/]+)/log$"
)
POD_EXEC_RE = re.compile(
    r"^/api/v1/namespaces/(?P<ns>[^/]+)/pods/(?P<name>[^/]+)"
    r"/(?P<verb>exec|attach)$"
)
RESOURCE_RE = re.compile(
    r"^/(?:api/(?P<core_version>v1)|apis/(?P<group>[^/]+)/(?P<version>[^/]+))"
    r"/namespaces/(?P<ns>[^/]+)/(?P<plural>[^/]+)(?:/(?P<name>[^/]+))?$"
)

_PLURALS = {
    "pods": "v1/Pod",
    "configmaps": "v1/ConfigMap",
    "secrets": "v1/Secret",
    "services": "v1/Service",
    "deployments": "apps/v1/Deployment",
    "statefulsets": "apps/v1/StatefulSet",
    "jobs": "batch/v1/Job",
}


class ClusterProxyServer:
    """Serves the proxy subresource over real HTTP with token auth ->
    impersonation headers -> member dispatch."""

    def __init__(
        self,
        members: MemberClientRegistry,
        address: tuple[str, int] = ("127.0.0.1", 0),
        *,
        tokens: Optional[dict[str, tuple[str, list[str]]]] = None,
    ):
        self.members = members
        #: bearer token -> (user, groups): the unified-auth table (the
        #: reference derives identity from the aggregated apiserver's
        #: authentication; agents register tokens via the CSR flow)
        self.tokens = dict(tokens or {})
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet test output
                pass

            def do_GET(self):
                outer._handle(self)

            def do_POST(self):
                # kubectl issues the exec/attach subresource as POST
                outer._handle(self)

        self._httpd = ThreadingHTTPServer(address, Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- request handling --------------------------------------------------

    def _authenticate(self, handler) -> Optional[tuple[str, list[str]]]:
        auth = handler.headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return None
        return self.tokens.get(auth[len("Bearer "):])

    def _handle(self, handler) -> None:
        parsed = urlparse(handler.path)
        m = PROXY_RE.match(parsed.path)
        if m is None:
            self._error(handler, 404, "not a cluster proxy path")
            return
        identity = self._authenticate(handler)
        if identity is None:
            self._error(handler, 401, "invalid or missing bearer token")
            return
        user, groups = identity
        cluster = m.group("cluster")
        member = self.members.get(cluster)
        if member is None:
            self._error(handler, 404, f"cluster {cluster} not registered")
            return
        # impersonation-based unified auth: the member request carries the
        # CALLER's identity (proxy.go ConnectCluster sets these from the
        # requesting user before dialing the member)
        impersonation = {
            "Impersonate-User": user,
            "Impersonate-Group": groups,
        }
        sub_path = m.group("path") or "/"
        multi = parse_qs(parsed.query)
        query = {k: v[-1] for k, v in multi.items()}
        try:
            self._dispatch(
                handler, member, sub_path, query, impersonation, multi
            )
        except UnreachableError as e:
            self._fail(handler, 503, str(e))
        except (KeyError, ValueError) as e:
            self._fail(handler, 404 if isinstance(e, KeyError) else 400, str(e))
        except OSError as e:
            # e.g. an exec runtime whose command does not exist
            # (FileNotFoundError from Popen) — a clean 400 beats a
            # dropped connection the client sees as a protocol failure
            self._fail(handler, 400, str(e))

    def _fail(self, handler, code: int, message: str) -> None:
        """Error path that respects an already-started chunked stream: once
        headers are out, a second status line would corrupt the response —
        terminate the stream instead."""
        if getattr(handler, "_streamed", False):
            try:
                handler.wfile.write(b"0\r\n\r\n")
                handler.wfile.flush()
            except OSError:
                pass
            return
        self._error(handler, code, message)

    def _dispatch(
        self, handler, member, path, query, impersonation, multi=None
    ) -> None:
        member.record_proxy_request(path, impersonation)
        log_m = POD_LOG_RE.match(path)
        if log_m is not None:
            self._serve_logs(handler, member, log_m, query)
            return
        exec_m = POD_EXEC_RE.match(path)
        if exec_m is not None:
            self._serve_exec(handler, member, exec_m, multi or {})
            return
        res_m = RESOURCE_RE.match(path)
        if res_m is not None:
            gvk = _PLURALS.get(res_m.group("plural"))
            if gvk is None:
                self._error(handler, 404, f"unknown resource {res_m.group('plural')}")
                return
            ns, name = res_m.group("ns"), res_m.group("name")
            if name:
                obj = member.get(gvk, ns, name)
                if obj is None:
                    self._error(handler, 404, f"{gvk} {ns}/{name} not found")
                    return
                self._json(handler, 200, resource_to_dict(obj))
            else:
                items = [
                    resource_to_dict(o)
                    for o in member.list(gvk)
                    if o.meta.namespace == ns
                ]
                self._json(handler, 200, {"kind": "List", "items": items})
            return
        self._error(handler, 501, f"path {path} not proxied in-proc")

    def _serve_exec(self, handler, member, m, multi) -> None:
        """Streaming exec/attach subresource: output lines chunk out AS
        the member runtime produces them (the SPDY-session analogue —
        ref pkg/karmadactl/exec/exec.go holds the stream through the
        proxy; with SubprocessExecRuntime wired on the member this pipes
        a real OS process end-to-end). ``command`` repeats per argv
        element, kube-style; attach streams with no command."""
        ns, name = m.group("ns"), m.group("name")
        command = list(multi.get("command") or [])
        if m.group("verb") == "attach" or not command:
            # attach = follow the pod's log stream (no new process)
            self._serve_logs(
                handler, member, POD_LOG_RE.match(
                    f"/api/v1/namespaces/{ns}/pods/{name}/log"
                ), {"follow": "true"},
            )
            return
        # pod existence (and member reachability) check BEFORE headers go
        # out so failures are still clean HTTP errors
        stream = member.pod_exec_stream(ns, name, command)
        first = next(stream, None)
        chunk = self._start_chunked(handler)
        try:
            if first is not None:
                chunk(first.encode() + b"\n")
                for line in stream:
                    chunk(line.encode() + b"\n")
        except Exception as exc:  # noqa: BLE001 — headers are out: report
            # the runtime failure IN-BAND (like an SPDY session would) and
            # still terminate the chunked stream cleanly
            chunk(f"error: {exc}".encode() + b"\n")
        chunk(b"")
        handler.wfile.flush()

    def _serve_logs(self, handler, member, m, query) -> None:
        ns, name = m.group("ns"), m.group("name")
        tail = None
        if "tailLines" in query:
            try:
                tail = int(query["tailLines"])
            except ValueError:
                raise ValueError(f"invalid tailLines {query['tailLines']!r}")
        follow = query.get("follow", "") in ("true", "1")
        # ONE snapshot read: computing `seen` from a second read would skip
        # lines appended between the two reads
        all_lines = member.pod_logs(ns, name)
        seen = len(all_lines)
        lines = all_lines if tail is None else (
            all_lines[-tail:] if tail > 0 else []
        )
        chunk = self._start_chunked(handler)
        for line in lines:
            chunk(line.encode() + b"\n")
        if follow:
            # stream lines appended AFTER the snapshot; the in-proc follow
            # holds the pipe open until the member goes quiet for the grace
            # window (a real deployment pipes the member response body
            # until the client disconnects)
            while True:
                fresh = member.wait_pod_logs(ns, name, seen, timeout=0.5)
                if not fresh:
                    break
                for line in fresh:
                    chunk(line.encode() + b"\n")
                seen += len(fresh)
        chunk(b"")  # zero-length chunk terminates the stream
        handler.wfile.flush()

    @staticmethod
    def _start_chunked(handler):
        """Send streaming headers and return the chunk writer (shared by
        the log-follow and exec paths). Marks the handler streamed so
        later failures terminate the stream instead of re-responding."""
        handler.send_response(200)
        handler.send_header("Content-Type", "text/plain")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()
        handler._streamed = True

        def chunk(data: bytes) -> None:
            handler.wfile.write(f"{len(data):X}\r\n".encode())
            handler.wfile.write(data)
            handler.wfile.write(b"\r\n")
            handler.wfile.flush()

        return chunk

    @staticmethod
    def _json(handler, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    @staticmethod
    def _error(handler, code: int, message: str) -> None:
        body = json.dumps({"error": message}).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
