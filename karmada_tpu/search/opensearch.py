"""OpenSearch wire-protocol backend store (server + client).

Ref: pkg/search/backendstore/opensearch.go — the reference's
``backend: opensearch`` store speaks the real OpenSearch REST API:

- index-per-kind named ``{prefix}-{lowercase kind}`` created lazily with
  a mapping (``PUT /{index}``, "already exists" tolerated;
  opensearch.go:250-284);
- one document per object keyed by UID (``PUT /{index}/_doc/{uid}``,
  ``DELETE /{index}/_doc/{uid}``; opensearch.go:158-247), with the
  member cluster recorded in the ``resource.karmada.io/cached-from-cluster``
  annotation and ``spec``/``status`` serialized as JSON STRINGS inside
  the document (opensearch.go:203-218).

This module carries that protocol for the TPU-native plane:

- ``OpenSearchServer`` — an HTTP process serving the REST subset the
  reference client issues (index create, _doc index/delete, _search
  with query_string/match_all, _delete_by_query, _count, NDJSON _bulk)
  over the in-proc inverted-index document store. It stands in for a
  real OpenSearch node in tests AND documents exactly which slice of
  the API the plane depends on.
- ``OpenSearchBackend`` — a ``BackendStore`` implementation speaking
  that protocol (the opensearch-go client analogue): per-event
  IndexRequest/DeleteRequest semantics, UID document ids, index-per-
  kind, the reference's document shape, plus buffered NDJSON ``_bulk``
  flushing (the reference marks bulk "TODO"; the wire format is the
  standard one so a real OpenSearch accepts it).

Run the server: ``python -m karmada_tpu.search.opensearch``.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterable, Optional

from ..api.core import ObjectMeta, Resource
from .backend import InvertedIndexBackend

CACHE_SOURCE_ANNOTATION = "resource.karmada.io/cached-from-cluster"
# cluster/v1alpha1/well_known_constants.go:35 CacheSourceAnnotationKey
DEFAULT_PREFIX = "kubernetes"  # opensearch.go:39 defaultPrefix


def index_name(kind: str, prefix: str = DEFAULT_PREFIX) -> str:
    return f"{prefix}-{kind.lower()}"


def rfc3339(epoch: Optional[float]) -> str:
    """Go time.Format(RFC3339): the zero Time renders as year 1, which is
    exactly what GetCreationTimestamp() yields for objects without one."""
    if not epoch:
        return "0001-01-01T00:00:00Z"
    from datetime import datetime, timezone

    return datetime.fromtimestamp(epoch, timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )


def doc_id(cluster: str, obj: Resource) -> str:
    """UID when the object has one (the reference's DocumentID), else a
    deterministic key — simulated members don't always stamp UIDs."""
    return obj.meta.uid or (
        f"{cluster}/{obj.api_version}/{obj.kind}/"
        f"{obj.meta.namespace}/{obj.meta.name}"
    )


def resource_to_doc(cluster: str, obj: Resource) -> dict:
    """The reference's document shape (opensearch.go:203-218): metadata
    fields flattened, the cache-source annotation stamped, spec/status as
    JSON strings."""
    annotations = dict(obj.meta.annotations)
    annotations[CACHE_SOURCE_ANNOTATION] = cluster
    return {
        "apiVersion": obj.api_version,
        "kind": obj.kind,
        "metadata": {
            "name": obj.meta.name,
            "namespace": obj.meta.namespace,
            "creationTimestamp": rfc3339(obj.meta.creation_timestamp),
            "labels": dict(obj.meta.labels),
            "annotations": annotations,
            "deletionTimestamp": (
                rfc3339(obj.meta.deletion_timestamp)
                if obj.meta.deletion_timestamp
                else None
            ),
        },
        "spec": json.dumps(obj.spec),
        "status": json.dumps(obj.status),
    }


def doc_to_resource(doc: dict) -> tuple[str, Resource]:
    """(cluster, Resource) from the reference-shaped document."""
    meta = doc.get("metadata") or {}
    annotations = dict(meta.get("annotations") or {})
    cluster = annotations.pop(CACHE_SOURCE_ANNOTATION, "")

    def _parse(v):
        if isinstance(v, str):
            try:
                return json.loads(v) or {}
            except ValueError:
                return {}
        return v or {}

    return cluster, Resource(
        api_version=doc.get("apiVersion", ""),
        kind=doc.get("kind", ""),
        meta=ObjectMeta(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", ""),
            labels=dict(meta.get("labels") or {}),
            annotations=annotations,
        ),
        spec=_parse(doc.get("spec")),
        status=_parse(doc.get("status")),
    )


class OpenSearchServer:
    """An OpenSearch-node stand-in: the REST subset the plane speaks,
    over the inverted-index document store."""

    def __init__(self, address: tuple[str, int] = ("127.0.0.1", 0)):
        self.index = InvertedIndexBackend()
        self.indices: dict[str, dict] = {}  # index name -> mapping body
        # _doc id -> (cluster, gvk, namespace, name) for deletes, plus the
        # reverse map so a client that only knows the object coordinates
        # (our BackendStore.delete signature) can address a UID-keyed doc
        # via the deterministic fallback id
        self._ids: dict[str, tuple[str, str, str, str]] = {}
        self._by_key: dict[tuple[str, str, str, str], str] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            # -- helpers --------------------------------------------------
            def _body(self) -> bytes:
                length = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(length) if length else b""

            def _reply(self, status, payload):
                data = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _index_doc(self, _index: str, _id: str, doc: dict) -> dict:
                cluster, obj = doc_to_resource(doc)
                key = (
                    cluster, f"{obj.api_version}/{obj.kind}",
                    obj.meta.namespace, obj.meta.name,
                )
                with outer._lock:
                    created = _id not in outer._ids
                    outer._ids[_id] = key
                    outer._by_key[key] = _id
                outer.index.upsert(cluster, obj)
                return {
                    "_index": _index, "_id": _id,
                    "result": "created" if created else "updated",
                }

            def _delete_doc(self, _index: str, _id: str) -> dict:
                with outer._lock:
                    key = outer._ids.pop(_id, None)
                    if key is None:
                        # coordinate-form fallback id: the doc itself may
                        # be keyed by UID — resolve through the reverse map
                        parts = _id.split("/")
                        if len(parts) >= 5:
                            cand = (
                                parts[0], "/".join(parts[1:-2]),
                                parts[-2], parts[-1],
                            )
                            real = outer._by_key.get(cand)
                            if real is not None:
                                key = outer._ids.pop(real, None)
                    if key is not None:
                        outer._by_key.pop(key, None)
                if key is None:
                    return {"_index": _index, "_id": _id,
                            "result": "not_found"}
                outer.index.delete(*key)
                return {"_index": _index, "_id": _id, "result": "deleted"}

            def _search(self, body: dict, limit_default=100) -> dict:
                query = body.get("query") or {}
                size = int(body.get("size", limit_default))
                q = ""
                if "query_string" in query:
                    q = query["query_string"].get("query", "")
                elif "match" in query:
                    q = " ".join(
                        f"{k}:{v}" for k, v in query["match"].items()
                    )
                docs = outer.index.search("" if q == "*" else q, limit=size)
                hits = []
                for d in docs:
                    obj = d["object"]
                    cluster = d.get("cluster", "")
                    key = (
                        cluster, f"{obj.api_version}/{obj.kind}",
                        obj.meta.namespace, obj.meta.name,
                    )
                    with outer._lock:
                        real_id = outer._by_key.get(key)
                    hits.append({
                        "_index": index_name(obj.kind),
                        "_id": real_id or doc_id(cluster, obj),
                        "_source": resource_to_doc(cluster, obj),
                    })
                return {
                    "hits": {
                        "total": {"value": len(hits), "relation": "eq"},
                        "hits": hits,
                    }
                }

            # -- routes ---------------------------------------------------
            def do_PUT(self):
                parts = [p for p in self.path.split("/") if p]
                try:
                    if len(parts) == 1:  # PUT /{index} — create index
                        name = parts[0]
                        with outer._lock:
                            if name in outer.indices:
                                # resource_already_exists, like OpenSearch
                                self._reply(400, {"error": {
                                    "type":
                                    "resource_already_exists_exception",
                                }})
                                return
                            body = self._body()
                            outer.indices[name] = (
                                json.loads(body) if body else {}
                            )
                        self._reply(200, {"acknowledged": True,
                                          "index": name})
                        return
                    if len(parts) == 3 and parts[1] == "_doc":
                        doc = json.loads(self._body())
                        self._reply(
                            200, self._index_doc(parts[0], parts[2], doc)
                        )
                        return
                    self._reply(404, {"error": "no route"})
                except Exception as exc:  # noqa: BLE001 — wire surface
                    self._reply(400, {"error": str(exc)})

            def do_POST(self):
                parts = [p for p in self.path.split("/") if p]
                try:
                    if parts and parts[-1] == "_bulk":
                        self._bulk()
                        return
                    if parts and parts[-1] == "_search":
                        body = self._body()
                        self._reply(
                            200,
                            self._search(json.loads(body) if body else {}),
                        )
                        return
                    if parts and parts[-1] == "_count":
                        self._reply(200, {"count": outer.index.count()})
                        return
                    if len(parts) == 2 and parts[1] == "_delete_by_query":
                        self._delete_by_query(json.loads(self._body()))
                        return
                    if len(parts) == 3 and parts[1] == "_doc":
                        doc = json.loads(self._body())
                        self._reply(
                            200, self._index_doc(parts[0], parts[2], doc)
                        )
                        return
                    self._reply(404, {"error": "no route"})
                except Exception as exc:  # noqa: BLE001 — wire surface
                    self._reply(400, {"error": str(exc)})

            def do_DELETE(self):
                parts = [p for p in self.path.split("/") if p]
                try:
                    if len(parts) == 3 and parts[1] == "_doc":
                        self._reply(
                            200, self._delete_doc(parts[0], parts[2])
                        )
                        return
                    self._reply(404, {"error": "no route"})
                except Exception as exc:  # noqa: BLE001 — wire surface
                    self._reply(400, {"error": str(exc)})

            def do_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                if parsed.path == "/":
                    self._reply(200, {"tagline": "opensearch stand-in"})
                elif parsed.path.endswith("/_count"):
                    self._reply(200, {"count": outer.index.count()})
                else:
                    self._reply(404, {"error": "no route"})

            def _bulk(self):
                """NDJSON _bulk: alternating action and source lines
                (the standard wire format; delete actions carry no
                source line). Item results mirror OpenSearch's."""
                lines = [
                    ln for ln in self._body().decode().split("\n") if ln
                ]
                items = []
                errors = False
                i = 0
                while i < len(lines):
                    action = json.loads(lines[i])
                    i += 1
                    if "index" in action or "create" in action:
                        meta = action.get("index") or action.get("create")
                        doc = json.loads(lines[i])
                        i += 1
                        res = self._index_doc(
                            meta.get("_index", ""), meta.get("_id", ""), doc
                        )
                        items.append({"index": {**res, "status": 200}})
                    elif "delete" in action:
                        meta = action["delete"]
                        res = self._delete_doc(
                            meta.get("_index", ""), meta.get("_id", "")
                        )
                        items.append({"delete": {**res, "status": 200}})
                    else:
                        items.append({"unknown": {"status": 400}})
                        errors = True
                self._reply(200, {"errors": errors, "items": items})

            def _delete_by_query(self, body: dict):
                """The subset drop_cluster needs: match on the cache-
                source annotation."""
                query = (body.get("query") or {}).get("match") or {}
                cluster = query.get(
                    f"metadata.annotations.{CACHE_SOURCE_ANNOTATION}", ""
                )
                if not cluster:
                    self._reply(400, {"error": "unsupported query"})
                    return
                with outer._lock:
                    gone = [
                        _id for _id, key in outer._ids.items()
                        if key[0] == cluster
                    ]
                    for _id in gone:
                        key = outer._ids.pop(_id, None)
                        if key is not None:
                            outer._by_key.pop(key, None)
                outer.index.drop_cluster(cluster)
                self._reply(200, {"deleted": len(gone)})

        self._httpd = ThreadingHTTPServer(address, Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class OpenSearchBackend:
    """``BackendStore`` over the OpenSearch REST protocol (the
    opensearch-go client analogue): lazily-created index per kind, UID
    document ids, the reference's document shape, buffered NDJSON
    ``_bulk`` flushes. Points at ``OpenSearchServer`` in tests and at a
    real OpenSearch node in production — the wire is the same."""

    # the index-create body, field for field the reference's ``mapping``
    # const (opensearch.go:41-116): 1 shard / 0 replicas, searchable
    # name/namespace with 256-char keyword subfields, annotations/labels
    # stored-not-indexed, and spec/status disabled objects (the documents
    # carry them as JSON strings)
    MAPPING = {
        "settings": {
            "index": {"number_of_shards": 1, "number_of_replicas": 0}
        },
        "mappings": {
            "properties": {
                "apiVersion": {"type": "text"},
                "kind": {"type": "text"},
                "metadata": {
                    "properties": {
                        "annotations": {"type": "object", "enabled": False},
                        "creationTimestamp": {"type": "text"},
                        "deletionTimestamp": {"type": "text"},
                        "labels": {"type": "object", "enabled": False},
                        "name": {
                            "type": "text",
                            "fields": {
                                "keyword": {
                                    "type": "keyword", "ignore_above": 256
                                }
                            },
                        },
                        "namespace": {
                            "type": "text",
                            "fields": {
                                "keyword": {
                                    "type": "keyword", "ignore_above": 256
                                }
                            },
                        },
                        "ownerReferences": {"type": "text"},
                        "resourceVersion": {
                            "type": "text",
                            "fields": {
                                "keyword": {
                                    "type": "keyword", "ignore_above": 256
                                }
                            },
                        },
                    }
                },
                "spec": {"type": "object", "enabled": False},
                "status": {"type": "object", "enabled": False},
            }
        },
    }

    def __init__(
        self,
        target: str,
        *,
        prefix: str = DEFAULT_PREFIX,
        batch_size: int = 64,
        timeout_seconds: float = 5.0,
    ):
        self.target = target
        self.prefix = prefix
        self.batch_size = batch_size
        self.timeout = timeout_seconds
        self._indices: set[str] = set()
        self._buffer: list[str] = []  # NDJSON lines
        # (cluster, gvk, ns, name) -> indexed _id: deletes only know the
        # object coordinates while documents key by UID on the node, so
        # the client remembers what it indexed under which id — against a
        # REAL OpenSearch the coordinate-form fallback id would address
        # nothing (the reference's informer always has the object, so its
        # deletes carry the UID; ours reconstructs it from this map)
        self._doc_ids: dict[tuple[str, str, str, str], str] = {}
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()
        self.dropped = 0

    # -- HTTP helpers -------------------------------------------------------

    def _request(self, method: str, path: str, body: Optional[bytes] = None,
                 content_type: str = "application/json"):
        req = urllib.request.Request(
            f"http://{self.target}{path}", data=body, method=method,
            headers={"Content-Type": content_type},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read() or b"{}")

    def _ensure_index(self, kind: str) -> str:
        name = index_name(kind, self.prefix)
        if name in self._indices:
            return name
        try:
            self._request(
                "PUT", f"/{name}", json.dumps(self.MAPPING).encode()
            )
        except urllib.error.HTTPError as e:
            # OpenSearch answers 400 for validation failures too — only
            # the already-exists TYPE is benign (opensearch.go:264 checks
            # the exception type, not the status code)
            try:
                err = json.loads(e.read()).get("error") or {}
                etype = err.get("type", "") if isinstance(err, dict) else ""
            except Exception:  # noqa: BLE001 — wire surface
                etype = ""
            if etype != "resource_already_exists_exception":
                raise
        self._indices.add(name)
        return name

    # -- BackendStore -------------------------------------------------------

    def upsert(self, cluster: str, obj: Resource) -> None:
        name = self._ensure_index(obj.kind)
        _id = doc_id(cluster, obj)
        with self._lock:
            self._doc_ids[(
                cluster, f"{obj.api_version}/{obj.kind}",
                obj.meta.namespace, obj.meta.name,
            )] = _id
        action = json.dumps({"index": {"_index": name, "_id": _id}})
        source = json.dumps(resource_to_doc(cluster, obj))
        self._enqueue([action, source])

    def delete(self, cluster: str, gvk: str, namespace: str, name: str) -> None:
        kind = gvk.rsplit("/", 1)[-1]
        key = (cluster, gvk, namespace, name)
        with self._lock:
            _id = self._doc_ids.pop(key, None)
        if _id is None:
            # never indexed by this client: the deterministic fallback id
            obj = Resource(
                api_version=gvk.rsplit("/", 1)[0], kind=kind,
                meta=ObjectMeta(name=name, namespace=namespace),
            )
            _id = doc_id(cluster, obj)
        self._enqueue([json.dumps({"delete": {
            "_index": index_name(kind, self.prefix),
            "_id": _id,
        }})])

    def drop_cluster(self, cluster: str) -> None:
        self.flush()
        with self._lock:
            for key in [k for k in self._doc_ids if k[0] == cluster]:
                self._doc_ids.pop(key, None)
        # wildcard across every kind index (a real node 404s a literal
        # nonexistent index; '{prefix}-*' is the standard multi-index form)
        self._request(
            "POST", f"/{self.prefix}-*/_delete_by_query",
            json.dumps({"query": {"match": {
                f"metadata.annotations.{CACHE_SOURCE_ANNOTATION}": cluster,
            }}}).encode(),
        )

    def _enqueue(self, lines: list[str]) -> None:
        with self._lock:
            self._buffer.extend(lines)
            should = len(self._buffer) >= 2 * self.batch_size
        if should:
            self.flush()

    def flush(self) -> bool:
        with self._send_lock:
            with self._lock:
                if not self._buffer:
                    return True
                batch, self._buffer = self._buffer, []
            body = ("\n".join(batch) + "\n").encode()
            try:
                resp = self._request(
                    "POST", "/_bulk", body, "application/x-ndjson"
                )
                if resp.get("errors"):
                    self.dropped += sum(
                        1
                        for item in resp.get("items", [])
                        for v in item.values()
                        if v.get("status", 200) >= 400
                    )
                return True
            except urllib.error.HTTPError:
                # count OPERATIONS, not NDJSON lines (index ops carry an
                # action line AND a source line)
                self.dropped += sum(
                    1
                    for ln in batch
                    if ln.startswith(('{"index"', '{"create"', '{"delete"'))
                )
                return False
            except (urllib.error.URLError, OSError):
                with self._lock:
                    self._buffer = batch + self._buffer  # retry in order
                return False

    # -- queries ------------------------------------------------------------

    def search(
        self,
        query: str = "",
        *,
        clusters: Optional[Iterable[str]] = None,
        limit: int = 100,
    ) -> list[dict]:
        self.flush()
        body = {
            "size": limit,
            "query": (
                {"query_string": {"query": query}}
                if query
                else {"match_all": {}}
            ),
        }
        resp = self._request("POST", "/_search", json.dumps(body).encode())
        out = []
        want = set(clusters) if clusters else None
        for hit in resp.get("hits", {}).get("hits", []):
            cluster, obj = doc_to_resource(hit.get("_source") or {})
            if want is not None and cluster not in want:
                continue
            out.append({
                "cluster": cluster, "gvk": f"{obj.api_version}/{obj.kind}",
                "namespace": obj.meta.namespace, "name": obj.meta.name,
                "object": obj,
            })
        return out

    def count(self) -> int:
        self.flush()
        return int(self._request("GET", "/_count").get("count", 0))


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--address", default="127.0.0.1:0")
    args = p.parse_args(argv)
    from ..utils.net import parse_hostport

    server = OpenSearchServer(parse_hostport(args.address, default_host=""))
    bound = server.start()
    print(f"opensearch stand-in listening on port {bound}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
