"""Cross-cluster resource search, cache and proxy (ref: pkg/search).

- ResourceRegistry selects which GVKs to cache from which clusters
  (pkg/search/controller.go:79-430 builds per-cluster informer caches).
- MultiClusterCache answers list/get across member caches
  (pkg/search/proxy/store/multi_cluster_cache.go).
- The proxy framework chains plugins cache -> member cluster -> karmada
  control plane (pkg/search/proxy/framework/plugins/, order karmada.go:68-74).
"""

from .registry import ResourceRegistry, ResourceRegistrySpec, SearchController  # noqa: F401
from .proxy import Proxy, ProxyRequest, ProxyResponse  # noqa: F401
