"""Search backend stores: pluggable indexers behind ResourceRegistry.

Ref: pkg/search/backendstore (interface.go: BackendStore with
ResourceEventHandlerFuncs — OnAdd/OnUpdate/OnDelete per registry; default
in-memory cacher, opensearch.go: documents indexed per cluster with
bulk upserts and deletes, queried by the search API).

The reference's OpenSearch backend ships objects to an external indexer as
JSON documents keyed ``{cluster}/{namespace}/{name}``. The analogue here is
an in-process inverted-index document store with the same document shape and
life-cycle (upsert/delete per watch event, drop-by-cluster on cluster
removal) and a query surface covering the search API's needs: term match
over tokenized fields, field-scoped terms (``kind:Deployment``,
``label:app=web``), prefix match, and conjunction. An external OpenSearch
can implement the same ``BackendStore`` protocol against a real cluster.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Iterable, Optional, Protocol

from ..api.core import Resource


class BackendStore(Protocol):
    """backendstore.BackendStore: watch-event sink + lifecycle."""

    def upsert(self, cluster: str, obj: Resource) -> None: ...

    def delete(self, cluster: str, gvk: str, namespace: str, name: str) -> None: ...

    def drop_cluster(self, cluster: str) -> None: ...


def _doc_id(cluster: str, gvk: str, namespace: str, name: str) -> str:
    return f"{cluster}/{gvk}/{namespace}/{name}"


def _tokenize(text: str) -> list[str]:
    out, cur = [], []
    for ch in text.lower():
        if ch.isalnum():
            cur.append(ch)
        else:
            if cur:
                out.append("".join(cur))
            cur = []
    if cur:
        out.append("".join(cur))
    return out


class InvertedIndexBackend:
    """The opensearch.go analogue: objects as documents in an inverted
    index; terms carry optional field scopes."""

    def __init__(self) -> None:
        self._docs: dict[str, dict] = {}
        self._index: dict[str, set[str]] = defaultdict(set)
        self._by_cluster: dict[str, set[str]] = defaultdict(set)
        self._lock = threading.Lock()

    # -- BackendStore -------------------------------------------------------

    def upsert(self, cluster: str, obj: Resource) -> None:
        gvk = f"{obj.api_version}/{obj.kind}"
        doc_id = _doc_id(cluster, gvk, obj.meta.namespace, obj.meta.name)
        doc = {
            "cluster": cluster,
            "apiVersion": obj.api_version,
            "kind": obj.kind,
            "namespace": obj.meta.namespace,
            "name": obj.meta.name,
            "labels": dict(obj.meta.labels),
            "annotations": dict(obj.meta.annotations),
            "object": obj,
        }
        terms = set()
        for field_name in ("cluster", "kind", "namespace", "name"):
            for tok in _tokenize(doc[field_name]):
                terms.add(tok)
                terms.add(f"{field_name}:{tok}")
        for k, v in obj.meta.labels.items():
            terms.add(f"label:{k.lower()}={v.lower()}")
            terms.update(_tokenize(v))
        with self._lock:
            self._remove_locked(doc_id)
            self._docs[doc_id] = doc
            self._by_cluster[cluster].add(doc_id)
            for t in terms:
                self._index[t].add(doc_id)
            doc["_terms"] = terms

    def delete(self, cluster: str, gvk: str, namespace: str, name: str) -> None:
        with self._lock:
            self._remove_locked(_doc_id(cluster, gvk, namespace, name))

    def drop_cluster(self, cluster: str) -> None:
        with self._lock:
            for doc_id in list(self._by_cluster.get(cluster, ())):
                self._remove_locked(doc_id)
            self._by_cluster.pop(cluster, None)

    # called-with-lock-held helper (the ``_locked`` suffix contract):
    # every caller above holds self._lock
    # graftlint: disable=GL004,GL011
    def _remove_locked(self, doc_id: str) -> None:
        doc = self._docs.pop(doc_id, None)
        if doc is None:
            return
        for t in doc.get("_terms", ()):
            bucket = self._index.get(t)
            if bucket:
                bucket.discard(doc_id)
                if not bucket:
                    del self._index[t]
        self._by_cluster[doc["cluster"]].discard(doc_id)

    # -- query surface ------------------------------------------------------

    def search(
        self,
        query: str = "",
        *,
        clusters: Optional[Iterable[str]] = None,
        limit: int = 100,
    ) -> list[dict]:
        """Conjunction of query terms. Term forms: bare token, ``field:tok``
        (cluster/kind/namespace/name), ``label:k=v``, trailing ``*`` prefix."""
        with self._lock:
            candidates: Optional[set[str]] = None
            for raw in query.split():
                term = raw.lower()
                if term.endswith("*"):
                    prefix = term[:-1]
                    matched: set[str] = set()
                    for t, ids in self._index.items():
                        if t.startswith(prefix):
                            matched |= ids
                else:
                    matched = set(self._index.get(term, ()))
                candidates = matched if candidates is None else candidates & matched
            if candidates is None:  # empty query = everything
                candidates = set(self._docs)
            if clusters is not None:
                allowed = set(clusters)
                candidates = {d for d in candidates if self._docs[d]["cluster"] in allowed}
            docs = sorted(candidates)[:limit]
            return [
                {k: v for k, v in self._docs[d].items() if not k.startswith("_")}
                for d in docs
            ]

    def count(self) -> int:
        with self._lock:
            return len(self._docs)
