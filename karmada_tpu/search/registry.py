"""ResourceRegistry + multi-cluster cache.

Ref: pkg/apis/search/v1alpha1 (ResourceRegistry: target clusters + resource
selectors + backend) and pkg/search/controller.go (per-cluster caches for the
selected GVKs).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import base64
import json

from ..api.core import ObjectMeta, Resource
from ..api.policy import ClusterAffinity
from ..utils import DONE, Runtime, Store
from ..utils.member import MemberClientRegistry, UnreachableError


def encode_token(payload: dict) -> str:
    """Opaque list token (the reference base64-encodes JSON the same way —
    multiClusterResourceVersion.String / multiClusterContinue.String)."""
    return base64.urlsafe_b64encode(
        json.dumps(payload, sort_keys=True).encode()
    ).decode()


def decode_token(token: str) -> dict:
    try:
        return json.loads(base64.urlsafe_b64decode(token.encode()))
    except Exception:
        return {}


@dataclass
class ResourceRegistrySpec:
    target_cluster: ClusterAffinity = field(default_factory=ClusterAffinity)
    resource_selectors: list[dict] = field(default_factory=list)  # {apiVersion, kind}
    backend: str = "cache"  # cache | opensearch (external indexer plug point)


@dataclass
class ResourceRegistry:
    KIND = "ResourceRegistry"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceRegistrySpec = field(default_factory=ResourceRegistrySpec)


class MultiClusterCache:
    """(cluster, gvk, namespace, name) -> Resource, queryable across
    clusters. Fed by the SearchController's collection sweeps (the informer
    analogue)."""

    def __init__(self) -> None:
        self._items: dict[tuple[str, str, str, str], Resource] = {}

    def put(self, cluster: str, obj: Resource) -> None:
        self._items[
            (cluster, f"{obj.api_version}/{obj.kind}", obj.meta.namespace, obj.meta.name)
        ] = obj

    def drop_cluster(self, cluster: str) -> None:
        self._items = {
            k: v for k, v in self._items.items() if k[0] != cluster
        }

    def clear(self) -> None:
        self._items = {}

    def get(
        self, gvk: str, namespace: str, name: str, cluster: Optional[str] = None
    ) -> Optional[tuple[str, Resource]]:
        for (c, g, ns, n), obj in self._items.items():
            if g == gvk and ns == namespace and n == name:
                if cluster is None or c == cluster:
                    return c, obj
        return None

    def list(
        self,
        gvk: str,
        namespace: Optional[str] = None,
        labels: Optional[dict[str, str]] = None,
    ) -> list[tuple[str, Resource]]:
        out = []
        for (c, g, ns, _), obj in self._items.items():
            if g != gvk:
                continue
            if namespace is not None and ns != namespace:
                continue
            if labels and any(
                obj.meta.labels.get(k) != v for k, v in labels.items()
            ):
                continue
            out.append((c, obj))
        return sorted(out, key=lambda t: (t[0], t[1].meta.namespaced_name))

    def list_paged(
        self,
        gvk: str,
        namespace: Optional[str] = None,
        labels: Optional[dict[str, str]] = None,
        limit: int = 0,
        continue_token: str = "",
        cluster: Optional[str] = None,
    ) -> tuple[list[tuple[str, Resource]], str, str]:
        """Paged multi-cluster list (ref: pkg/search/proxy/store/
        multi_cluster_cache.go:187-265): items stream cluster by cluster in
        name order; the continue token records (cluster, last item) so the
        next page resumes mid-cluster and then moves on; the returned
        resource version is the per-cluster rv map (the reference's
        multiClusterResourceVersion encoding). Returns
        (items, next_continue, resource_version)."""
        everything = self.list(gvk, namespace, labels)
        if cluster is not None:
            # cluster scoping must precede the page window, or the limit
            # counts items the caller never sees
            everything = [(c, o) for c, o in everything if c == cluster]
        start_cluster, after = "", ""
        if continue_token:
            tok = decode_token(continue_token)
            start_cluster = tok.get("cluster", "")
            after = tok.get("after", "")
        # multi-cluster rv covers EVERY cluster contributing to the full
        # list, independent of the page window
        rv_map: dict[str, int] = {}
        for c, obj in everything:
            rv_map[c] = max(rv_map.get(c, 0), obj.meta.resource_version)
        items: list[tuple[str, Resource]] = []
        next_token = ""
        for c, obj in everything:
            key = obj.meta.namespaced_name
            if c < start_cluster or (c == start_cluster and after and key <= after):
                continue
            if limit and len(items) >= limit:
                last_c, last_obj = items[-1]
                next_token = encode_token(
                    {"cluster": last_c, "after": last_obj.meta.namespaced_name}
                )
                break
            items.append((c, obj))
        return items, next_token, encode_token(rv_map)


class SearchController:
    """Builds/refreshes the cache for every ResourceRegistry
    (pkg/search/controller.go)."""

    def __init__(
        self, store: Store, runtime: Runtime, members: MemberClientRegistry,
        indexer=None,
    ) -> None:
        from .backend import InvertedIndexBackend

        self.store = store
        self.members = members
        self.cache = MultiClusterCache()
        # registries with spec.backend == "opensearch" additionally index
        # into the document backend (backendstore/opensearch.go analogue).
        # Inject an HttpIndexerBackend (search/indexer.py) to ship the
        # documents to an EXTERNAL indexer process over the wire instead.
        self.indexer = indexer if indexer is not None else InvertedIndexBackend()
        # registry key -> doc keys it indexed last pass; the diff drives
        # deletions so member-side removals and backend switches don't
        # leave stale documents
        self._indexed: dict[str, set[tuple[str, str, str, str]]] = {}
        self.enabled = True  # addon toggle (karmada-search install state)
        self.worker = runtime.new_worker("search", self._reconcile)
        store.watch("ResourceRegistry", lambda e: self.worker.enqueue(e.key))
        runtime.add_ticker(self._sweep)

    def _sweep(self) -> None:
        if not self.enabled:
            return
        for rr in self.store.list("ResourceRegistry"):
            self.worker.enqueue(rr.meta.namespaced_name)
        # networked backends buffer bulk batches; the periodic sweep drains
        # them so documents don't sit unshipped between watch bursts
        flush = getattr(self.indexer, "flush", None)
        if flush is not None:
            flush()

    def resync(self) -> None:
        """Re-enqueue every registry (addon enable / manual refresh)."""
        self.enabled = True
        self._sweep()

    def disable(self) -> None:
        """addon disable: stop refreshing and drop cached state (the
        uninstall analogue — the aggregated API goes away)."""
        self.enabled = False
        for rr in list(self._indexed):
            for doc in self._indexed.pop(rr, set()):
                self.indexer.delete(*doc)
        # networked backends buffer deletions: ship them now — the sweep
        # no longer runs once disabled
        flush = getattr(self.indexer, "flush", None)
        if flush is not None:
            flush()
        self.cache.clear()

    def _reconcile(self, key: str) -> Optional[str]:
        if not self.enabled:
            return DONE
        rr = self.store.get("ResourceRegistry", key)
        index = rr is not None and rr.spec.backend == "opensearch"
        fresh: set[tuple[str, str, str, str]] = set()
        if rr is not None:
            for cluster in self.store.list("Cluster"):
                if not rr.spec.target_cluster.matches(cluster):
                    continue
                member = self.members.get(cluster.name)
                if member is None or not member.reachable:
                    continue
                for sel in rr.spec.resource_selectors:
                    gvk = f"{sel.get('apiVersion', 'v1')}/{sel.get('kind', '')}"
                    try:
                        for obj in member.list(gvk):
                            self.cache.put(cluster.name, obj)
                            if index:
                                self.indexer.upsert(cluster.name, obj)
                                fresh.add(
                                    (cluster.name, gvk, obj.meta.namespace, obj.meta.name)
                                )
                    except UnreachableError:
                        self.cache.drop_cluster(cluster.name)
                        self.indexer.drop_cluster(cluster.name)
                        fresh = {d for d in fresh if d[0] != cluster.name}
        # documents this registry indexed before but not this pass are gone
        # from the members (or the backend/registry changed) — delete them.
        # An overlapping registry that still wants one re-upserts next sweep.
        for doc in self._indexed.get(key, set()) - fresh:
            self.indexer.delete(*doc)
        if fresh:
            self._indexed[key] = fresh
        else:
            self._indexed.pop(key, None)
        return DONE

    def search(self, query: str = "", **kw) -> list[dict]:
        """Search the document backend (the search API surface)."""
        return self.indexer.search(query, **kw)
