"""Proxy framework: ordered plugins answering unified resource requests.

Ref: pkg/search/proxy/framework — a connect chain where each plugin decides
whether it can serve the request; order is cache -> member cluster ->
karmada control plane (pkg/search/proxy/framework/plugins + karmada.go:68-74).
The aggregated-apiserver's clusters/{name}/proxy passthrough
(pkg/registry/cluster/storage/proxy.go:41-102) is the ClusterProxyPlugin.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..api.core import Resource
from ..utils import Store
from ..utils.member import MemberClientRegistry, UnreachableError
from .registry import MultiClusterCache


@dataclass
class ProxyRequest:
    verb: str  # get | list | logs | exec
    gvk: str
    namespace: str = ""
    name: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    # explicit member-cluster routing (clusters/{name}/proxy passthrough)
    cluster: Optional[str] = None
    # subresource payload: logs tail, exec command
    options: dict[str, Any] = field(default_factory=dict)


@dataclass
class ProxyResponse:
    served_by: str  # cache | cluster | karmada
    obj: Optional[Resource] = None
    items: list[tuple[str, Resource]] = field(default_factory=list)
    error: str = ""
    # subresource result (log lines, exec output)
    data: Any = None
    # list paging (multi-cluster continue + resource-version encodings)
    continue_token: str = ""
    resource_version: str = ""


class CachePlugin:
    name = "cache"

    def __init__(self, cache: MultiClusterCache):
        self.cache = cache

    def connect(self, req: ProxyRequest) -> Optional[ProxyResponse]:
        if req.verb not in ("get", "list"):
            return None  # subresources always go to the member
        if req.verb == "get":
            hit = self.cache.get(req.gvk, req.namespace, req.name, req.cluster)
            if hit is not None:
                return ProxyResponse(served_by=self.name, obj=hit[1])
            return None
        limit = int(req.options.get("limit", 0) or 0)
        cont = str(req.options.get("continue", "") or "")
        if limit or cont:
            items, next_token, rv = self.cache.list_paged(
                req.gvk, req.namespace or None, req.labels or None,
                limit=limit, continue_token=cont, cluster=req.cluster,
            )
            if items or cont:
                return ProxyResponse(
                    served_by=self.name, items=items,
                    continue_token=next_token, resource_version=rv,
                )
            return None
        items = self.cache.list(req.gvk, req.namespace or None, req.labels or None)
        if req.cluster is not None:
            items = [(c, o) for c, o in items if c == req.cluster]
        if items:
            return ProxyResponse(served_by=self.name, items=items)
        return None


class ClusterProxyPlugin:
    """Direct passthrough to one member cluster (requires req.cluster)."""

    name = "cluster"

    def __init__(self, members: MemberClientRegistry):
        self.members = members

    def connect(self, req: ProxyRequest) -> Optional[ProxyResponse]:
        if req.cluster is None:
            return None
        member = self.members.get(req.cluster)
        if member is None:
            return ProxyResponse(
                served_by=self.name, error=f"unknown cluster {req.cluster}"
            )
        try:
            if req.verb == "get":
                obj = member.get(req.gvk, req.namespace, req.name)
                if obj is None:
                    return ProxyResponse(
                        served_by=self.name, error="not found"
                    )
                return ProxyResponse(served_by=self.name, obj=obj)
            if req.verb in ("logs", "exec"):
                # pod subresources ride the same clusters/{name}/proxy
                # passthrough that karmadactl logs/exec/attach uses
                # (pkg/registry/cluster/storage/proxy.go:41-102)
                try:
                    if req.verb == "logs":
                        data = member.pod_logs(
                            req.namespace, req.name, tail=req.options.get("tail")
                        )
                    else:
                        data = member.pod_exec(
                            req.namespace, req.name, req.options.get("command", [])
                        )
                except KeyError as e:
                    return ProxyResponse(served_by=self.name, error=str(e))
                return ProxyResponse(served_by=self.name, data=data)
            items = [
                (req.cluster, o)
                for o in member.list(req.gvk)
                if (not req.namespace or o.meta.namespace == req.namespace)
                and all(o.meta.labels.get(k) == v for k, v in req.labels.items())
            ]
            return ProxyResponse(served_by=self.name, items=items)
        except UnreachableError as e:
            return ProxyResponse(served_by=self.name, error=str(e))


class KarmadaPlugin:
    """Fallback: serve from the control-plane store (templates)."""

    name = "karmada"

    def __init__(self, store: Store):
        self.store = store

    def connect(self, req: ProxyRequest) -> Optional[ProxyResponse]:
        if req.verb not in ("get", "list"):
            return ProxyResponse(
                served_by=self.name,
                error=f"verb {req.verb} requires cluster routing",
            )
        if req.verb == "get":
            key = f"{req.namespace}/{req.name}" if req.namespace else req.name
            obj = self.store.get("Resource", key)
            if obj is not None and f"{obj.api_version}/{obj.kind}" == req.gvk:
                return ProxyResponse(served_by=self.name, obj=obj)
            return ProxyResponse(served_by=self.name, error="not found")
        items = [
            ("karmada", o)
            for o in self.store.list("Resource", req.namespace or None)
            if f"{o.api_version}/{o.kind}" == req.gvk
            and all(o.meta.labels.get(k) == v for k, v in req.labels.items())
        ]
        return ProxyResponse(served_by=self.name, items=items)


class Proxy:
    """Ordered plugin chain (karmada.go:68-74: cache, cluster, karmada)."""

    def __init__(
        self,
        store: Store,
        members: MemberClientRegistry,
        cache: MultiClusterCache,
    ) -> None:
        self.plugins = [
            CachePlugin(cache),
            ClusterProxyPlugin(members),
            KarmadaPlugin(store),
        ]

    def connect(self, req: ProxyRequest) -> ProxyResponse:
        for plugin in self.plugins:
            resp = plugin.connect(req)
            if resp is not None:
                return resp
        return ProxyResponse(served_by="", error="no plugin served the request")
