"""Networked search indexer: documents shipped over a wire protocol.

Ref: pkg/search/backendstore/opensearch.go — the reference's OpenSearch
backend POSTs bulk upsert/delete document batches to an EXTERNAL indexer
over HTTP and the search API queries it back. This module is that shape
for the TPU-native plane:

- ``IndexerServer``: a standalone HTTP process hosting the inverted-index
  document store (the OpenSearch stand-in). Endpoints: POST /bulk (batched
  upsert/delete/drop_cluster operations), GET /search, GET /count,
  GET /healthz. Run: ``python -m karmada_tpu.search.indexer``.
- ``HttpIndexerBackend``: a ``BackendStore`` implementation that buffers
  watch events and ships them as bulk batches (opensearch.go's
  BulkIndexer), flushing on batch size or explicitly; queries round-trip
  over HTTP. Drop-in for ``SearchController``'s indexer seam — the
  ResourceRegistry's ``backend: opensearch`` documents land in the remote
  process instead of the in-proc index.

Unreachable-indexer semantics: bulk flushes buffer and retry on the next
flush (the reference's BulkIndexer also queues); queries raise.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterable, Optional
from urllib.parse import parse_qs, urlparse

from ..api.core import Resource
from .backend import InvertedIndexBackend


def _obj_to_doc(obj: Resource) -> dict:
    from ..bus.service import encode_object

    return json.loads(encode_object(obj))


def _doc_to_obj(doc: dict) -> Resource:
    from ..bus.service import decode_object

    return decode_object("Resource", json.dumps(doc))


class IndexerServer:
    """The external indexer process (OpenSearch stand-in)."""

    def __init__(self, address: tuple[str, int] = ("127.0.0.1", 0)):
        self.index = InvertedIndexBackend()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                if self.path != "/bulk":
                    self._reply(404, {"error": "not found"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                # two-phase: validate + decode the WHOLE batch into thunks
                # first, then apply. A malformed op must not leave an
                # applied prefix behind (the old sequential form both
                # persisted the prefix and made the client count the whole
                # batch as poison); on rejection nothing is applied and the
                # failing index is reported so the client drops only it.
                try:
                    ops = json.loads(self.rfile.read(length) or b"[]")
                    if not isinstance(ops, list):
                        raise ValueError("bulk body must be a JSON array")
                except Exception as exc:  # noqa: BLE001 — wire surface
                    self._reply(400, {"error": str(exc), "failed_index": -1})
                    return
                thunks = []
                for i, op in enumerate(ops):
                    try:
                        kind = op.get("op")
                        if kind == "upsert":
                            cluster, obj = op["cluster"], _doc_to_obj(
                                op["object"]
                            )
                            thunks.append(
                                lambda c=cluster, o=obj: outer.index.upsert(
                                    c, o
                                )
                            )
                        elif kind == "delete":
                            a = (
                                op["cluster"], op["gvk"],
                                op["namespace"], op["name"],
                            )
                            thunks.append(
                                lambda a=a: outer.index.delete(*a)
                            )
                        elif kind == "drop_cluster":
                            cluster = op["cluster"]
                            thunks.append(
                                lambda c=cluster: outer.index.drop_cluster(c)
                            )
                        else:
                            raise ValueError(f"unknown op {kind!r}")
                    except Exception as exc:  # noqa: BLE001 — wire surface
                        self._reply(
                            400, {"error": str(exc), "failed_index": i}
                        )
                        return
                applied = 0
                try:
                    for t in thunks:
                        t()
                        applied += 1
                except Exception as exc:  # noqa: BLE001 — an apply-phase
                    # failure must still produce an HTTP response: a dropped
                    # connection reads as TRANSIENT to the client, which
                    # would requeue (and partially re-apply) the same batch
                    # forever. 500 + no failed_index → the client drops the
                    # batch and counts it, making progress.
                    self._reply(
                        500, {"error": str(exc), "applied": applied}
                    )
                    return
                self._reply(200, {"applied": applied})

            def do_GET(self):
                parsed = urlparse(self.path)
                if parsed.path == "/healthz":
                    self._reply(200, {"ok": True})
                elif parsed.path == "/count":
                    self._reply(200, {"count": outer.index.count()})
                elif parsed.path == "/search":
                    q = parse_qs(parsed.query)
                    clusters = q.get("cluster")
                    docs = outer.index.search(
                        q.get("q", [""])[0],
                        clusters=clusters,
                        limit=int(q.get("limit", ["100"])[0]),
                    )
                    out = []
                    for d in docs:
                        d = dict(d)
                        d["object"] = _obj_to_doc(d["object"])
                        out.append(d)
                    self._reply(200, {"hits": out})
                else:
                    self._reply(404, {"error": "not found"})

            def _reply(self, status, payload):
                data = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer(address, Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class HttpIndexerBackend:
    """BackendStore over the wire, with bulk buffering.

    Satisfies the same Protocol as ``InvertedIndexBackend`` (upsert /
    delete / drop_cluster / search / count); watch events buffer locally
    and flush as one POST /bulk per ``batch_size`` events (or on
    ``flush()``), mirroring opensearch.go's BulkIndexer."""

    def __init__(
        self,
        target: str,
        *,
        batch_size: int = 64,
        timeout_seconds: float = 5.0,
    ):
        self.target = target
        self.batch_size = batch_size
        self.timeout = timeout_seconds
        self._buffer: list[dict] = []
        self._lock = threading.Lock()
        # serializes take+POST+requeue so concurrent flushes cannot ship
        # batches out of order (a delete overtaking an older upsert would
        # resurrect the document remotely)
        self._send_lock = threading.Lock()
        self.dropped = 0  # poison ops rejected by the server (HTTP 4xx)

    # -- BackendStore -------------------------------------------------------

    def upsert(self, cluster: str, obj: Resource) -> None:
        self._enqueue(
            {"op": "upsert", "cluster": cluster, "object": _obj_to_doc(obj)}
        )

    def delete(self, cluster: str, gvk: str, namespace: str, name: str) -> None:
        self._enqueue(
            {
                "op": "delete", "cluster": cluster, "gvk": gvk,
                "namespace": namespace, "name": name,
            }
        )

    def drop_cluster(self, cluster: str) -> None:
        self._enqueue({"op": "drop_cluster", "cluster": cluster})

    def _enqueue(self, op: dict) -> None:
        with self._lock:
            self._buffer.append(op)
            should_flush = len(self._buffer) >= self.batch_size
        if should_flush:
            self.flush()

    def flush(self) -> bool:
        """Ship the buffered batch. Transient failures (connection/timeout)
        requeue the batch for the next flush, in order (BulkIndexer retry
        semantics). An HTTP rejection is atomic server-side (nothing was
        applied): the reported ``failed_index`` op is POISON — dropped and
        counted — and the rest of the batch retries, so one malformed op
        neither persists a prefix nor discards its batchmates. Returns
        success."""
        with self._send_lock:
            with self._lock:
                if not self._buffer:
                    return True
                batch, self._buffer = self._buffer, []
            while batch:
                req = urllib.request.Request(
                    f"http://{self.target}/bulk",
                    data=json.dumps(batch).encode(),
                    headers={"Content-Type": "application/json"},
                )
                try:
                    with urllib.request.urlopen(
                        req, timeout=self.timeout
                    ) as resp:
                        json.loads(resp.read())
                    return True
                except urllib.error.HTTPError as exc:
                    try:
                        bad = json.loads(exc.read()).get("failed_index", -1)
                    except Exception:  # noqa: BLE001 — wire surface
                        bad = -1
                    if 0 <= bad < len(batch):
                        self.dropped += 1
                        batch = batch[:bad] + batch[bad + 1 :]
                        continue  # retry the rest without the poison op
                    self.dropped += len(batch)  # unidentifiable rejection
                    return False
                except (urllib.error.URLError, OSError):
                    with self._lock:
                        # retry later, in order
                        self._buffer = batch + self._buffer
                    return False
            return True

    # -- queries ------------------------------------------------------------

    def search(
        self,
        query: str = "",
        *,
        clusters: Optional[Iterable[str]] = None,
        limit: int = 100,
    ) -> list[dict]:
        self.flush()
        params = [("q", query), ("limit", str(limit))]
        for c in clusters or ():
            params.append(("cluster", c))
        qs = "&".join(
            f"{k}={urllib.parse.quote(str(v))}" for k, v in params
        )
        with urllib.request.urlopen(
            f"http://{self.target}/search?{qs}", timeout=self.timeout
        ) as resp:
            hits = json.loads(resp.read())["hits"]
        for d in hits:
            d["object"] = _doc_to_obj(d["object"])
        return hits

    def count(self) -> int:
        self.flush()
        with urllib.request.urlopen(
            f"http://{self.target}/count", timeout=self.timeout
        ) as resp:
            return json.loads(resp.read())["count"]


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--address", default="127.0.0.1:0")
    args = p.parse_args(argv)
    from ..utils.net import parse_hostport

    server = IndexerServer(parse_hostport(args.address, default_host=""))
    bound = server.start()
    print(f"indexer listening on port {bound}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
