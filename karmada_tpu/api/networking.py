"""Networking API: MultiClusterService, ServiceExport/Import, MCI.

Ref: pkg/apis/networking/v1alpha1 (MultiClusterService types) and the
mcs-api ServiceExport/ServiceImport kinds the reference vendors.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .core import Condition, ObjectMeta

# MultiClusterService exposure types
EXPOSURE_CROSS_CLUSTER = "CrossCluster"
EXPOSURE_LOAD_BALANCER = "LoadBalancer"


@dataclass
class ExposureRange:
    cluster_names: list[str] = field(default_factory=list)


@dataclass
class MultiClusterServiceSpec:
    types: list[str] = field(default_factory=lambda: [EXPOSURE_CROSS_CLUSTER])
    ports: list[dict] = field(default_factory=list)
    # provider: clusters where the backing service runs; consumer: clusters
    # that should see the derived service
    provider_clusters: list[ExposureRange] = field(default_factory=list)
    consumer_clusters: list[ExposureRange] = field(default_factory=list)


@dataclass
class MultiClusterServiceStatus:
    conditions: list[Condition] = field(default_factory=list)


@dataclass
class MultiClusterService:
    KIND = "MultiClusterService"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: MultiClusterServiceSpec = field(default_factory=MultiClusterServiceSpec)
    status: MultiClusterServiceStatus = field(default_factory=MultiClusterServiceStatus)

    def provider_names(self) -> list[str]:
        return [n for r in self.spec.provider_clusters for n in r.cluster_names]

    def consumer_names(self) -> list[str]:
        return [n for r in self.spec.consumer_clusters for n in r.cluster_names]


@dataclass
class ServiceExport:
    """mcs-api ServiceExport: marks a service for cross-cluster export."""

    KIND = "ServiceExport"

    meta: ObjectMeta = field(default_factory=ObjectMeta)


@dataclass
class ServiceImportSpec:
    type: str = "ClusterSetIP"
    ports: list[dict] = field(default_factory=list)


@dataclass
class ServiceImport:
    KIND = "ServiceImport"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceImportSpec = field(default_factory=ServiceImportSpec)


@dataclass
class MultiClusterIngressSpec:
    """Ref: networking/v1alpha1 MultiClusterIngress: ingress spec over
    services backed by multiple clusters."""

    rules: list[dict] = field(default_factory=list)


@dataclass
class MultiClusterIngress:
    KIND = "MultiClusterIngress"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: MultiClusterIngressSpec = field(default_factory=MultiClusterIngressSpec)
    status: dict = field(default_factory=dict)
