"""Cluster API: member-cluster inventory and capacity status.

Ref: pkg/apis/cluster/v1alpha1/types.go —
SyncMode (:77-80), Provider/Region/Zones (:119-139), Taints (:141-145),
ResourceModels (:147-203), APIEnablements (:293-295),
ResourceSummary Allocatable/Allocated/Allocating + AllocatableModelings
(:305-369).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .core import Condition, ObjectMeta

# SyncMode
PUSH = "Push"
PULL = "Pull"

# Taint effects (k8s core semantics; scheduler filters NoSchedule/NoExecute:
# pkg/scheduler/framework/plugins/tainttoleration/taint_toleration.go:46-74)
NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"

# Well-known cluster condition / taint keys
# (ref: pkg/apis/cluster/v1alpha1/well_known_constants.go)
CLUSTER_CONDITION_READY = "Ready"
TAINT_CLUSTER_NOT_READY = "cluster.karmada.io/not-ready"
TAINT_CLUSTER_UNREACHABLE = "cluster.karmada.io/unreachable"


@dataclass(frozen=True)
class Taint:
    key: str
    effect: str
    value: str = ""


@dataclass(frozen=True)
class Toleration:
    """k8s-style toleration. operator: 'Equal' matches key+value, 'Exists'
    matches key regardless of value; empty key + Exists tolerates everything.
    ``toleration_seconds`` only applies to NoExecute (eviction delay)."""

    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if not self.key:  # empty key with Exists tolerates all taints
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


@dataclass
class ResourceModelRange:
    """[min, max) range for one resource in a model grade.
    Ref: cluster types.go:147-203."""

    name: str
    min: int
    max: int


@dataclass
class ResourceModel:
    grade: int
    ranges: list[ResourceModelRange] = field(default_factory=list)


@dataclass
class AllocatableModeling:
    grade: int
    count: int


#: (grade, cpu-min cores, cpu-max cores, mem-min GB, mem-max GB); the last
#: grade's max is open-ended (apis/cluster/mutation/mutation.go:81-215)
_DEFAULT_GRADES = (
    (0, 0, 1, 0, 4),
    (1, 1, 2, 4, 16),
    (2, 2, 4, 16, 32),
    (3, 4, 8, 32, 64),
    (4, 8, 16, 64, 128),
    (5, 16, 32, 128, 256),
    (6, 32, 64, 256, 512),
    (7, 64, 128, 512, 1024),
    (8, 128, None, 1024, None),
)

MAX_INT64 = 2**63 - 1
_GB = 1 << 30


def default_resource_models() -> list[ResourceModel]:
    """The reference's nine default cpu/memory grades, in canonical units
    (cpu milli, memory bytes) — SetDefaultClusterResourceModels."""
    out = []
    for grade, cmin, cmax, mmin, mmax in _DEFAULT_GRADES:
        out.append(ResourceModel(grade=grade, ranges=[
            ResourceModelRange(
                name="cpu", min=cmin * 1000,
                max=MAX_INT64 if cmax is None else cmax * 1000,
            ),
            ResourceModelRange(
                name="memory", min=mmin * _GB,
                max=MAX_INT64 if mmax is None else mmax * _GB,
            ),
        ]))
    return out


def standardize_resource_models(models: list[ResourceModel]) -> None:
    """StandardizeClusterResourceModels: sort by grade; the first grade's
    mins act as zero and the last grade's maxes as MaxInt64, so the model
    space is gapless at both ends."""
    if not models:
        return
    models.sort(key=lambda m: m.grade)
    for rng in models[0].ranges:
        rng.min = 0
    for rng in models[-1].ranges:
        rng.max = MAX_INT64


@dataclass
class ResourceSummary:
    """Cluster-level resource accounting (canonical int units, see
    utils.quantity). Ref: cluster types.go:305-369."""

    allocatable: dict[str, int] = field(default_factory=dict)
    allocated: dict[str, int] = field(default_factory=dict)
    allocating: dict[str, int] = field(default_factory=dict)
    allocatable_modelings: list[AllocatableModeling] = field(default_factory=list)


@dataclass
class ClusterSpec:
    sync_mode: str = PUSH
    provider: str = ""
    region: str = ""
    zones: list[str] = field(default_factory=list)
    taints: list[Taint] = field(default_factory=list)
    resource_models: list[ResourceModel] = field(default_factory=list)
    # endpoint/secret refs omitted: member access is via the cluster client
    # registry (utils.member_clients), the analogue of Secret-stored
    # kubeconfigs (pkg/util/membercluster_client.go).
    api_endpoint: str = ""

    @property
    def zone(self) -> str:
        return self.zones[0] if self.zones else ""


@dataclass
class ClusterStatus:
    kubernetes_version: str = ""
    api_enablements: list[str] = field(default_factory=list)  # list of GVK strings
    conditions: list[Condition] = field(default_factory=list)
    node_summary_total: int = 0
    node_summary_ready: int = 0
    resource_summary: ResourceSummary = field(default_factory=ResourceSummary)


@dataclass
class Cluster:
    KIND = "Cluster"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ClusterSpec = field(default_factory=ClusterSpec)
    status: ClusterStatus = field(default_factory=ClusterStatus)

    @property
    def name(self) -> str:
        return self.meta.name


@dataclass
class Lease:
    """coordination.k8s.io Lease analogue, serving both reference uses:

    - agent heartbeat for Pull clusters (cluster_status_controller.go:
      210-213 + monitorClusterHealth lease observation): the agent renews
      ``renew_time``; the control plane judges freshness — it cannot probe
      a Pull cluster directly.
    - leader-election resource lock (client-go leaderelection over
      LeasesResourceLock — every reference binary's --leader-elect): the
      holder fields + CAS applies (Store.apply expected_rv) implement
      tryAcquireOrRenew; see utils/leaderelect.py."""

    KIND = "Lease"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    renew_time: float = 0.0
    holder_identity: str = ""
    lease_duration_seconds: float = 0.0
    acquire_time: float = 0.0
    lease_transitions: int = 0
