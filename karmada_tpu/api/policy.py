"""Policy API: propagation/override policies and placement.

Ref: pkg/apis/policy/v1alpha1/propagation_types.go —
PropagationPolicy (:52), Placement (:393-447), ClusterAffinity/ClusterAffinities
(:400-433), SpreadConstraint (:453-487), ReplicaSchedulingStrategy (:546-614);
override_types.go (OverridePolicy); federatedresourcequota_types.go.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from .cluster import Cluster, Toleration
from .core import ObjectMeta

# ReplicaSchedulingType
DUPLICATED = "Duplicated"
DIVIDED = "Divided"
# ReplicaDivisionPreference
AGGREGATED = "Aggregated"
WEIGHTED = "Weighted"
# DynamicWeightFactor
DYNAMIC_WEIGHT_AVAILABLE_REPLICAS = "AvailableReplicas"
# SpreadByField
SPREAD_BY_CLUSTER = "cluster"
SPREAD_BY_ZONE = "zone"
SPREAD_BY_REGION = "region"
SPREAD_BY_PROVIDER = "provider"

# ConflictResolution
CONFLICT_OVERWRITE = "Overwrite"
CONFLICT_ABORT = "Abort"


@dataclass(frozen=True)
class LabelSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist
    values: tuple[str, ...] = ()


@dataclass
class LabelSelector:
    """k8s LabelSelector: AND of match_labels and match_expressions."""

    match_labels: dict[str, str] = field(default_factory=dict)
    match_expressions: list[LabelSelectorRequirement] = field(default_factory=list)

    def matches(self, labels: dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for req in self.match_expressions:
            has = req.key in labels
            if req.operator == "Exists":
                if not has:
                    return False
            elif req.operator == "DoesNotExist":
                if has:
                    return False
            elif req.operator == "In":
                if not has or labels[req.key] not in req.values:
                    return False
            elif req.operator == "NotIn":
                if has and labels[req.key] in req.values:
                    return False
            else:
                raise ValueError(f"unknown operator {req.operator}")
        return True


@dataclass
class FieldSelector:
    """Cluster field selector over provider/region/zone.
    Ref: propagation_types.go FieldSelector + pkg/util/cluster.go matching."""

    match_expressions: list[LabelSelectorRequirement] = field(default_factory=list)

    _FIELDS = ("provider", "region", "zone")

    def matches(self, cluster: Cluster) -> bool:
        fields = {
            "provider": cluster.spec.provider,
            "region": cluster.spec.region,
            "zone": cluster.spec.zone,
        }
        for req in self.match_expressions:
            val = fields.get(req.key, "")
            if req.operator == "In":
                if val not in req.values:
                    return False
            elif req.operator == "NotIn":
                if val in req.values:
                    return False
            else:
                raise ValueError(f"unsupported field selector operator {req.operator}")
        return True


@dataclass
class ClusterAffinity:
    """Ref: propagation_types.go:400-415 + util.ClusterMatches
    (pkg/util/cluster.go:79-105): exclude wins, then cluster_names /
    label_selector / field_selector must all pass (empty means match-all)."""

    cluster_names: list[str] = field(default_factory=list)
    exclude: list[str] = field(default_factory=list)
    label_selector: Optional[LabelSelector] = None
    field_selector: Optional[FieldSelector] = None

    def matches(self, cluster: Cluster) -> bool:
        if cluster.name in self.exclude:
            return False
        if self.cluster_names and cluster.name not in self.cluster_names:
            return False
        if self.label_selector is not None and not self.label_selector.matches(
            cluster.meta.labels
        ):
            return False
        if self.field_selector is not None and not self.field_selector.matches(cluster):
            return False
        return True


@dataclass
class ClusterAffinityTerm(ClusterAffinity):
    """Named affinity group for ordered failover.
    Ref: propagation_types.go:417-424."""

    affinity_name: str = ""


@dataclass
class SpreadConstraint:
    """Ref: propagation_types.go:461-487. min_groups defaults to 1;
    max_groups 0 means unbounded."""

    spread_by_field: str = ""  # cluster | zone | region | provider
    spread_by_label: str = ""
    min_groups: int = 1
    max_groups: int = 0


@dataclass
class StaticClusterWeight:
    target_cluster: ClusterAffinity = field(default_factory=ClusterAffinity)
    weight: int = 1


@dataclass
class ClusterPreferences:
    static_weight_list: list[StaticClusterWeight] = field(default_factory=list)
    dynamic_weight: str = ""  # "" or AvailableReplicas


@dataclass
class ReplicaSchedulingStrategy:
    """Ref: propagation_types.go:546-614."""

    replica_scheduling_type: str = DIVIDED
    replica_division_preference: str = ""  # Aggregated | Weighted
    weight_preference: Optional[ClusterPreferences] = None


@dataclass
class Placement:
    """Ref: propagation_types.go:393-447."""

    cluster_affinity: Optional[ClusterAffinity] = None
    cluster_affinities: list[ClusterAffinityTerm] = field(default_factory=list)
    cluster_tolerations: list[Toleration] = field(default_factory=list)
    spread_constraints: list[SpreadConstraint] = field(default_factory=list)
    replica_scheduling: Optional[ReplicaSchedulingStrategy] = None

    def replica_scheduling_type(self) -> str:
        """Defaulting mirrors Placement.ReplicaSchedulingType():
        nil strategy means Duplicated."""
        if self.replica_scheduling is None:
            return DUPLICATED
        return self.replica_scheduling.replica_scheduling_type or DUPLICATED


@dataclass
class ResourceSelector:
    """Selects which templates a policy applies to.
    Ref: propagation_types.go ResourceSelector."""

    api_version: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""
    label_selector: Optional[LabelSelector] = None


@dataclass
class PropagationSpec:
    resource_selectors: list[ResourceSelector] = field(default_factory=list)
    placement: Placement = field(default_factory=Placement)
    priority: int = 0
    preemption: str = "Never"  # Never | Always
    propagate_deps: bool = False
    conflict_resolution: str = CONFLICT_ABORT
    suspend_dispatching: bool = False
    # suspend dispatching only to these member clusters
    # (propagation_types.go:237-258 Suspension.DispatchingOnClusters)
    suspend_dispatching_on_clusters: Optional[list[str]] = None
    preserve_resources_on_deletion: bool = False
    failover: Optional["FailoverBehavior"] = None
    # scheduler to use; default scheduler name mirrors the reference default
    scheduler_name: str = "default-scheduler"
    # "" (immediate) | "Lazy": policy changes defer until the resource
    # template itself changes (propagation_types.go:159-178,653-660)
    activation_preference: str = ""


@dataclass
class ApplicationFailoverBehavior:
    """Ref: propagation_types.go ApplicationFailoverBehavior."""

    decision_conditions_toleration_seconds: int = 300
    purge_mode: str = "Graciously"  # Graciously | Immediately | Never
    grace_period_seconds: Optional[int] = None
    state_preservation: Optional[dict[str, str]] = None  # name -> JSONPath


@dataclass
class FailoverBehavior:
    application: Optional[ApplicationFailoverBehavior] = None


@dataclass
class PropagationPolicy:
    KIND = "PropagationPolicy"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PropagationSpec = field(default_factory=PropagationSpec)

    @property
    def cluster_scoped(self) -> bool:
        return False


@dataclass
class ClusterPropagationPolicy(PropagationPolicy):
    KIND = "ClusterPropagationPolicy"

    @property
    def cluster_scoped(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# Override policy (ref: pkg/apis/policy/v1alpha1/override_types.go)
# ---------------------------------------------------------------------------


@dataclass
class PlaintextOverrider:
    """JSONPatch-style overrider: op add/remove/replace at a path."""

    path: str = ""
    operator: str = "replace"  # add | remove | replace
    value: Any = None


@dataclass
class FieldPatchOperation:
    """One operation inside an embedded document
    (override_types.go:287-310 JSONPatchOperation/YAMLPatchOperation)."""

    sub_path: str = ""  # RFC 6901 path within the embedded document
    operator: str = "replace"  # add | remove | replace
    value: Any = None


@dataclass
class FieldOverrider:
    """Patch a STRING field whose value is an embedded JSON or YAML
    document (e.g. a ConfigMap data key): parse, apply the operations at
    their sub-paths, re-serialize (override_types.go:266-285). A single
    instance carries either json or yaml operations, not both."""

    field_path: str = ""  # RFC 6901 path to the string field
    json: list[FieldPatchOperation] = field(default_factory=list)
    yaml: list[FieldPatchOperation] = field(default_factory=list)


@dataclass
class ImageOverrider:
    component: str = "Registry"  # Registry | Repository | Tag
    operator: str = "replace"
    value: str = ""
    predicate_path: str = ""


@dataclass
class CommandArgsOverrider:
    container_name: str = ""
    operator: str = "add"  # add | remove
    value: list[str] = field(default_factory=list)


@dataclass
class LabelAnnotationOverrider:
    operator: str = "replace"  # add | remove | replace
    value: dict[str, str] = field(default_factory=dict)


@dataclass
class Overriders:
    plaintext: list[PlaintextOverrider] = field(default_factory=list)
    image_overrider: list[ImageOverrider] = field(default_factory=list)
    command_overrider: list[CommandArgsOverrider] = field(default_factory=list)
    args_overrider: list[CommandArgsOverrider] = field(default_factory=list)
    labels_overrider: list[LabelAnnotationOverrider] = field(default_factory=list)
    annotations_overrider: list[LabelAnnotationOverrider] = field(default_factory=list)
    field_overrider: list[FieldOverrider] = field(default_factory=list)


@dataclass
class RuleWithCluster:
    target_cluster: Optional[ClusterAffinity] = None
    overriders: Overriders = field(default_factory=Overriders)


@dataclass
class OverrideSpec:
    resource_selectors: list[ResourceSelector] = field(default_factory=list)
    override_rules: list[RuleWithCluster] = field(default_factory=list)


@dataclass
class OverridePolicy:
    KIND = "OverridePolicy"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: OverrideSpec = field(default_factory=OverrideSpec)

    @property
    def cluster_scoped(self) -> bool:
        return False


@dataclass
class ClusterOverridePolicy(OverridePolicy):
    KIND = "ClusterOverridePolicy"

    @property
    def cluster_scoped(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# FederatedResourceQuota (ref: federatedresourcequota_types.go)
# ---------------------------------------------------------------------------


@dataclass
class StaticClusterAssignment:
    cluster_name: str = ""
    hard: dict[str, int] = field(default_factory=dict)


@dataclass
class FederatedResourceQuotaSpec:
    overall: dict[str, int] = field(default_factory=dict)
    static_assignments: list[StaticClusterAssignment] = field(default_factory=list)


@dataclass
class FederatedResourceQuotaStatus:
    overall: dict[str, int] = field(default_factory=dict)
    overall_used: dict[str, int] = field(default_factory=dict)
    aggregated_status: list[Any] = field(default_factory=list)


@dataclass
class FederatedResourceQuota:
    KIND = "FederatedResourceQuota"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: FederatedResourceQuotaSpec = field(default_factory=FederatedResourceQuotaSpec)
    status: FederatedResourceQuotaStatus = field(
        default_factory=FederatedResourceQuotaStatus
    )
