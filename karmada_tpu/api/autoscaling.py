"""Autoscaling API: FederatedHPA and CronFederatedHPA.

Ref: pkg/apis/autoscaling/v1alpha1 — FederatedHPA (scale target + min/max +
metrics, HPA-shaped) and CronFederatedHPA (cron rules scaling a FederatedHPA
or a workload directly).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .core import ObjectMeta


@dataclass
class ScaleTargetRef:
    api_version: str = "apps/v1"
    kind: str = "Deployment"
    name: str = ""


@dataclass
class MetricSpec:
    """HPA metric source (autoscaling/v2 MetricSpec).

    - type "Resource": resource utilization vs request, merged by the
      metrics adapter's resource flavor (target_average_utilization in
      percent, or target_average_value in canonical units per pod);
    - type "Pods": a custom per-pod metric (custom.metrics.k8s.io) named by
      metric_name, optionally filtered by metric_selector, compared against
      target_average_value per pod;
    - type "Object": a metric describing a single cluster object
      (described_object), compared against target_value (Value) or
      target_average_value (AverageValue per pod) —
      federatedhpa_controller.go computeStatusForObjectMetric;
    - type "External": an external series (external.metrics.k8s.io) named
      by metric_name + metric_selector, compared against target_value
      (total) or target_average_value (per pod)."""

    type: str = "Resource"  # Resource | Pods | Object | External
    resource_name: str = "cpu"
    target_average_utilization: Optional[int] = None
    target_average_value: Optional[float] = None
    metric_name: str = ""
    metric_selector: Optional[dict] = None  # label selector (match_labels)
    target_value: Optional[float] = None
    described_object: Optional[ScaleTargetRef] = None  # Object flavor


@dataclass
class FederatedHPASpec:
    scale_target_ref: ScaleTargetRef = field(default_factory=ScaleTargetRef)
    min_replicas: int = 1
    max_replicas: int = 10
    metrics: list[MetricSpec] = field(default_factory=list)
    # scale-down stabilization (behavior.scaleDown.stabilizationWindowSeconds)
    stabilization_window_seconds: int = 300


@dataclass
class FederatedHPAStatus:
    current_replicas: int = 0
    desired_replicas: int = 0
    last_scale_time: Optional[float] = None


@dataclass
class FederatedHPA:
    KIND = "FederatedHPA"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: FederatedHPASpec = field(default_factory=FederatedHPASpec)
    status: FederatedHPAStatus = field(default_factory=FederatedHPAStatus)


@dataclass
class CronFederatedHPARule:
    name: str = ""
    schedule: str = "* * * * *"  # 5-field cron
    target_replicas: Optional[int] = None
    target_min_replicas: Optional[int] = None
    target_max_replicas: Optional[int] = None
    suspend: bool = False


@dataclass
class CronFederatedHPASpec:
    scale_target_ref: ScaleTargetRef = field(default_factory=ScaleTargetRef)
    rules: list[CronFederatedHPARule] = field(default_factory=list)


@dataclass
class ExecutionHistoryItem:
    rule_name: str = ""
    execution_time: float = 0.0
    applied_replicas: Optional[int] = None
    message: str = ""


@dataclass
class CronFederatedHPAStatus:
    execution_histories: list[ExecutionHistoryItem] = field(default_factory=list)


@dataclass
class CronFederatedHPA:
    KIND = "CronFederatedHPA"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CronFederatedHPASpec = field(default_factory=CronFederatedHPASpec)
    status: CronFederatedHPAStatus = field(default_factory=CronFederatedHPAStatus)
