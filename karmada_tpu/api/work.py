"""Work API: ResourceBinding (the scheduling unit) and Work (the per-cluster
manifest envelope).

Ref: pkg/apis/work/v1alpha2/binding_types.go — ResourceBinding (:58),
ReplicaRequirements (:193), TargetCluster (:229), GracefulEvictionTask (:238),
BindingSnapshot/RequiredBy (:309), status (:326-353);
pkg/apis/work/v1alpha1/work_types.go — Work.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from .core import Condition, ObjectMeta, ObjectReference, Resource
from .policy import Placement

# Binding condition types (binding_types.go:355-371)
SCHEDULED = "Scheduled"
FULLY_APPLIED = "FullyApplied"

# Work condition types (work_types.go)
WORK_APPLIED = "Applied"
WORK_AVAILABLE = "Available"
WORK_DEGRADED = "Degraded"

# Eviction producers/reasons (binding_types.go well-knowns). The reason
# codes are registered in the REASONS taxonomy (utils/reasons.py — the
# API layer stays import-light, so the literals live here and tier-1
# asserts registry membership; graftlint GL010 guards emission sites)
EVICTION_PRODUCER_TAINT_MANAGER = "TaintManager"
EVICTION_REASON_TAINT_UNTOLERATED = "TaintUntolerated"
EVICTION_REASON_APPLICATION_FAILURE = "ApplicationFailure"
# scarcity plane (ISSUE 14): victim evictions produced by the batched
# preemption kernel; doubles as exclusion-mask stage bit 7 and the
# karmada_tpu_preemptions_total reason label
EVICTION_PRODUCER_PREEMPTION = "PreemptionKernel"
EVICTION_REASON_PREEMPTED = "PreemptedByHigherPriority"
# victim condition type (the reason codes live in utils/reasons.py)
PREEMPTED = "Preempted"
# PurgeMode
PURGE_IMMEDIATELY = "Immediately"
PURGE_GRACIOUSLY = "Graciously"
PURGE_NEVER = "Never"


@dataclass
class NodeClaim:
    """Node-level scheduling claim carried with replica requirements.
    Ref: binding_types.go NodeClaim (nodeSelector/tolerations/hard node
    affinity)."""

    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: list[Any] = field(default_factory=list)
    hard_node_affinity: Optional[dict] = None


@dataclass
class ReplicaRequirements:
    """Per-replica requirements (canonical int units).
    Ref: binding_types.go:193-213."""

    resource_request: dict[str, int] = field(default_factory=dict)
    node_claim: Optional[NodeClaim] = None
    namespace: str = ""
    priority_class_name: str = ""


@dataclass
class TargetCluster:
    """One schedule-result entry. Ref: binding_types.go:229-236."""

    name: str
    replicas: int = 0


@dataclass
class GracefulEvictionTask:
    """Ref: binding_types.go:238-307."""

    from_cluster: str
    replicas: int = 0
    reason: str = ""
    message: str = ""
    producer: str = ""
    purge_mode: str = PURGE_GRACIOUSLY
    grace_period_seconds: Optional[int] = None
    suppress_deletion: Optional[bool] = None
    creation_timestamp: float = 0.0
    # state carried over for stateful failover (PreservedLabelState)
    preserved_label_state: dict[str, str] = field(default_factory=dict)
    clusters_before_failover: list[str] = field(default_factory=list)


@dataclass
class BindingSnapshot:
    """Dependent-binding shadow of another binding's schedule result.
    Ref: binding_types.go:309-324 (RequiredBy)."""

    namespace: str = ""
    name: str = ""
    clusters: list[TargetCluster] = field(default_factory=list)


@dataclass
class AggregatedStatusItem:
    """Per-cluster aggregated status. Ref: binding_types.go:326-353."""

    cluster_name: str
    status: Optional[dict] = None
    applied: bool = False
    health: str = "Unknown"  # Healthy | Unhealthy | Unknown
    applied_message: str = ""


@dataclass
class ResourceBindingSpec:
    """Ref: binding_types.go:58-148."""

    resource: ObjectReference = field(default_factory=ObjectReference)
    replicas: int = 0
    replica_requirements: Optional[ReplicaRequirements] = None
    placement: Optional[Placement] = None
    # scheduling priority class (ISSUE 14): plumbed from the matched
    # PropagationPolicy's spec.priority by the detector so the scheduler
    # can order waves and the preemption kernel can rank victims. 0 is
    # the back-compat default — pre-priority bindings (and checkpoints
    # restored from them) schedule exactly as before.
    priority: int = 0
    clusters: list[TargetCluster] = field(default_factory=list)
    graceful_eviction_tasks: list[GracefulEvictionTask] = field(default_factory=list)
    required_by: list[BindingSnapshot] = field(default_factory=list)
    reschedule_triggered_at: Optional[float] = None
    conflict_resolution: str = "Abort"
    failover: Optional[Any] = None  # FailoverBehavior snapshot from policy
    propagate_deps: bool = False
    suspend_dispatching: bool = False
    # per-cluster dispatch suspension (Suspension.DispatchingOnClusters,
    # binding_types.go:150-153)
    suspend_dispatching_on_clusters: Optional[list[str]] = None
    preserve_resources_on_deletion: bool = False
    scheduler_name: str = "default-scheduler"


@dataclass
class ResourceBindingStatus:
    """Ref: binding_types.go:326-353."""

    scheduler_observed_generation: int = 0
    scheduler_observed_affinity_name: str = ""
    last_scheduled_time: Optional[float] = None
    conditions: list[Condition] = field(default_factory=list)
    aggregated_status: list[AggregatedStatusItem] = field(default_factory=list)


@dataclass
class ResourceBinding:
    KIND = "ResourceBinding"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceBindingSpec = field(default_factory=ResourceBindingSpec)
    status: ResourceBindingStatus = field(default_factory=ResourceBindingStatus)

    @property
    def cluster_scoped(self) -> bool:
        return False


@dataclass
class ClusterResourceBinding(ResourceBinding):
    KIND = "ClusterResourceBinding"

    @property
    def cluster_scoped(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# Work (ref: pkg/apis/work/v1alpha1/work_types.go)
# ---------------------------------------------------------------------------


@dataclass
class ManifestStatus:
    identifier: ObjectReference = field(default_factory=ObjectReference)
    status: Optional[dict] = None
    health: str = "Unknown"


@dataclass
class WorkloadTemplateRef:
    """Template-delta Work rendering (ISSUE 11 tentpole c): instead of a
    full manifest clone per target cluster, a Work may reference ONE
    content-addressed ``WorkloadTemplate`` (shared by every Work of the
    workload family) plus a small per-cluster ``patch`` of spec fields —
    the replica revision the binding controller would have applied.
    Consumers rehydrate via ``controllers.propagation.work_manifests``;
    identity fields ride here so indexes and status routing never need
    the template body."""

    digest: str = ""
    api_version: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""
    patch: dict[str, Any] = field(default_factory=dict)


@dataclass
class WorkloadTemplate:
    """One rendered manifest per workload family, stored content-addressed
    (``meta.name`` == digest) and shipped over the bus ONCE instead of
    inside each of N Works. ``manifest`` is the pruned jsonable Resource
    document (the shape ``utils.codec.to_jsonable`` emits)."""

    KIND = "WorkloadTemplate"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    manifest: dict[str, Any] = field(default_factory=dict)


@dataclass
class WorkSpec:
    workload: list[Resource] = field(default_factory=list)
    # template-delta rendering: when set (and workload is empty) the
    # manifest is template + patch; full-object ``workload`` remains the
    # fallback for non-templatable workloads (custom revise hooks,
    # override-transformed targets) and the kill-switch path
    workload_template: Optional[WorkloadTemplateRef] = None
    suspend_dispatching: bool = False
    preserve_resources_on_deletion: bool = False
    conflict_resolution: str = "Overwrite"  # Overwrite | Abort


@dataclass
class WorkStatus:
    conditions: list[Condition] = field(default_factory=list)
    manifest_statuses: list[ManifestStatus] = field(default_factory=list)


@dataclass
class Work:
    KIND = "Work"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: WorkSpec = field(default_factory=WorkSpec)
    status: WorkStatus = field(default_factory=WorkStatus)
