"""Shared API machinery: object metadata, conditions, resource references.

The reference builds on k8s apimachinery; here the contract is plain typed
records. Ref: pkg/apis/work/v1alpha2/binding_types.go (ObjectReference),
metav1.ObjectMeta / metav1.Condition semantics.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Optional

_uid_counter = itertools.count(1)


def new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    uid: str = ""
    generation: int = 1
    resource_version: int = 0
    finalizers: list[str] = field(default_factory=list)
    deletion_timestamp: Optional[float] = None
    creation_timestamp: float = 0.0

    @property
    def namespaced_name(self) -> str:
        return f"{self.namespace}/{self.name}" if self.namespace else self.name


@dataclass
class Condition:
    """Mirrors metav1.Condition."""

    type: str
    status: bool
    reason: str = ""
    message: str = ""
    last_transition_time: float = field(default_factory=time.time)


def set_condition(conditions: list[Condition], new: Condition) -> bool:
    """Upsert by type; returns True if status changed (transition)."""
    for i, c in enumerate(conditions):
        if c.type == new.type:
            if c.status == new.status:
                # refresh reason/message but keep transition time
                new.last_transition_time = c.last_transition_time
                conditions[i] = new
                return False
            conditions[i] = new
            return True
    conditions.append(new)
    return True


def get_condition(conditions: list[Condition], ctype: str) -> Optional[Condition]:
    for c in conditions:
        if c.type == ctype:
            return c
    return None


def is_condition_true(conditions: list[Condition], ctype: str) -> bool:
    c = get_condition(conditions, ctype)
    return c is not None and c.status


@dataclass
class ObjectReference:
    """Reference to a resource template.

    Ref: pkg/apis/work/v1alpha2/binding_types.go:150-176 (ObjectReference).
    """

    api_version: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""
    resource_version: str = ""

    @property
    def gvk(self) -> str:
        return f"{self.api_version}/{self.kind}"

    @property
    def namespaced_key(self) -> str:
        return f"{self.namespace}/{self.name}" if self.namespace else self.name


@dataclass
class Resource:
    """A plain (unstructured) resource template, kube-style.

    ``spec``/``status`` are free-form dicts; the resource interpreter
    (karmada_tpu.interpreter) gives them semantics per kind.
    """

    api_version: str = "apps/v1"
    kind: str = "Deployment"
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: dict[str, Any] = field(default_factory=dict)
    status: dict[str, Any] = field(default_factory=dict)

    def object_reference(self) -> ObjectReference:
        return ObjectReference(
            api_version=self.api_version,
            kind=self.kind,
            namespace=self.meta.namespace,
            name=self.meta.name,
            uid=self.meta.uid,
        )
