"""Multi-version API surface + conversion seam.

Ref: pkg/apis/work/v1alpha1 + v1alpha2 — the reference serves BOTH
binding versions simultaneously: v1alpha1 nests the replica count and
per-replica resource requirements INSIDE ``spec.resource`` while the hub
(v1alpha2) hoists them to ``spec.replicas`` /
``spec.replicaRequirements.resourceRequest``
(binding_types_conversion.go:77-129), and a CRD conversion webhook
(/convert, ConversionReview contract) translates on demand. That version
-skew story is what makes operator upgrades real: an old client or a
stored legacy object keeps working against a new control plane.

Design here (hub-and-spoke over WIRE DICTS): the current dataclasses are
the hub; each legacy version registers ``(to_hub, from_hub)`` functions
over the codec's jsonable form. Three consumers share this registry —
the bus (legacy-shaped applies upgrade before decode), the webhook
server's ``/convert`` endpoint (ConversionReview in/out), and the CLI's
``apply`` (a v1alpha1 manifest lands as a hub object). Down-conversion
is lossy exactly where the reference's is (hub-only fields drop), and
up-conversion fills hub defaults.
"""

from __future__ import annotations

from typing import Callable, Optional

GROUP = "work.karmada.io"
HUB_VERSION = f"{GROUP}/v1alpha2"
LEGACY_VERSION = f"{GROUP}/v1alpha1"


class ConversionError(Exception):
    """Unknown (kind, version) pair or malformed payload."""


# (kind, version) -> (to_hub, from_hub); the hub itself is implicit
_REGISTRY: dict[tuple[str, str], tuple[Callable, Callable]] = {}
# kind -> hub apiVersion string
_HUBS: dict[str, str] = {}


def register(
    kind: str, version: str, to_hub: Callable[[dict], dict],
    from_hub: Callable[[dict], dict], hub_version: str = HUB_VERSION,
) -> None:
    _REGISTRY[(kind, version)] = (to_hub, from_hub)
    _HUBS[kind] = hub_version


def served_versions(kind: str) -> list[str]:
    """Versions this plane serves for ``kind`` (hub first)."""
    out = [_HUBS[kind]] if kind in _HUBS else []
    out += [v for (k, v) in _REGISTRY if k == kind]
    return out


def hub_version_of(kind: str) -> Optional[str]:
    return _HUBS.get(kind)


def convert(doc: dict, kind: str, to_version: str) -> dict:
    """Convert a wire doc of ``kind`` to ``to_version``. The doc's own
    version comes from its apiVersion field (hub assumed when absent).
    Hub-and-spoke: legacy -> hub -> legacy'."""
    from_version = doc.get("apiVersion") or doc.get("api_version") or (
        _HUBS.get(kind, to_version)
    )
    if from_version == to_version:
        return doc
    hub_doc = doc
    if from_version != _HUBS.get(kind):
        pair = _REGISTRY.get((kind, from_version))
        if pair is None:
            raise ConversionError(
                f"{kind} version {from_version!r} is not served"
            )
        hub_doc = pair[0](doc)
        hub_doc["apiVersion"] = _HUBS.get(kind, to_version)
    if to_version == _HUBS.get(kind):
        return hub_doc
    pair = _REGISTRY.get((kind, to_version))
    if pair is None:
        raise ConversionError(f"{kind} version {to_version!r} is not served")
    out = pair[1](hub_doc)
    out["apiVersion"] = to_version
    return out


def maybe_upgrade(kind: str, doc: dict) -> dict:
    """Upgrade a wire doc to the hub version when its apiVersion marks a
    registered legacy version; pass through otherwise. The bus and CLI
    call this before decoding, so legacy clients keep working against a
    hub store."""
    ver = doc.get("apiVersion") or doc.get("api_version")
    if ver and (kind, ver) in _REGISTRY:
        return convert(doc, kind, _HUBS[kind])
    return doc


# --------------------------------------------------------------------------
# work/v1alpha1 bindings (the reference's live multi-version pair)
# --------------------------------------------------------------------------


def _get(d: dict, *names, default=None):
    for n in names:
        if n in d:
            return d[n]
    return default


def _binding_to_hub(doc: dict) -> dict:
    """v1alpha1 -> hub: hoist spec.resource.{replicas,
    replicaResourceRequirements} to spec.{replicas, replica_requirements}
    (ConvertBindingSpecToHub, binding_types_conversion.go:77-95)."""
    out = dict(doc)
    spec = dict(_get(doc, "spec", default={}) or {})
    res = dict(_get(spec, "resource", default={}) or {})
    reps = res.pop("replicas", 0)
    rrr = res.pop(
        "replicaResourceRequirements", None
    ) or res.pop("replica_resource_requirements", None)
    spec["resource"] = res
    spec["replicas"] = reps
    if rrr:
        rr = dict(_get(spec, "replica_requirements", default={}) or {})
        rr["resource_request"] = rrr
        spec["replica_requirements"] = rr
    out["spec"] = spec
    # status: conditions + aggregated items carry over field-for-field
    # (the hub's extra aggregated fields default)
    return out


def _binding_from_hub(doc: dict) -> dict:
    """hub -> v1alpha1: push spec.replicas / replica_requirements
    .resource_request back under spec.resource; hub-only spec fields the
    legacy schema cannot express are DROPPED (lossy, like the
    reference's ConvertBindingSpecFromHub which simply does not map
    them)."""
    out = dict(doc)
    spec = dict(_get(doc, "spec", default={}) or {})
    res = dict(_get(spec, "resource", default={}) or {})
    res["replicas"] = spec.pop("replicas", 0)
    rr = spec.pop("replica_requirements", None)
    if rr and _get(rr, "resource_request"):
        res["replicaResourceRequirements"] = _get(rr, "resource_request")
    # legacy schema: resource + clusters (+ the shared eviction-free core)
    legacy_spec = {"resource": res}
    if "clusters" in spec:
        legacy_spec["clusters"] = spec["clusters"]
    out["spec"] = legacy_spec
    status = dict(_get(doc, "status", default={}) or {})
    if status:
        legacy_status = {}
        if "conditions" in status:
            legacy_status["conditions"] = status["conditions"]
        if "aggregated_status" in status:
            legacy_status["aggregated_status"] = [
                {
                    k: v
                    for k, v in dict(item).items()
                    if k in (
                        "cluster_name", "status", "applied",
                        "applied_message",
                    )
                }
                for item in status["aggregated_status"]
            ]
        out["status"] = legacy_status
    return out


for _kind in ("ResourceBinding", "ClusterResourceBinding"):
    register(_kind, LEGACY_VERSION, _binding_to_hub, _binding_from_hub)


# --------------------------------------------------------------------------
# ConversionReview (the CRD conversion-webhook wire contract)
# --------------------------------------------------------------------------


def handle_conversion_review(review: dict) -> dict:
    """Serve a ConversionReview request dict -> response dict (the
    /convert contract a CRD with strategy: Webhook uses; the webhook
    server mounts this). Objects that fail to convert fail the whole
    review, matching the apiserver's all-or-nothing semantics."""
    req = review.get("request") or {}
    uid = req.get("uid", "")
    desired = req.get("desiredAPIVersion", "")
    converted = []
    try:
        for obj in req.get("objects") or []:
            kind = obj.get("kind", "")
            converted.append(convert(obj, kind, desired))
    # a malformed object must still produce an HTTP-200 ConversionReview
    # with result.status=Failure — the apiserver treats anything else as
    # an unrecognized response, not a reported conversion failure
    except Exception as exc:  # noqa: BLE001 — wire surface
        return {
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "ConversionReview",
            "response": {
                "uid": uid,
                "result": {"status": "Failure", "message": str(exc)},
            },
        }
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "ConversionReview",
        "response": {
            "uid": uid,
            "convertedObjects": converted,
            "result": {"status": "Success"},
        },
    }
