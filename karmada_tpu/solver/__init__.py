"""Out-of-process solver sidecar (gRPC Score/Assign service).

``python -m karmada_tpu.solver --address 127.0.0.1:PORT`` runs the server
process; the scheduler controller connects with ``RemoteSolver``.
"""

from .client import HASolver, RemoteScheduleResult, RemoteSolver  # noqa: F401
from .service import (  # noqa: F401
    SolverGrpcServer,
    SolverService,
    StaleSnapshotError,
)
