"""Solver sidecar: the scheduler's Score/Assign subtree as a gRPC service.

Ref: SURVEY.md section 7 ("a gRPC sidecar wrapper (mirroring service.proto)
for out-of-tree use per the north star") and the estimator transport
pattern (estimator/grpc_transport.py; pkg/estimator/service/
service.proto:26-29). The sidecar owns a TensorScheduler (and therefore the
TPU and the device-resident fleet table); the control plane pushes cluster
state through SyncClusters on cluster events and calls ScoreAndAssign with
binding batches. Snapshot versions fence the two: scheduling against a
version the solver doesn't hold fails FAILED_PRECONDITION and the caller
re-syncs — placements are never computed against stale capacity.

Placements travel as canonical JSON of the Placement CR, interned per
request AND cached by-content server-side so the engine's id()-keyed
compile caches (and the fleet table's slots) keep hitting across calls.
"""

from __future__ import annotations

import json
from collections import OrderedDict
from concurrent import futures
from typing import Optional, Sequence

import grpc

from ..api.cluster import (
    AllocatableModeling,
    Cluster,
    ClusterSpec,
    ClusterStatus,
    ResourceModel,
    ResourceModelRange,
    ResourceSummary,
    Taint,
)
from ..api.core import Condition, ObjectMeta
from ..api.policy import Placement
from ..scheduler import BindingProblem, ClusterSnapshot, TensorScheduler
from ..utils.codec import from_jsonable, to_jsonable
from .proto import solver_pb2 as pb

SERVICE_NAME = "karmada_tpu.solver.Solver"


# -- cluster state <-> wire -------------------------------------------------


def cluster_to_state(cl: Cluster) -> pb.ClusterState:
    msg = pb.ClusterState(
        name=cl.name,
        provider=cl.spec.provider,
        region=cl.spec.region,
        zone=cl.spec.zones[0] if cl.spec.zones else "",
        api_enablements=list(cl.status.api_enablements),
        complete_enablements=any(
            c.type == "CompleteAPIEnablements" and c.status
            for c in cl.status.conditions
        ),
    )
    for k, v in cl.meta.labels.items():
        msg.labels[k] = v
    for t in cl.spec.taints:
        msg.taints.add(key=t.key, value=t.value, effect=t.effect)
    rs = cl.status.resource_summary
    for k, v in rs.allocatable.items():
        msg.allocatable[k] = int(v)
    for k, v in rs.allocated.items():
        msg.allocated[k] = int(v)
    for k, v in rs.allocating.items():
        msg.allocating[k] = int(v)
    for rm in cl.spec.resource_models:
        m = msg.resource_models.add(grade=rm.grade)
        for r in rm.ranges:
            m.ranges.add(name=r.name, min=int(r.min), max=int(r.max))
    for am in rs.allocatable_modelings:
        msg.allocatable_modelings.add(grade=am.grade, count=am.count)
    return msg


def state_to_cluster(msg: pb.ClusterState) -> Cluster:
    conditions = [Condition(type="Ready", status=True)]
    if msg.complete_enablements:
        conditions.append(Condition(type="CompleteAPIEnablements", status=True))
    return Cluster(
        meta=ObjectMeta(name=msg.name, labels=dict(msg.labels)),
        spec=ClusterSpec(
            provider=msg.provider,
            region=msg.region,
            zones=[msg.zone] if msg.zone else [],
            taints=[
                Taint(key=t.key, value=t.value, effect=t.effect)
                for t in msg.taints
            ],
            resource_models=[
                ResourceModel(
                    grade=m.grade,
                    ranges=[
                        ResourceModelRange(name=r.name, min=r.min, max=r.max)
                        for r in m.ranges
                    ],
                )
                for m in msg.resource_models
            ],
        ),
        status=ClusterStatus(
            api_enablements=list(msg.api_enablements),
            conditions=conditions,
            resource_summary=ResourceSummary(
                allocatable=dict(msg.allocatable),
                allocated=dict(msg.allocated),
                allocating=dict(msg.allocating),
                allocatable_modelings=[
                    AllocatableModeling(grade=a.grade, count=a.count)
                    for a in msg.allocatable_modelings
                ],
            ),
        ),
    )


# -- problems/results <-> wire ----------------------------------------------


def placement_json(pl: Optional[Placement]) -> str:
    return (
        json.dumps(to_jsonable(pl), sort_keys=True, separators=(",", ":"))
        if pl is not None
        else ""
    )


def encode_problems(problems: Sequence[BindingProblem]) -> pb.ScoreAndAssignRequest:
    req = pb.ScoreAndAssignRequest()
    interned: dict[int, int] = {}
    json_slot: dict[str, int] = {}
    for p in problems:
        if p.placement is None:
            idx = -1
        else:
            idx = interned.get(id(p.placement))
            if idx is None:
                js = placement_json(p.placement)
                idx = json_slot.get(js)
                if idx is None:
                    idx = len(req.placement_jsons)
                    req.placement_jsons.append(js)
                    json_slot[js] = idx
                interned[id(p.placement)] = idx
        msg = req.problems.add(
            key=p.key,
            placement_idx=idx,
            replicas=p.replicas,
            gvk=p.gvk,
            evict_clusters=list(p.evict_clusters),
            fresh=p.fresh,
        )
        for k, v in p.requests.items():
            msg.requests[k] = int(v)
        for k, v in p.prev.items():
            msg.prev[k] = int(v)
    return req


class SolverService:
    """In-proc core of the sidecar: snapshot custody + engine dispatch."""

    PLACEMENT_JSON_CACHE = 8192

    def __init__(self, engine_factory=None):
        self._engine: Optional[TensorScheduler] = None
        self._version = 0
        self._engine_factory = engine_factory or TensorScheduler
        # canonical-JSON -> Placement object, LRU: stable identity across
        # calls keeps the engine's id()-keyed compile caches warm
        self._placements: OrderedDict[str, Placement] = OrderedDict()

    @property
    def snapshot_version(self) -> int:
        return self._version

    def sync_clusters(self, clusters: Sequence[Cluster], version: int) -> int:
        snap = ClusterSnapshot(sorted(clusters, key=lambda c: c.name))
        if self._engine is None or not self._engine.update_snapshot(snap):
            self._engine = self._engine_factory(snap)
        self._version = version
        return self._version

    def _placement(self, js: str) -> Placement:
        pl = self._placements.get(js)
        if pl is None:
            pl = from_jsonable(Placement, json.loads(js))
            self._placements[js] = pl
            if len(self._placements) > self.PLACEMENT_JSON_CACHE:
                self._placements.popitem(last=False)
        else:
            self._placements.move_to_end(js)
        return pl

    def score_and_assign(self, request: pb.ScoreAndAssignRequest) -> pb.ScoreAndAssignResponse:
        if self._engine is None:
            raise StaleSnapshotError("solver holds no cluster snapshot")
        if request.snapshot_version != self._version:
            raise StaleSnapshotError(
                f"snapshot version mismatch: caller {request.snapshot_version} "
                f"!= solver {self._version}"
            )
        placements = [self._placement(js) for js in request.placement_jsons]
        problems = [
            BindingProblem(
                key=m.key,
                placement=placements[m.placement_idx] if m.placement_idx >= 0 else None,
                replicas=m.replicas,
                requests=dict(m.requests),
                gvk=m.gvk,
                prev=dict(m.prev),
                evict_clusters=tuple(m.evict_clusters),
                fresh=m.fresh,
            )
            for m in request.problems
        ]
        results = self._engine.schedule(problems)
        resp = pb.ScoreAndAssignResponse(snapshot_version=self._version)
        for r in results:
            msg = resp.results.add(
                key=r.key, affinity_name=r.affinity_name, error=r.error
            )
            if r.success:
                for name, n in sorted(r.clusters.items()):
                    msg.clusters.add(name=name, replicas=n)
                msg.feasible.extend(sorted(r.feasible))
        return resp


class StaleSnapshotError(Exception):
    pass


class SolverGrpcServer:
    """Serves a SolverService over gRPC, optionally mTLS (same credential
    contract as the estimator server, grpcconnection/config.go)."""

    def __init__(
        self,
        service: SolverService,
        address: str = "127.0.0.1:0",
        *,
        server_cert: Optional[bytes] = None,
        server_key: Optional[bytes] = None,
        client_ca: Optional[bytes] = None,
        max_workers: int = 4,
    ):
        self._service = service
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[("grpc.so_reuseport", 0),
                     ("grpc.max_receive_message_length", 256 << 20),
                     ("grpc.max_send_message_length", 256 << 20)],
        )

        # served-RPC accounting: the sidecar PROCESS's /metrics answers
        # with this family (ISSUE 6 c). Handlers record ``solver.sync`` /
        # ``solver.solve`` spans under the CALLER's wave (trace context
        # decoded from the invocation metadata, ISSUE 10) — the engine's
        # own scheduler.solve / kernel.* spans nest inside solver.solve,
        # so the sidecar's kernel attribution stitches into the plane's
        # wave tree
        from ..utils.metrics import solver_requests
        from ..utils.tracing import decode_trace_metadata, tracer

        def _ctx(context):
            return decode_trace_metadata(context.invocation_metadata())

        def sync(request: pb.SyncClustersRequest, context):
            solver_requests.inc(method="SyncClusters")
            with tracer.server_span(
                "solver.sync", _ctx(context),
                clusters=len(request.clusters),
            ):
                version = self._service.sync_clusters(
                    [state_to_cluster(m) for m in request.clusters],
                    request.snapshot_version,
                )
            return pb.SyncClustersResponse(snapshot_version=version)

        def score(request: pb.ScoreAndAssignRequest, context):
            solver_requests.inc(method="ScoreAndAssign")
            with tracer.server_span(
                "solver.solve", _ctx(context), rows=len(request.problems),
            ) as sp:
                try:
                    return self._service.score_and_assign(request)
                except StaleSnapshotError as e:
                    sp.attrs["error"] = "stale_snapshot"
                    context.abort(
                        grpc.StatusCode.FAILED_PRECONDITION, str(e)
                    )

        handlers = {
            "SyncClusters": grpc.unary_unary_rpc_method_handler(
                sync,
                request_deserializer=pb.SyncClustersRequest.FromString,
                response_serializer=pb.SyncClustersResponse.SerializeToString,
            ),
            "ScoreAndAssign": grpc.unary_unary_rpc_method_handler(
                score,
                request_deserializer=pb.ScoreAndAssignRequest.FromString,
                response_serializer=pb.ScoreAndAssignResponse.SerializeToString,
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
        )
        if bool(server_cert) != bool(server_key) or (
            client_ca and not (server_cert and server_key)
        ):
            raise ValueError(
                "incomplete server TLS config: server_cert and server_key are "
                "both required (and client_ca implies them)"
            )
        if server_cert and server_key:
            creds = grpc.ssl_server_credentials(
                [(server_key, server_cert)],
                root_certificates=client_ca,
                require_client_auth=client_ca is not None,
            )
            self.port = self._server.add_secure_port(address, creds)
        else:
            self.port = self._server.add_insecure_port(address)
        if self.port == 0:
            raise RuntimeError(f"solver gRPC server failed to bind {address}")

    def start(self) -> int:
        self._server.start()
        return self.port

    def stop(self, grace: Optional[float] = 0.5) -> None:
        self._server.stop(grace)

    def wait(self) -> None:
        self._server.wait_for_termination()
