"""Solver sidecar process entry: ``python -m karmada_tpu.solver``."""

from __future__ import annotations

import argparse
import sys

from .service import SolverGrpcServer, SolverService


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="karmada-tpu solver sidecar")
    p.add_argument("--address", default="127.0.0.1:0")
    p.add_argument("--server-cert", default="", help="PEM file (TLS)")
    p.add_argument("--server-key", default="", help="PEM file (TLS)")
    p.add_argument("--client-ca", default="", help="PEM file (mTLS client auth)")
    p.add_argument(
        "--report-backend", action="store_true",
        help="print the resolved jax backend platform after binding — the "
        "orchestrator scrapes it to confirm which component owns the "
        "accelerator (forces backend init, which can take tens of "
        "seconds over a TPU tunnel)",
    )
    args = p.parse_args(argv)

    def read(path):
        return open(path, "rb").read() if path else None

    server = SolverGrpcServer(
        SolverService(),
        args.address,
        server_cert=read(args.server_cert),
        server_key=read(args.server_key),
        client_ca=read(args.client_ca),
    )
    port = server.start()
    # the parent process scrapes this line to learn the bound port
    print(f"solver listening on port {port}", flush=True)
    if args.report_backend:
        import jax

        print(f"solver backend {jax.devices()[0].platform}", flush=True)
    try:
        server.wait()
    except KeyboardInterrupt:
        server.stop()
        sys.exit(0)


if __name__ == "__main__":
    main()
