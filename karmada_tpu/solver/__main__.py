"""Solver sidecar process entry: ``python -m karmada_tpu.solver``."""

from __future__ import annotations

import argparse
import sys

from .service import SolverGrpcServer, SolverService


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="karmada-tpu solver sidecar")
    p.add_argument("--address", default="127.0.0.1:0")
    p.add_argument("--server-cert", default="", help="PEM file (TLS)")
    p.add_argument("--server-key", default="", help="PEM file (TLS)")
    p.add_argument("--client-ca", default="", help="PEM file (mTLS client auth)")
    p.add_argument(
        "--report-backend", action="store_true",
        help="print the resolved jax backend platform after binding — the "
        "orchestrator scrapes it to confirm which component owns the "
        "accelerator (forces backend init, which can take tens of "
        "seconds over a TPU tunnel)",
    )
    p.add_argument(
        "--backend-timeout", type=float, default=90.0,
        help="seconds to wait for accelerator backend init before printing "
        "'solver backend timeout' and exiting rc=3 — a single-client "
        "tunnel whose previous claimant died uncleanly holds the claim "
        "for minutes and the stuck claim cannot be cancelled in-process; "
        "fail-fast lets the orchestrator respawn a fresh claimant",
    )
    p.add_argument(
        "--warmup-manifest", default=None,
        help="trace-manifest path: AOT-prewarm the engine's XLA traces "
        "from it after backend init (off the serving path) and record "
        "fresh traces back into it, so a sidecar restart's first "
        "ScoreAndAssign wave runs only already-compiled traces "
        "(default: $KARMADA_TPU_TRACE_MANIFEST; '' disables)",
    )
    p.add_argument(
        "--metrics-port", default=None,
        help="serve /metrics + /healthz + /debug/traces on this port or HOST:PORT "
        "(0 = ephemeral, printed as 'metrics listening on port N'; "
        "default: $KARMADA_TPU_METRICS_PORT, empty = disabled)",
    )
    p.add_argument(
        "--estimator", action="append", default=[],
        help="NAME=HOST:PORT of an accurate-estimator server for cluster "
        "NAME (repeatable; same HOST:PORT shares one channel): the "
        "sidecar's engines min-merge live estimator answers into "
        "availability exactly like the in-proc plane does (localup serve "
        "--estimator) — the estimator channel moves WITH the engine when "
        "scheduling moves into the sidecar",
    )
    args = p.parse_args(argv)
    # chaos: arm deterministic fault injection from the environment
    # (KARMADA_TPU_FAULT_SPEC; disarmed when empty — zero overhead)
    from ..utils.faultinject import arm_from_env
    from ..utils.tracing import register_peers_from_env, tracer

    arm_from_env()
    # cross-process tracing: this process's spans export as proc="solver"
    # (the stitcher keys on it) and any configured peers register for
    # stitched dumps taken FROM this process
    tracer.set_process("solver")
    register_peers_from_env()

    def read(path):
        return open(path, "rb").read() if path else None

    # graceful SIGTERM: run the interpreter's normal exit path so the
    # accelerator client's destructors release the tunnel session — a
    # default-action SIGTERM death leaves the claim held server-side and
    # blocks the NEXT claimant for minutes (observed on the e2e)
    import signal as _signal

    _signal.signal(_signal.SIGTERM, lambda s, f: sys.exit(0))

    import os

    from ..scheduler.prewarm import resolve_boot_manifest
    from ..utils.compilecache import MANIFEST_ENV

    # flag absent (None) falls back to the env default; an EXPLICIT
    # --warmup-manifest '' disables even with the env var set (the
    # opt-out the help text promises). Exported so an opt-out also sticks
    # for engines this process builds without an explicit manifest.
    manifest_path = resolve_boot_manifest(args.warmup_manifest)
    os.environ[MANIFEST_ENV] = manifest_path
    if manifest_path:
        # the sidecar owns the engine (and with it the accelerator's trace
        # set): its engines record fresh traces into the manifest and —
        # once the prewarm below ran — seed their new-trace ledger from it
        from ..scheduler import TensorScheduler
        from ..scheduler.prewarm import TraceManifest

        manifest = TraceManifest(manifest_path)

        def base_factory(snap):
            return TensorScheduler(snap, trace_manifest=manifest)
    else:
        from ..scheduler import TensorScheduler

        manifest = None
        base_factory = TensorScheduler

    est_registry = None
    if args.estimator:
        # estimator-aware sidecar: register a RemoteAccurateEstimator per
        # named cluster (channels shared per target) and fold the live
        # answers into every engine this service builds — the same
        # min-merge the in-proc plane applies, now ON the process that
        # actually solves, so the scheduler->solver->estimator chain is
        # one stitched trace
        from ..estimator.accurate import EstimatorRegistry
        from ..estimator.grpc_transport import GrpcEstimatorConnection

        est_registry = EstimatorRegistry()
        svc_cell: list = []  # filled after SolverService construction

        def engine_dims():
            return list(svc_cell[0]._engine.snapshot.dims)

        conns: dict = {}
        from ..estimator.grpc_transport import RemoteAccurateEstimator

        for spec in args.estimator:
            name, _, target = spec.partition("=")
            if not name or not target:
                p.error(f"--estimator wants NAME=HOST:PORT, got {spec!r}")
            conn = conns.get(target)
            if conn is None:
                conn = GrpcEstimatorConnection(name, target)
                conns[target] = conn
            est_registry.register(
                RemoteAccurateEstimator(name, conn, engine_dims)
            )

        def engine_factory(snap):
            eng = base_factory(snap)
            eng.extra_estimators = [
                est_registry.make_batch_estimator(list(snap.names))
            ]
            return eng
    else:
        engine_factory = base_factory

    service = SolverService(engine_factory=engine_factory)
    if est_registry is not None:
        svc_cell.append(service)
        # the sidecar has no member-event channel to invalidate the
        # registry, so every solve revalidates it generation-gated (the
        # PR 4 contract): one GetGenerations ping per server per pass,
        # re-fetch only for clusters whose snapshot actually moved — a
        # memoized answer can never go stale across passes
        _score = service.score_and_assign

        def score_with_revalidate(request):
            est_registry.invalidate()
            return _score(request)

        service.score_and_assign = score_with_revalidate

    server = SolverGrpcServer(
        service,
        args.address,
        server_cert=read(args.server_cert),
        server_key=read(args.server_key),
        client_ca=read(args.client_ca),
    )
    port = server.start()
    # the parent process scrapes this line to learn the bound port
    print(f"solver listening on port {port}", flush=True)
    from ..utils.metrics import serve_process_metrics

    # AFTER the gRPC port line (orchestrators scrape the first
    # "port (\d+)" match) and BEFORE the backend probe/prewarm: the
    # endpoint answers while the accelerator claim is still settling
    metrics = serve_process_metrics(args.metrics_port)
    if metrics is not None:
        print(f"metrics listening on port {metrics.port}", flush=True)
    if args.report_backend:
        import os as _os
        import threading
        import traceback

        done = threading.Event()
        platform = [""]
        failure = [None]

        def probe() -> None:
            try:
                import jax

                platform[0] = jax.devices()[0].platform
            except BaseException as e:  # noqa: BLE001 — reported below
                failure[0] = e
            finally:
                done.set()

        threading.Thread(target=probe, daemon=True).start()
        if not done.wait(args.backend_timeout):
            # a true HANG (single-client claim held): retryable — the
            # orchestrator respawns a fresh claimant
            print("solver backend timeout", flush=True)
            _os._exit(3)
        if failure[0] is not None:
            # a deterministic init FAILURE: retrying would burn the whole
            # retry budget on the same traceback — distinct marker + the
            # traceback after it so the orchestrator can surface it
            print("solver backend error", flush=True)
            traceback.print_exception(failure[0], file=sys.stdout)
            sys.stdout.flush()
            _os._exit(4)
        print(f"solver backend {platform[0]}", flush=True)
    # scheduling-mesh report: when the env requests a mesh
    # (KARMADA_TPU_MESH_DEVICES), resolve and print its shape so the
    # orchestrator (and `karmadactl-tpu trace dump`) can tell a
    # single-chip from an 8-chip plane. Env-gated: without the knob this
    # prints nothing and never touches the backend.
    if os.environ.get("KARMADA_TPU_MESH_DEVICES", "").strip() not in (
        "", "0", "1"
    ):
        from ..parallel.mesh import mesh_shape, resolve_mesh

        try:
            shape = mesh_shape(resolve_mesh(None))
        except Exception as exc:  # noqa: BLE001 — report, then let the
            # first engine construction fail loudly with the same error
            print(f"solver mesh error: {exc}", flush=True)
        else:
            axes = " ".join(f"{n}={s}" for n, s in (shape or ()))
            print(f"solver mesh {axes or 'single-device'}", flush=True)
    if manifest is not None:
        # prewarm AFTER the port/backend lines the orchestrator scrapes:
        # compiles run off the serving path (the plane connects and syncs
        # while this proceeds; the gRPC executor serves concurrently).
        # warmup() also drops the persistence threshold to 0 so every
        # warmed trace lands in the persistent cache.
        from ..scheduler.prewarm import warmup

        stats = warmup(manifest.path)
        print(
            f"solver prewarm {stats['compiled']}/{stats['specs']} traces "
            f"in {stats['seconds']:.1f}s",
            flush=True,
        )
    try:
        server.wait()
    except KeyboardInterrupt:
        server.stop()
        sys.exit(0)


if __name__ == "__main__":
    main()
