"""Solver sidecar process entry: ``python -m karmada_tpu.solver``."""

from __future__ import annotations

import argparse
import sys

from .service import SolverGrpcServer, SolverService


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="karmada-tpu solver sidecar")
    p.add_argument("--address", default="127.0.0.1:0")
    p.add_argument("--server-cert", default="", help="PEM file (TLS)")
    p.add_argument("--server-key", default="", help="PEM file (TLS)")
    p.add_argument("--client-ca", default="", help="PEM file (mTLS client auth)")
    p.add_argument(
        "--report-backend", action="store_true",
        help="print the resolved jax backend platform after binding — the "
        "orchestrator scrapes it to confirm which component owns the "
        "accelerator (forces backend init, which can take tens of "
        "seconds over a TPU tunnel)",
    )
    p.add_argument(
        "--backend-timeout", type=float, default=90.0,
        help="seconds to wait for accelerator backend init before printing "
        "'solver backend timeout' and exiting rc=3 — a single-client "
        "tunnel whose previous claimant died uncleanly holds the claim "
        "for minutes and the stuck claim cannot be cancelled in-process; "
        "fail-fast lets the orchestrator respawn a fresh claimant",
    )
    args = p.parse_args(argv)

    def read(path):
        return open(path, "rb").read() if path else None

    # graceful SIGTERM: run the interpreter's normal exit path so the
    # accelerator client's destructors release the tunnel session — a
    # default-action SIGTERM death leaves the claim held server-side and
    # blocks the NEXT claimant for minutes (observed on the e2e)
    import signal as _signal

    _signal.signal(_signal.SIGTERM, lambda s, f: sys.exit(0))

    server = SolverGrpcServer(
        SolverService(),
        args.address,
        server_cert=read(args.server_cert),
        server_key=read(args.server_key),
        client_ca=read(args.client_ca),
    )
    port = server.start()
    # the parent process scrapes this line to learn the bound port
    print(f"solver listening on port {port}", flush=True)
    if args.report_backend:
        import os as _os
        import threading
        import traceback

        done = threading.Event()
        platform = [""]
        failure = [None]

        def probe() -> None:
            try:
                import jax

                platform[0] = jax.devices()[0].platform
            except BaseException as e:  # noqa: BLE001 — reported below
                failure[0] = e
            finally:
                done.set()

        threading.Thread(target=probe, daemon=True).start()
        if not done.wait(args.backend_timeout):
            # a true HANG (single-client claim held): retryable — the
            # orchestrator respawns a fresh claimant
            print("solver backend timeout", flush=True)
            _os._exit(3)
        if failure[0] is not None:
            # a deterministic init FAILURE: retrying would burn the whole
            # retry budget on the same traceback — distinct marker + the
            # traceback after it so the orchestrator can surface it
            print("solver backend error", flush=True)
            traceback.print_exception(failure[0], file=sys.stdout)
            sys.stdout.flush()
            _os._exit(4)
        print(f"solver backend {platform[0]}", flush=True)
    try:
        server.wait()
    except KeyboardInterrupt:
        server.stop()
        sys.exit(0)


if __name__ == "__main__":
    main()
