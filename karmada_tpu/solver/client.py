"""Control-plane side of the solver sidecar channel.

``RemoteSolver`` satisfies the engine seam the scheduler controller uses
(``schedule(problems) -> results``) over gRPC, with snapshot-version
fencing: cluster events push SyncClusters, ScoreAndAssign carries the
pushed version, and a FAILED_PRECONDITION answer (solver restarted, missed
sync) triggers one re-sync + retry. Mirrors the estimator client pattern
(estimator/grpc_transport.py; ref pkg/estimator/client/cache.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import grpc

from ..scheduler import BindingProblem
from ..utils.backoff import CircuitBreakerOpen, Deadline, default_breaker
from ..utils.faultinject import apply_fault, fault_point
from ..utils.tracing import trace_metadata, tracer
from .proto import solver_pb2 as pb
from .service import SERVICE_NAME, cluster_to_state, encode_problems


@dataclass
class RemoteScheduleResult:
    """Wire-decoded ScheduleResult (same surface the engine returns)."""

    key: str
    clusters: dict = field(default_factory=dict)
    feasible: tuple = ()
    affinity_name: str = ""
    error: str = ""

    @property
    def success(self) -> bool:
        return not self.error


class RemoteSolver:
    def __init__(
        self,
        target: str,
        *,
        root_ca: Optional[bytes] = None,
        client_cert: Optional[bytes] = None,
        client_key: Optional[bytes] = None,
        timeout_seconds: float = 120.0,
        cluster_source=None,  # () -> list[Cluster]; used for re-sync
    ):
        if (client_cert or client_key) and not (root_ca and client_cert and client_key):
            raise ValueError(
                "incomplete client TLS config: client_cert/client_key require "
                "each other and root_ca"
            )
        self.target = target
        opts = [("grpc.max_receive_message_length", 256 << 20),
                ("grpc.max_send_message_length", 256 << 20)]
        if root_ca is not None:
            creds = grpc.ssl_channel_credentials(
                root_certificates=root_ca,
                private_key=client_key,
                certificate_chain=client_cert,
            )
            self._channel = grpc.secure_channel(target, creds, options=opts)
        else:
            self._channel = grpc.insecure_channel(target, options=opts)
        self.timeout = timeout_seconds
        self._version = 0
        self._cluster_source = cluster_source
        # unified channel resilience (utils.backoff): the breaker marks
        # this sidecar degraded after consecutive transport failures so
        # the scheduler's in-proc fallback engages without burning a
        # doomed RPC per pass; half-open re-probes heal it automatically
        self.breaker = default_breaker(f"solver@{target}")
        self._sync = self._channel.unary_unary(
            f"/{SERVICE_NAME}/SyncClusters",
            request_serializer=pb.SyncClustersRequest.SerializeToString,
            response_deserializer=pb.SyncClustersResponse.FromString,
        )
        self._score = self._channel.unary_unary(
            f"/{SERVICE_NAME}/ScoreAndAssign",
            request_serializer=pb.ScoreAndAssignRequest.SerializeToString,
            response_deserializer=pb.ScoreAndAssignResponse.FromString,
        )

    # -- snapshot channel --------------------------------------------------

    def sync_clusters(
        self,
        clusters,
        *,
        timeout: Optional[float] = None,
        check_breaker: bool = True,
    ) -> int:
        """``check_breaker=False`` is for the re-sync inside ``schedule``:
        that caller already holds the breaker's admission (possibly the
        single half-open probe slot) and owns the outcome record."""
        if check_breaker and not self.breaker.allow():
            raise CircuitBreakerOpen(
                f"solver {self._channel!r} breaker is open"
            )
        self._version += 1
        req = pb.SyncClustersRequest(snapshot_version=self._version)
        for cl in clusters:
            req.clusters.append(cluster_to_state(cl))
        ok = False
        try:
            with tracer.span(
                "solver.rpc", remote=True, peer=self.target,
                method="SyncClusters",
            ):
                md = trace_metadata(tracer.current_context())
                apply_fault(
                    fault_point("solver.rpc", "SyncClusters"),
                    "solver.rpc", "SyncClusters", channel=self._channel,
                )
                resp = self._sync(
                    req,
                    timeout=self.timeout if timeout is None else timeout,
                    metadata=md,
                )
            ok = True
        finally:
            # every admitted call records its outcome: a half-open probe
            # slot taken but never resolved would wedge the breaker. The
            # ungated form records nothing — the owning schedule() call
            # does.
            if check_breaker:
                (self.breaker.record_success if ok
                 else self.breaker.record_failure)()
        return resp.snapshot_version

    # -- engine seam -------------------------------------------------------

    def schedule(self, problems: Sequence[BindingProblem]) -> list:
        """Score the batch under ONE overall deadline budget: the re-sync-
        then-retry path (FAILED_PRECONDITION after a solver restart) used
        to stack ``self.timeout`` up to three times (score, sync, retry);
        every RPC now carries the REMAINING budget, so a dead or black-
        holed solver fails the whole call within 1x ``self.timeout`` —
        the standby-sync discipline HASolver already had, generalized."""
        if not self.breaker.allow():
            raise CircuitBreakerOpen(
                f"solver {self._channel!r} breaker is open"
            )
        deadline = Deadline(self.timeout)
        req = encode_problems(problems)
        req.snapshot_version = self._version
        ok = False

        def score_attempt(attempt: int):
            # one client span per WIRE attempt: a retried RPC is two
            # spans, so each server-side ``solver.solve`` span re-parents
            # under exactly one attempt — never under two parents
            with tracer.span(
                "solver.rpc", remote=True, peer=self.target,
                method="ScoreAndAssign", attempt=attempt,
            ):
                md = trace_metadata(tracer.current_context())
                return self._score(
                    req, timeout=deadline.attempt_timeout(), metadata=md
                )

        try:
            apply_fault(
                fault_point("solver.rpc", "ScoreAndAssign"),
                "solver.rpc", "ScoreAndAssign", channel=self._channel,
            )
            try:
                resp = score_attempt(1)
            except grpc.RpcError as e:
                if (
                    e.code() == grpc.StatusCode.FAILED_PRECONDITION
                    and self._cluster_source is not None
                ):
                    # solver restarted or missed a sync: push state and
                    # retry once, both on the REMAINING budget (this call
                    # holds the breaker admission, so the sync is ungated)
                    self.sync_clusters(
                        self._cluster_source(),
                        timeout=deadline.attempt_timeout(),
                        check_breaker=False,
                    )
                    req.snapshot_version = self._version
                    resp = score_attempt(2)
                else:
                    raise
            ok = True
        finally:
            (self.breaker.record_success if ok
             else self.breaker.record_failure)()
        return [
            RemoteScheduleResult(
                key=m.key,
                clusters={tc.name: tc.replicas for tc in m.clusters},
                feasible=tuple(m.feasible),
                affinity_name=m.affinity_name,
                error=m.error,
            )
            for m in resp.results
        ]

    def close(self) -> None:
        self._channel.close()


class HASolver:
    """N solver sidecars, one active: the reference runs scheduler
    replicas behind leader election / a Service and any single live
    backend can answer. Here ``schedule()`` sticks to the active endpoint
    and fails over on transport errors; ``sync_clusters`` broadcasts
    best-effort so standbys hold warm snapshots (a cold standby heals
    anyway via the FAILED_PRECONDITION re-sync in RemoteSolver.schedule).

    Satisfies the same engine seam as RemoteSolver, so
    ``ControlPlane(solver=HASolver([...]))`` is a drop-in."""

    def __init__(
        self,
        targets: Sequence[str],
        *,
        cluster_source=None,
        **kw,
    ):
        if not targets:
            raise ValueError("HASolver needs at least one target")
        self._solvers = [
            RemoteSolver(t, cluster_source=cluster_source, **kw)
            for t in targets
        ]
        self._active = 0

    @property
    def _cluster_source(self):
        return self._solvers[0]._cluster_source

    @_cluster_source.setter
    def _cluster_source(self, fn) -> None:
        # the scheduler controller assigns this post-construction; every
        # backend heals independently, so each needs the source
        for s in self._solvers:
            s._cluster_source = fn

    @property
    def active_target(self) -> int:
        return self._active

    #: standby sync deadline: standby warmth is best-effort (a cold one
    #: heals via FAILED_PRECONDITION re-sync), so a black-holed standby
    #: must not stall the scheduler path for the full RPC timeout
    STANDBY_SYNC_TIMEOUT = 5.0

    def sync_clusters(self, clusters) -> int:
        from concurrent.futures import ThreadPoolExecutor

        results: list = [None] * len(self._solvers)
        errs: list = [None] * len(self._solvers)
        # fan-out threads inherit the caller's trace context so each
        # backend's solver.rpc span lands in the wave that synced
        ctx = tracer.current_context()

        def one(i: int) -> None:
            with tracer.activate(ctx):
                return _one(i)

        def _one(i: int) -> None:
            try:
                results[i] = self._solvers[i].sync_clusters(
                    clusters,
                    timeout=(
                        None
                        if i == self._active
                        else self.STANDBY_SYNC_TIMEOUT
                    ),
                )
            except (grpc.RpcError, CircuitBreakerOpen) as e:
                # standby down (or breaker-open, costing zero RPC): its
                # FAILED_PRECONDITION re-sync heals it later
                errs[i] = e

        # concurrent fan-out: N black-holed standbys cost ONE standby
        # deadline, not N of them stacked
        with ThreadPoolExecutor(max_workers=len(self._solvers)) as pool:
            list(pool.map(one, range(len(self._solvers))))
        live = [v for v in results if v is not None]
        if not live:
            err = next(e for e in errs if e is not None)
            raise err
        return max(live)

    def schedule(self, problems: Sequence[BindingProblem]) -> list:
        n = len(self._solvers)
        last_err: Optional[Exception] = None
        for i in range(n):
            idx = (self._active + i) % n
            try:
                res = self._solvers[idx].schedule(problems)
                self._active = idx
                return res
            except (grpc.RpcError, CircuitBreakerOpen) as e:
                # a breaker-open backend is skipped without burning an RPC
                last_err = e
        assert last_err is not None
        raise last_err

    def close(self) -> None:
        for s in self._solvers:
            s.close()
