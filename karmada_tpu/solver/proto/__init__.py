"""Generated protobuf messages for the solver sidecar wire contract."""

from . import solver_pb2  # noqa: F401
