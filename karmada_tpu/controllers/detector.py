"""ResourceDetector: template -> policy match -> ResourceBinding.

Ref: pkg/detector/detector.go — event-driven discovery of resource
templates, policy matching with priority + preemption (policy.go,
preemption.go), claiming (claim.go), and ResourceBinding construction with
interpreter-provided replicas (BuildResourceBinding, detector.go:710-752).
Policy add/update/delete re-binds claimed templates (detector.go:851-1360).
"""

from __future__ import annotations

import time
from typing import Optional

from ..api.core import Resource
from ..api.policy import (
    ClusterPropagationPolicy,
    PropagationPolicy,
    ResourceSelector,
)
from ..api.work import ResourceBinding, ResourceBindingSpec
from ..api.core import ObjectMeta
from ..interpreter import ResourceInterpreter
from ..utils import DONE, Runtime, Store
from ..utils.features import POLICY_PREEMPTION, feature_gate
from ..utils.tracing import tracer
from .overridemanager import resource_matches_selector

# claim labels (ref: policy permanent-ID labels, claim.go)
POLICY_LABEL = "propagationpolicy.karmada.io/name"
POLICY_NS_LABEL = "propagationpolicy.karmada.io/namespace"
CLUSTER_POLICY_LABEL = "clusterpropagationpolicy.karmada.io/name"


def binding_name(template: Resource) -> str:
    return f"{template.meta.name}-{template.kind.lower()}"


def policy_matches(template: Resource, selectors: list[ResourceSelector]) -> bool:
    return any(resource_matches_selector(template, s) for s in selectors)


def _policy_priority(policy, template: Resource) -> tuple:
    """Implicit priority (ref: policy.go getHighestPriorityPropagationPolicy):
    explicit spec.priority first; for ties, name-selector matches outrank
    selector-only matches; final tiebreak alphabetical (oldest-wins is
    approximated by name for determinism)."""
    by_name = any(
        s.name == template.meta.name and (not s.kind or s.kind == template.kind)
        for s in policy.spec.resource_selectors
    )
    return (-policy.spec.priority, 0 if by_name else 1, policy.meta.name)


class ResourceDetector:
    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        interpreter: ResourceInterpreter,
    ) -> None:
        self.store = store
        self.interpreter = interpreter
        # per-drain write set (ISSUE 11): claims + bindings buffer during
        # a batched drain and flush as one store.apply_many; per-namespace
        # ownership sharding keeps one namespace's storm from serializing
        # another's drain on a single queue
        self._buffering = False
        self._pending: list = []
        self.worker = runtime.new_worker(
            "detector", self._reconcile,
            reconcile_batch=self._reconcile_batch,
            shard_fn=lambda key: key.partition("/")[0] if "/" in key else "",
        )
        # keys whose pending reconcile was triggered ONLY by Karmada itself
        # (policy events), not by a user template change — consumed by the
        # lazy-activation gate (detector.go:444,529 resourceChangeByKarmada).
        # _user_pending tracks queued template-event keys so a policy event
        # arriving AFTER a user change (but before the worker drains) cannot
        # re-mark the coalesced reconcile as Karmada-triggered and swallow
        # the user's update under a Lazy policy.
        self._by_karmada: set[str] = set()
        self._user_pending: set[str] = set()
        store.watch("Resource", self._on_template_event)
        store.watch("PropagationPolicy", self._on_policy_event)
        store.watch("ClusterPropagationPolicy", self._on_policy_event)

    # -- events ------------------------------------------------------------

    def _on_template_event(self, event) -> None:
        # a user-driven template event is the canonical start of a wave:
        # stamp the monotonic wave id HERE so the whole downstream chain
        # (policy match -> binding -> scheduler pass -> work render ->
        # status) records its spans under one tree (utils.tracing). A
        # burst of events shares the open wave; the wave closes when the
        # plane settles.
        tracer.ensure_wave("detector")
        self._by_karmada.discard(event.key)  # a user change always syncs
        self._user_pending.add(event.key)
        self.worker.enqueue(event.key)

    def _on_policy_event(self, event) -> None:
        # scope the requeue the way the reference does: templates matching
        # the (new) selectors, plus templates currently claimed by this
        # policy (they may need to unbind after a selector change)
        policy = event.obj
        selectors = policy.spec.resource_selectors
        pname = policy.meta.name
        for template in self.store.list("Resource"):
            claimed = (
                template.meta.labels.get(POLICY_LABEL) == pname
                or template.meta.labels.get(CLUSTER_POLICY_LABEL) == pname
            )
            if claimed or policy_matches(template, selectors):
                key = template.meta.namespaced_name
                if key not in self._user_pending:
                    self._by_karmada.add(key)
                self.worker.enqueue(key)

    # -- reconcile ---------------------------------------------------------

    def _reconcile_batch(self, keys) -> dict:
        out: dict = {}
        self._buffering = True
        try:
            for key in keys:
                out[key] = self._reconcile(key)
        finally:
            self._buffering = False
            self._flush()
        return out

    def _apply(self, obj) -> None:
        if self._buffering:
            self._pending.append(obj)
        else:
            self.store.apply(obj)

    def _flush(self) -> None:
        pending, self._pending = self._pending, []
        if not pending:
            return
        apply_many = getattr(self.store, "apply_many", None)
        if apply_many is not None:
            for obj, err in apply_many(pending):
                print(
                    f"# detector: apply rejected for "
                    f"{obj.meta.namespaced_name}: {err}",
                    flush=True,
                )
                # re-reconcile the TEMPLATE the rejected write belongs
                # to (bindings carry their template in spec.resource) —
                # the unbatched path raised here and the worker retried
                resource = getattr(obj.spec, "resource", None)
                self.worker.enqueue(
                    resource.namespaced_key
                    if resource is not None
                    else obj.meta.namespaced_name
                )
        else:
            for obj in pending:
                self.store.apply(obj)

    def _reconcile(self, key: str) -> Optional[str]:
        by_karmada = key in self._by_karmada
        self._by_karmada.discard(key)
        self._user_pending.discard(key)
        template = self.store.get("Resource", key)
        if template is None:
            self._remove_binding_for(key)
            return DONE
        policy = self._match_policy(template)
        if policy is None:
            self._unclaim(template)
            return DONE
        self._claim(template, policy)
        self._ensure_binding(template, policy, by_karmada)
        return DONE

    def _match_policy(self, template: Resource):
        """Priority + preemption matching. Namespaced policies outrank
        cluster-scoped ones for namespaced resources (detector.go ordering:
        PropagationPolicy first, then ClusterPropagationPolicy)."""
        candidates = [
            p
            for p in self.store.list("PropagationPolicy", template.meta.namespace or None)
            if p.meta.namespace == template.meta.namespace
            and policy_matches(template, p.spec.resource_selectors)
        ]
        pool = sorted(candidates, key=lambda p: _policy_priority(p, template))
        claimed_by = template.meta.labels.get(POLICY_LABEL)
        if not pool:
            cluster_pool = sorted(
                (
                    p
                    for p in self.store.list("ClusterPropagationPolicy")
                    if policy_matches(template, p.spec.resource_selectors)
                ),
                key=lambda p: _policy_priority(p, template),
            )
            pool = cluster_pool
            # the preemption gate guards whichever claim kind this pool
            # competes for — a CPP-claimed template is protected from other
            # CPPs exactly like a PP-claimed one from other PPs
            claimed_by = template.meta.labels.get(CLUSTER_POLICY_LABEL)
        if not pool:
            return None
        best = pool[0]
        if claimed_by and claimed_by != best.meta.name:
            # a higher-priority policy takes a claimed template only when the
            # PolicyPreemption gate is on AND the policy itself declares
            # spec.preemption Always (preemption.go: both are required)
            may_preempt = (
                feature_gate.enabled(POLICY_PREEMPTION)
                and getattr(best.spec, "preemption", "Never") == "Always"
            )
            if not may_preempt:
                # keep the existing claim unless it vanished
                current = next((p for p in pool if p.meta.name == claimed_by), None)
                if current is not None:
                    return current
        return best

    def _claim(self, template: Resource, policy) -> None:
        labels = template.meta.labels
        if isinstance(policy, ClusterPropagationPolicy) or policy.cluster_scoped:
            changed = labels.get(CLUSTER_POLICY_LABEL) != policy.meta.name
            labels[CLUSTER_POLICY_LABEL] = policy.meta.name
            labels.pop(POLICY_LABEL, None)
            labels.pop(POLICY_NS_LABEL, None)
        else:
            changed = labels.get(POLICY_LABEL) != policy.meta.name
            labels[POLICY_LABEL] = policy.meta.name
            labels[POLICY_NS_LABEL] = policy.meta.namespace
            labels.pop(CLUSTER_POLICY_LABEL, None)
        if changed:
            self._apply(template)

    def _unclaim(self, template: Resource) -> None:
        labels = template.meta.labels
        had = (
            labels.pop(POLICY_LABEL, None) is not None
            or labels.pop(CLUSTER_POLICY_LABEL, None) is not None
        )
        labels.pop(POLICY_NS_LABEL, None)
        if had:
            self.store.apply(template)
            self._remove_binding_for(template.meta.namespaced_name)

    def _ensure_binding(self, template: Resource, policy, by_karmada: bool = False) -> None:
        """BuildResourceBinding (detector.go:710-752). Cluster-scoped
        templates produce ClusterResourceBindings."""
        replicas, requirements = self.interpreter.get_replicas(template)
        name = binding_name(template)
        key = (
            f"{template.meta.namespace}/{name}" if template.meta.namespace else name
        )
        kind = "ResourceBinding" if template.meta.namespace else "ClusterResourceBinding"
        existing = self.store.get(kind, key)
        # Lazy activation (detector.go:444-450): a reconcile that Karmada
        # itself triggered (policy change) must not refresh an existing
        # binding when the bound policy defers activation — the new policy
        # content lands only when the USER next updates the template. The
        # claim above still records the new policy id.
        if (
            existing is not None
            and by_karmada
            and getattr(policy.spec, "activation_preference", "") == "Lazy"
        ):
            return
        spec = ResourceBindingSpec(
            resource=template.object_reference(),
            replicas=replicas,
            replica_requirements=requirements,
            placement=policy.spec.placement,
            # ISSUE 14: the policy's explicit priority reaches the
            # ResourceBinding spec (before this it only ordered policy
            # MATCHING, so the scheduler could never see it); default 0
            # keeps pre-priority bindings scheduling exactly as before
            priority=policy.spec.priority,
            conflict_resolution=policy.spec.conflict_resolution,
            propagate_deps=policy.spec.propagate_deps,
            suspend_dispatching=policy.spec.suspend_dispatching,
            suspend_dispatching_on_clusters=getattr(
                policy.spec, "suspend_dispatching_on_clusters", None
            ),
            preserve_resources_on_deletion=policy.spec.preserve_resources_on_deletion,
            failover=policy.spec.failover,
            scheduler_name=policy.spec.scheduler_name,
        )
        if existing is not None:
            # preserve schedule state; bump generation when the scheduling-
            # relevant spec changed (placement or replicas)
            spec.clusters = existing.spec.clusters
            spec.graceful_eviction_tasks = existing.spec.graceful_eviction_tasks
            spec.reschedule_triggered_at = existing.spec.reschedule_triggered_at
            changed = (
                existing.spec.placement != spec.placement
                or existing.spec.replicas != spec.replicas
                or existing.spec.replica_requirements != spec.replica_requirements
                # getattr: a checkpoint written by a pre-priority build
                # unpickles without the field (Store.restore bypasses
                # __init__) — it reads as the 0 default, not a change
                or getattr(existing.spec, "priority", 0) != spec.priority
            )
            existing.spec = spec
            if changed:
                existing.meta.generation += 1
            self._apply(existing)
        else:
            from ..api.work import ClusterResourceBinding

            cls = ResourceBinding if template.meta.namespace else ClusterResourceBinding
            rb = cls(
                meta=ObjectMeta(
                    name=name,
                    namespace=template.meta.namespace,
                    labels={
                        POLICY_LABEL: policy.meta.name,
                    },
                ),
                spec=spec,
            )
            self._apply(rb)

    def _remove_binding_for(self, template_key: str) -> None:
        ns, _, name = template_key.rpartition("/")
        for kind in ("ResourceBinding", "ClusterResourceBinding"):
            for rb in self.store.list(kind):
                if (
                    rb.spec.resource.namespaced_key == template_key
                    or (rb.meta.namespace == ns and rb.spec.resource.name == name)
                ):
                    self.store.delete(kind, rb.meta.namespaced_name)

    def write_back_status(self, binding: ResourceBinding) -> None:
        """Detector also writes aggregated status back onto the template
        (detector.go status sync)."""
        template = self.store.get("Resource", binding.spec.resource.namespaced_key)
        if template is None:
            return
        updated = self.interpreter.aggregate_status(
            template, binding.status.aggregated_status
        )
        if updated.status != template.status:
            template.status = updated.status
            self.store.apply(template)
