"""Smaller control-plane components: namespace sync, WorkloadRebalancer,
FederatedResourceQuota, unified auth.

Ref:
- namespace-sync-controller (pkg/controllers/namespace/, 285 LoC):
  auto-propagates user namespaces to every member cluster.
- workloadRebalancer (pkg/controllers/workloadrebalancer/):
  `WorkloadRebalancer` CR sets spec.rescheduleTriggeredAt on listed bindings
  -> Fresh reassignment (assignment.go:109-117).
- federatedResourceQuota sync/status (pkg/controllers/federatedresourcequota/):
  static quota slices propagated to member clusters as Works; status
  aggregates used from members.
- unified-auth-controller (pkg/controllers/unifiedauth/): RBAC sync into
  members for admin subjects.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field
from typing import Optional

from ..api.core import ObjectMeta, Resource
from ..api.work import Work, WorkSpec
from ..utils import DONE, Runtime, Store
from .propagation import execution_namespace

SKIP_AUTO_PROPAGATION_LABEL = "namespace.karmada.io/skip-auto-propagation"
_RESERVED_NS_PREFIXES = ("kube-", "karmada-")
_RESERVED_NS = {"default", "kube-system", "kube-public"}


class NamespaceSyncController:
    """Namespace templates -> Works in every member cluster
    (namespace/namespace_sync_controller.go)."""

    def __init__(self, store: Store, runtime: Runtime) -> None:
        self.store = store
        self.worker = runtime.new_worker("namespace-sync", self._reconcile)
        store.watch("Resource", self._on_resource_event)
        store.watch("Cluster", self._on_cluster_event)

    def _on_resource_event(self, event) -> None:
        if event.obj.kind == "Namespace":
            self.worker.enqueue(event.obj.meta.name)

    def _on_cluster_event(self, event) -> None:
        for res in self.store.list("Resource"):
            if res.kind == "Namespace":
                self.worker.enqueue(res.meta.name)

    def _should_sync(self, ns: Resource) -> bool:
        name = ns.meta.name
        if name in _RESERVED_NS or any(
            name.startswith(p) for p in _RESERVED_NS_PREFIXES
        ):
            return False
        if ns.meta.labels.get(SKIP_AUTO_PROPAGATION_LABEL) == "true":
            return False
        return True

    def _reconcile(self, name: str) -> Optional[str]:
        ns = self.store.get("Resource", name)
        if ns is None or ns.kind != "Namespace" or not self._should_sync(ns):
            return DONE
        for cluster in self.store.list("Cluster"):
            work_ns = execution_namespace(cluster.name)
            key = f"{work_ns}/ns-{name}"
            if self.store.get("Work", key) is None:
                self.store.apply(
                    Work(
                        meta=ObjectMeta(name=f"ns-{name}", namespace=work_ns),
                        spec=WorkSpec(workload=[ns]),
                    )
                )
        return DONE


# --- WorkloadRebalancer ------------------------------------------------------


@dataclass
class ObjectReferenceSelector:
    api_version: str = "apps/v1"
    kind: str = "Deployment"
    namespace: str = ""
    name: str = ""


@dataclass
class WorkloadRebalancerSpec:
    workloads: list[ObjectReferenceSelector] = field(default_factory=list)
    # lifetime after every workload finished; None = keep forever
    # (workloadrebalancer_types.go:61-67)
    ttl_seconds_after_finished: Optional[int] = None


@dataclass
class WorkloadRebalancerStatus:
    observed_workloads: list[dict] = field(default_factory=list)
    observed_generation: int = 0
    finish_time: Optional[float] = None
    # content digest of the spec.workloads that produced this status —
    # the echo gate's comparison key (see _workloads_digest)
    observed_spec_digest: str = ""


def _workloads_digest(workloads) -> str:
    """Content identity of ``spec.workloads``. The apiserver auto-bumps
    generation on spec writes but Store.apply does not, so a writer that
    edits the list in place hands the reconciler the SAME generation —
    and with a same-length edit, the same workload count. Only content
    tells such an edit apart from our own status-apply echo."""
    h = hashlib.sha256()
    for t in workloads:
        h.update(
            f"{t.api_version}|{t.kind}|{t.namespace}|{t.name}\n".encode()
        )
    return h.hexdigest()


@dataclass
class WorkloadRebalancer:
    KIND = "WorkloadRebalancer"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: WorkloadRebalancerSpec = field(default_factory=WorkloadRebalancerSpec)
    status: WorkloadRebalancerStatus = field(default_factory=WorkloadRebalancerStatus)


class WorkloadRebalancerController:
    """Sets rescheduleTriggeredAt on the bindings of listed workloads
    (workloadrebalancer controller -> Fresh assignment)."""

    def __init__(self, store: Store, runtime: Runtime, clock=time.time) -> None:
        self.store = store
        self.clock = clock
        self.worker = runtime.new_worker("workload-rebalancer", self._reconcile)
        store.watch("WorkloadRebalancer", lambda e: self.worker.enqueue(e.key))
        runtime.add_ticker(self._sweep_expired)

    def _sweep_expired(self) -> None:
        """TTLSecondsAfterFinished cleanup
        (workloadrebalancer_controller.go:99-107,295-298)."""
        now = self.clock()
        for r in list(self.store.list("WorkloadRebalancer")):
            if (
                r.spec.ttl_seconds_after_finished is not None
                and r.status.finish_time is not None
                and now - r.status.finish_time
                >= r.spec.ttl_seconds_after_finished
            ):
                self.store.delete("WorkloadRebalancer", r.meta.namespaced_name)

    def _reconcile(self, key: str) -> Optional[str]:
        rebalancer = self.store.get("WorkloadRebalancer", key)
        if rebalancer is None:
            return DONE
        spec_digest = _workloads_digest(rebalancer.spec.workloads)
        # getattr: a checkpoint restore unpickles statuses written by a
        # pre-digest build (Store.restore bypasses __init__), so the field
        # can be missing; such a legacy finished status falls back to the
        # old length gate rather than re-triggering every restored
        # rebalancer at boot
        status_digest = getattr(
            rebalancer.status, "observed_spec_digest", ""
        )
        digest_ok = (
            status_digest == spec_digest
            if status_digest
            else len(rebalancer.status.observed_workloads)
            == len(rebalancer.spec.workloads)
        )
        if (
            rebalancer.status.observed_generation == rebalancer.meta.generation
            and rebalancer.status.finish_time is not None
            # generation alone is not enough in this store: the apiserver
            # auto-bumps generation on spec writes, Store.apply does not —
            # an in-place workloads edit hands us the same generation. The
            # digest compares CONTENT, so a same-length in-place edit (a
            # swapped target) re-triggers like any other spec change; the
            # O(W) hash is noise next to the O(W x B) cascade it gates.
            and digest_ok
        ):
            # already fully observed at this generation: the reconcile we
            # are seeing is our own status-apply echo. Without this gate a
            # finished rebalancer RE-TRIGGERED every listed binding on its
            # echo — a 100k-workload storm wave re-ran the whole
            # reschedule cascade once per echo (188 s measured where the
            # clean wave runs 13 s). The reference requeues on generation
            # change only (workloadrebalancer_controller.go predicates).
            return DONE
        # one (kind, name) -> bindings index per reconcile (the reference
        # resolves each workload through an indexed lister): a 20k-workload
        # rebalancer over 20k bindings was O(W x B) = 400M scans — 330 s of
        # a measured whole-plane storm wave; indexed it is O(W + B)
        by_ref: dict[tuple[str, str], list] = {}
        for rb in self.store.list("ResourceBinding"):
            ref = rb.spec.resource
            by_ref.setdefault((ref.kind, ref.name), []).append(rb)
        observed = []
        # (observed index, rb, pre-bump trigger) — maps rejections back and
        # lets the rollback RESTORE a still-pending earlier trigger (the
        # store hands out live references: zeroing the field would erase a
        # legitimate trigger the scheduler had not yet consumed)
        triggered = []
        for target in rebalancer.spec.workloads:
            result = "NotFound"
            for rb in by_ref.get((target.kind, target.name), ()):
                if (
                    target.namespace
                    and rb.spec.resource.namespace != target.namespace
                ):
                    continue
                prior = rb.spec.reschedule_triggered_at
                rb.spec.reschedule_triggered_at = self.clock()
                rb.meta.generation += 1
                triggered.append((len(observed), rb, prior))
                result = "Successful"
            observed.append(
                {"workload": f"{target.kind}/{target.namespace}/{target.name}",
                 "result": result}
            )
        # one batched store sweep for the whole trigger wave; a per-object
        # admission rejection rolls the in-place bump back and surfaces as
        # Failed on the observed workload (the old per-object apply path
        # raised; swallowing it would report Successful for a binding that
        # will never reschedule)
        by_id = {
            id(rb): (idx, prior) for idx, rb, prior in triggered
        }
        apply_many = getattr(self.store, "apply_many", None)
        if apply_many is not None:
            rejected = apply_many([rb for _, rb, _ in triggered])
            for rb, err in rejected:
                idx, prior = by_id[id(rb)]
                rb.meta.generation -= 1
                rb.spec.reschedule_triggered_at = prior
                observed[idx]["result"] = f"Failed: {err}"
        else:
            for idx, rb, prior in triggered:
                try:
                    self.store.apply(rb)
                except Exception as err:  # noqa: BLE001 — per-object verdict
                    rb.meta.generation -= 1
                    rb.spec.reschedule_triggered_at = prior
                    observed[idx]["result"] = f"Failed: {err}"
        finished = all(o["result"] != "Pending" for o in observed)
        finish_time = rebalancer.status.finish_time
        reprocessed = (
            rebalancer.status.observed_workloads != observed
            or rebalancer.status.observed_generation
            != rebalancer.meta.generation
        )
        if finished and (finish_time is None or reprocessed):
            # a fresh observation wave RESTAMPS the finish: the TTL window
            # (ttlSecondsAfterFinished) must count from the LATEST finish,
            # or a spec update near the deadline would complete its
            # re-trigger and be swept with the new results seconds later
            finish_time = self.clock()
        elif not finished:
            # new unfinished work (e.g. a spec update added workloads) must
            # clear the stamp, or the TTL sweep deletes a pending rebalancer
            finish_time = None
        changed = (
            rebalancer.status.observed_workloads != observed
            or rebalancer.status.observed_generation != rebalancer.meta.generation
            or rebalancer.status.finish_time != finish_time
            or status_digest != spec_digest
        )
        if changed:
            rebalancer.status.observed_workloads = observed
            rebalancer.status.observed_generation = rebalancer.meta.generation
            rebalancer.status.finish_time = finish_time
            rebalancer.status.observed_spec_digest = spec_digest
            self.store.apply(rebalancer)
        return DONE


# --- FederatedResourceQuota --------------------------------------------------


class FederatedResourceQuotaController:
    """Static assignment sync + LIVE usage accounting.

    Per-cluster ResourceQuota slices still ship as Works
    (federatedresourcequota/federated_resource_quota_sync_controller.go),
    but ``status.overall_used`` is now recomputed from bound
    ResourceBindings — the reference's FRQ status controller shape: one
    sweep over the namespace's bindings sums ``assigned replicas x
    per-replica request`` per tracked resource (each replica occupying one
    pod, mirroring the estimator's implicit pods request). The member-
    reported aggregation this replaces double-counted the very workloads
    the plane itself propagated and went stale between member status
    syncs; binding-derived usage moves in the same settle wave as the
    schedule, which is what the scheduler's admission plane keys on.

    Binding events enqueue only the namespaces that actually carry an FRQ
    (a 100k-binding storm in unquota'd namespaces never touches this
    worker), and the batched reconcile computes every dirty FRQ from ONE
    sweep over the binding list."""

    def __init__(self, store: Store, runtime: Runtime, members=None) -> None:
        self.store = store
        self.members = members  # kept for constructor compat (unused)
        self.worker = runtime.new_worker(
            "frq", self._reconcile, reconcile_batch=self._reconcile_batch
        )
        # namespace -> FRQ keys, maintained from watch events so the
        # per-binding-event check is one set lookup
        self._frq_by_ns: dict[str, set[str]] = {}
        for frq in store.list("FederatedResourceQuota"):
            self._frq_by_ns.setdefault(
                frq.meta.namespace, set()
            ).add(frq.meta.namespaced_name)
        store.watch("FederatedResourceQuota", self._on_quota_event)
        store.watch("Cluster", self._on_cluster_event)
        store.watch("ResourceBinding", self._on_binding_event)

    def _on_quota_event(self, event) -> None:
        frq = event.obj
        ns = frq.meta.namespace
        if event.type == "Deleted":
            keys = self._frq_by_ns.get(ns, set())
            keys.discard(frq.meta.namespaced_name)
            if keys:
                # surviving FRQs re-reconcile so the namespace's gauge
                # sweep drops the deleted quota's samples
                for key in keys:
                    self.worker.enqueue(key)
            else:
                # last FRQ of the namespace: retire its gauge samples, or
                # `quota status` reports the dead quota's limits forever
                from ..utils.metrics import quota_limit, quota_used

                quota_limit.remove_matching(namespace=ns)
                quota_used.remove_matching(namespace=ns)
        else:
            self._frq_by_ns.setdefault(ns, set()).add(
                frq.meta.namespaced_name
            )
            self.worker.enqueue(frq.meta.namespaced_name)

    def _on_cluster_event(self, event) -> None:
        for frq in self.store.list("FederatedResourceQuota"):
            self.worker.enqueue(frq.meta.namespaced_name)

    def _on_binding_event(self, event) -> None:
        keys = self._frq_by_ns.get(event.obj.meta.namespace)
        if keys:
            for key in keys:
                self.worker.enqueue(key)

    def _usage_by_namespace(self, namespaces: set) -> dict:
        """One sweep over the binding list: namespace -> {resource: used}
        for the requested namespaces. Delegates to the scheduler plane's
        single usage formula (scheduler.quota.usage_from_bindings) so the
        accounting the status controller writes and the demand math the
        admission kernel charges can never disagree."""
        from ..scheduler.quota import usage_from_bindings

        return usage_from_bindings(self.store, namespaces)

    def _reconcile(self, key: str) -> Optional[str]:
        return self._reconcile_batch([key]).get(key, DONE)

    def _reconcile_batch(self, keys) -> dict:
        out: dict = {}
        live: list = []
        for key in keys:
            frq = self.store.get("FederatedResourceQuota", key)
            out[key] = DONE
            if frq is not None:
                live.append((key, frq))
        if not live:
            return out
        namespaces = {frq.meta.namespace for _, frq in live}
        usage = self._usage_by_namespace(namespaces)
        for key, frq in live:
            self._reconcile_one(frq, usage.get(frq.meta.namespace, {}))
        # gauge exposition is a per-namespace CLEAR-then-SET sweep over
        # every live FRQ: a deleted quota, or a spec edit dropping a
        # resource, retires its stale samples instead of serving them
        # forever
        from ..utils.metrics import quota_limit, quota_used

        for ns in namespaces:
            quota_limit.remove_matching(namespace=ns)
            quota_used.remove_matching(namespace=ns)
            ns_usage = usage.get(ns, {})
            for key in self._frq_by_ns.get(ns, set()):
                frq = self.store.get("FederatedResourceQuota", key)
                if frq is None:
                    continue
                for res, limit in frq.spec.overall.items():
                    quota_limit.set(int(limit), namespace=ns, resource=res)
                    quota_used.set(
                        int(ns_usage.get(res, 0)), namespace=ns, resource=res
                    )
        return out

    def _reconcile_one(self, frq, ns_usage: dict) -> None:
        for assignment in frq.spec.static_assignments:
            cluster = self.store.get("Cluster", assignment.cluster_name)
            if cluster is None:
                continue
            quota = Resource(
                api_version="v1",
                kind="ResourceQuota",
                meta=ObjectMeta(name=frq.meta.name, namespace=frq.meta.namespace),
                spec={"hard": dict(assignment.hard)},
            )
            work_ns = execution_namespace(assignment.cluster_name)
            work_name = f"quota-{frq.meta.namespace}.{frq.meta.name}"
            wkey = f"{work_ns}/{work_name}"
            existing = self.store.get("Work", wkey)
            if existing is None or existing.spec.workload[0].spec != quota.spec:
                self.store.apply(
                    Work(
                        meta=ObjectMeta(name=work_name, namespace=work_ns),
                        spec=WorkSpec(workload=[quota]),
                    )
                )
        # live accounting: only the tracked resources are reported (the
        # reference reports used for spec.overall's resource set)
        overall_used = {
            res: int(ns_usage.get(res, 0)) for res in frq.spec.overall
        }
        changed = False
        if frq.status.overall != frq.spec.overall:
            frq.status.overall = dict(frq.spec.overall)
            changed = True
        if frq.status.overall_used != overall_used:
            frq.status.overall_used = overall_used
            changed = True
        if changed:
            self.store.apply(frq)
