"""Failover machinery: graceful eviction, application failover, descheduler.

Ref:
- graceful-eviction controllers (pkg/controllers/gracefuleviction/
  evictiontask.go:36-150): keep the evicted cluster's workload until the
  replacement is healthy or a timeout passes, then drop the task (the
  binding controller then garbage-collects the Work).
- application-failover controllers (pkg/controllers/applicationfailover/
  rb_application_failover_controller.go:61-165): unhealthy longer than
  TolerationSeconds -> evict the cluster with the policy's PurgeMode and
  state-preservation rules (StatefulFailoverInjection).
- descheduler (pkg/descheduler/descheduler.go:141-241): periodic sweep
  asking estimators for unschedulable replicas, shrinking spec.clusters to
  trigger scale rescheduling.
"""

from __future__ import annotations

import time
from typing import Optional

from ..api.work import (
    EVICTION_REASON_APPLICATION_FAILURE,
    SCHEDULED,
    FULLY_APPLIED,
    ResourceBinding,
    TargetCluster,
)
from ..utils import DONE, Runtime, Store
from ..utils.features import (
    FAILOVER,
    STATEFUL_FAILOVER_INJECTION,
    feature_gate,
)
from .cluster import evict_binding

# default timeout after which an eviction task completes regardless
# (graceful-eviction controller --graceful-eviction-timeout, default 10m)
DEFAULT_EVICTION_TIMEOUT = 600.0


class GracefulEvictionController:
    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        timeout_seconds: float = DEFAULT_EVICTION_TIMEOUT,
        clock=time.time,
    ) -> None:
        self.store = store
        self.timeout = timeout_seconds
        self.clock = clock
        self.worker = runtime.new_worker("graceful-eviction", self._reconcile)
        for kind in ("ResourceBinding", "ClusterResourceBinding"):
            store.watch(kind, lambda e, k=kind: self.worker.enqueue((k, e.key)))
        runtime.add_ticker(self._sweep)

    def _sweep(self) -> None:
        for kind in ("ResourceBinding", "ClusterResourceBinding"):
            for rb in self.store.list(kind):
                if rb.spec.graceful_eviction_tasks:
                    self.worker.enqueue((kind, rb.meta.namespaced_name))

    def _reconcile(self, kind_key) -> Optional[str]:
        kind, key = kind_key
        rb = self.store.get(kind, key)
        if rb is None or not rb.spec.graceful_eviction_tasks:
            return DONE
        keep = []
        changed = False
        for task in rb.spec.graceful_eviction_tasks:
            if self._task_done(rb, task):
                changed = True  # drop the task; binding controller GCs work
            else:
                keep.append(task)
        if changed:
            rb.spec.graceful_eviction_tasks = keep
            self.store.apply(rb)
        return DONE

    def _task_done(self, rb: ResourceBinding, task) -> bool:
        """assessEvictionTasks (evictiontask.go:36-118): done when the new
        schedule result is healthy, or the task timed out, or deletion is
        suppressed-resolved."""
        now = self.clock()
        grace = (
            task.grace_period_seconds
            if task.grace_period_seconds is not None
            else self.timeout
        )
        if task.creation_timestamp and now - task.creation_timestamp > grace:
            return True
        if task.suppress_deletion is not None:
            return not task.suppress_deletion
        # replacement healthy: binding scheduled AND every scheduled cluster
        # reports healthy applied status (evictiontask.go:78-118)
        if not rb.spec.clusters:
            return False
        by_cluster = {i.cluster_name: i for i in rb.status.aggregated_status}
        for tc in rb.spec.clusters:
            item = by_cluster.get(tc.name)
            if item is None or not item.applied or item.health != "Healthy":
                return False
        return True


class ApplicationFailoverController:
    """Unhealthy-too-long applications get evicted and rescheduled."""

    def __init__(self, store: Store, runtime: Runtime, clock=time.time) -> None:
        self.store = store
        self.clock = clock
        # cluster -> first-unhealthy timestamp per binding key
        self._unhealthy_since: dict[tuple[str, str], float] = {}
        self.worker = runtime.new_worker("app-failover", self._reconcile)
        for kind in ("ResourceBinding", "ClusterResourceBinding"):
            store.watch(kind, lambda e, k=kind: self.worker.enqueue((k, e.key)))
        runtime.add_ticker(self._sweep)

    def _sweep(self) -> None:
        for kind in ("ResourceBinding", "ClusterResourceBinding"):
            for rb in self.store.list(kind):
                if rb.spec.failover is not None:
                    self.worker.enqueue((kind, rb.meta.namespaced_name))

    def _reconcile(self, kind_key) -> Optional[str]:
        kind, key = kind_key
        rb = self.store.get(kind, key)
        if rb is None or rb.spec.failover is None:
            return DONE
        app = getattr(rb.spec.failover, "application", None)
        if app is None:
            return DONE
        now = self.clock()
        toleration = app.decision_conditions_toleration_seconds
        changed = False
        for item in rb.status.aggregated_status:
            k = (key, item.cluster_name)
            if item.health == "Unhealthy":
                since = self._unhealthy_since.setdefault(k, now)
                if now - since >= toleration and any(
                    tc.name == item.cluster_name for tc in rb.spec.clusters
                ):
                    preserved = self._preserve_state(rb, item)
                    evict_binding(
                        rb,
                        item.cluster_name,
                        reason=EVICTION_REASON_APPLICATION_FAILURE,
                        producer="ResourceBindingApplicationFailover",
                        message="application unhealthy beyond toleration",
                        purge_mode=app.purge_mode,
                        grace_period_seconds=app.grace_period_seconds,
                        preserved_label_state=preserved,
                        now=now,
                    )
                    changed = True
                    self._unhealthy_since.pop(k, None)
            else:
                self._unhealthy_since.pop(k, None)
        if changed:
            self.store.apply(rb)
        return DONE

    def _preserve_state(self, rb: ResourceBinding, item) -> dict:
        """StatePreservation JSONPath extraction re-injected as labels on the
        replacement cluster (StatefulFailoverInjection,
        binding/common.go:117-121,153-176)."""
        app = rb.spec.failover.application
        if (
            not feature_gate.enabled(STATEFUL_FAILOVER_INJECTION)
            or not app.state_preservation
            or item.status is None
        ):
            return {}
        out = {}
        for name, path in app.state_preservation.items():
            value = item.status
            for part in path.strip(".").split("."):
                if isinstance(value, dict) and part in value:
                    value = value[part]
                else:
                    value = None
                    break
            if value is not None:
                out[name] = str(value)
        return out


class Descheduler:
    """Periodic unschedulable-replica reclaim (pkg/descheduler)."""

    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        members,
        clock=None,
    ) -> None:
        import time as _time

        self.store = store
        self.members = members
        self.clock = clock or _time.time
        #: addon on/off switch — the ticker registration is permanent
        #: (Runtime has no removal), so disable must gate the pass itself
        self.active = True
        runtime.add_ticker(self.deschedule_once)

    def deschedule_once(self) -> None:
        """descheduleOnce (descheduler.go:162-206): for every binding, ask
        each target cluster's estimator for unschedulable replicas and shrink
        the schedule result accordingly (floor at 0); the scheduler then
        scale-reschedules the delta elsewhere."""
        if not self.active:
            return
        # GetUnschedulableReplicas inputs: pod-condition derived counts
        # (PodScheduled=False/Unschedulable past the threshold) merged with
        # simulation overrides — memoized per member per pass, computed
        # lazily on first reference so a tick with no bindings (or bindings
        # touching few clusters) never pays a fleet-wide pod scan.
        now = self.clock()
        counts: dict[str, dict[str, int]] = {}

        def member_counts(name: str) -> dict[str, int]:
            got = counts.get(name)
            if got is None:
                member = self.members.get(name)
                got = (
                    member.count_unschedulable(now)
                    if member is not None and member.reachable
                    else {}
                )
                counts[name] = got
            return got

        for kind in ("ResourceBinding", "ClusterResourceBinding"):
          for rb in self.store.list(kind):
            if rb.spec.replicas <= 0 or not rb.spec.clusters:
                continue
            workload_key = rb.spec.resource.namespaced_key
            new_targets = []
            changed = False
            for tc in rb.spec.clusters:
                unschedulable = member_counts(tc.name).get(workload_key, 0)
                if unschedulable > 0:
                    reduced = max(tc.replicas - unschedulable, 0)
                    changed = True
                    if reduced > 0:
                        new_targets.append(
                            TargetCluster(name=tc.name, replicas=reduced)
                        )
                else:
                    new_targets.append(tc)
            if changed:
                rb.spec.clusters = new_targets
                rb.meta.generation += 1  # triggers scale rescheduling
                self.store.apply(rb)
