"""Control-plane reconcilers (ref: pkg/controllers, pkg/detector,
pkg/descheduler)."""

from .cluster import (  # noqa: F401
    ClusterController,
    ClusterStatusController,
    TaintManager,
    evict_binding,
)
from .dependencies import DependenciesDistributor  # noqa: F401
from .detector import ResourceDetector, binding_name  # noqa: F401
from .extras import (  # noqa: F401
    FederatedResourceQuotaController,
    NamespaceSyncController,
    WorkloadRebalancer,
    WorkloadRebalancerController,
    WorkloadRebalancerSpec,
    ObjectReferenceSelector,
)
from .failover import (  # noqa: F401
    ApplicationFailoverController,
    Descheduler,
    GracefulEvictionController,
)
from .overridemanager import OverrideManager  # noqa: F401
from .propagation import (  # noqa: F401
    BindingController,
    BindingStatusController,
    ExecutionController,
    WorkStatusController,
    execution_namespace,
)
from .scheduler_controller import SchedulerController  # noqa: F401
