"""FederatedHPA + CronFederatedHPA controllers and the metrics path.

Ref:
- FederatedHPA (pkg/controllers/federatedhpa/, 2,402 LoC): the kube HPA loop
  ported to multi-cluster — target the binding's clusters, pull pod metrics
  through the karmada-metrics-adapter, calibrate by ready-pod ratio, apply
  the stabilization window, write the scale subresource on the template
  (federatedhpa_controller.go:406-467, replica_calculator.go, :921-960).
- CronFederatedHPA (pkg/controllers/cronfederatedhpa/, gocron): cron rules
  scale a FederatedHPA's bounds or a workload's replicas directly.

Metrics transport: member clusters expose per-workload utilization samples
(MemberCluster.pod_metrics, the stand-in for metrics.k8s.io served by the
karmada-metrics-adapter — see karmada_tpu.metricsadapter); the replica
calculator merges them across the binding's clusters weighted by pod count.
"""

from __future__ import annotations

import math
import time
from typing import Optional

from ..api.autoscaling import CronFederatedHPA, FederatedHPA
from ..utils import DONE, Runtime, Store
from ..utils.cron import cron_matches
from .detector import binding_name


def _resource_plural(kind: str) -> str:
    """Kube-style lowercase plural resource name for a kind (the custom
    metrics API keys series by resource, e.g. Ingress -> ingresses)."""
    k = kind.lower()
    if not k:
        return k
    if k.endswith(("s", "x", "z", "ch", "sh")):
        return k + "es"
    if k.endswith("y") and k[-2:-1] not in "aeiou":
        return k[:-1] + "ies"
    return k + "s"


class FederatedHPAController:
    def __init__(
        self, store: Store, runtime: Runtime, members, clock=time.time
    ) -> None:
        self.store = store
        self.members = members
        self.clock = clock
        # scale-down stabilization: (hpa key) -> [(t, recommendation)]
        self._recommendations: dict[str, list[tuple[float, int]]] = {}
        # kube HPA sync period: evaluations are at least this far apart, so
        # stale metric samples cannot compound within one settle pass
        self.sync_period_seconds = 15.0
        self._last_eval: dict[str, float] = {}
        self.worker = runtime.new_worker("federated-hpa", self._reconcile)
        store.watch("FederatedHPA", lambda e: self.worker.enqueue(e.key))
        runtime.add_ticker(self._sweep)
        self._metrics_adapter = None

    def _adapter(self):
        """Lazy metrics-adapter facade (custom/external metric flavors)."""
        if self._metrics_adapter is None:
            from ..metricsadapter import MetricsAdapter

            self._metrics_adapter = MetricsAdapter(self.members)
        return self._metrics_adapter

    def _sweep(self) -> None:
        for hpa in self.store.list("FederatedHPA"):
            self.worker.enqueue(hpa.meta.namespaced_name)

    # -- metric collection (metrics-adapter fan-out analogue) --------------

    def _collect(self, hpa: FederatedHPA, clusters: list[str]) -> Optional[tuple[float, int, int]]:
        """Returns (avg_utilization_pct, ready_pods, total_pods) merged
        across the target clusters, or None when no samples exist."""
        target = hpa.spec.scale_target_ref
        workload_key = (
            f"{hpa.meta.namespace}/{target.name}"
            if hpa.meta.namespace
            else target.name
        )
        total_util = 0.0
        total_pods = 0
        ready = 0
        for name in clusters:
            member = self.members.get(name)
            if member is None or not member.reachable:
                continue
            sample = member.pod_metrics.get(workload_key)
            if not sample:
                continue
            pods = int(sample.get("pods", 0))
            total_util += float(sample.get("cpu_utilization", 0.0)) * pods
            total_pods += pods
            ready += int(sample.get("ready_pods", pods))
        if total_pods == 0:
            return None
        return total_util / total_pods, ready, total_pods

    def _pod_list(
        self, hpa: FederatedHPA, clusters: list[str]
    ) -> tuple[list, bool]:
        """The federated podList (federatedhpa_controller.go:540 — member
        pod informers merged): each member's per-pod samples for the target
        workload, as PodSample records. Also reports whether EVERY reachable
        target cluster published per-pod data — a partial list must not
        silently stand in for the federation (a member still on aggregate
        samples would have its load ignored)."""
        from .replica_calculator import PodSample

        target = hpa.spec.scale_target_ref
        workload_key = (
            f"{hpa.meta.namespace}/{target.name}"
            if hpa.meta.namespace
            else target.name
        )
        pods = []
        complete = False
        for name in clusters:
            member = self.members.get(name)
            if member is None or not member.reachable:
                continue
            samples = member.workload_pods.get(workload_key)
            if samples is None:
                # a reachable target cluster without per-pod data: the
                # federated list would be partial — callers fall back to
                # the aggregate path
                return [], False
            complete = True
            for d in samples:
                pods.append(PodSample(cluster=name, **d))
        return pods, complete and bool(pods)

    # -- reconcile ---------------------------------------------------------

    def _reconcile(self, key: str) -> Optional[str]:
        hpa = self.store.get("FederatedHPA", key)
        if hpa is None:
            self._recommendations.pop(key, None)
            return DONE
        target = hpa.spec.scale_target_ref
        template_key = (
            f"{hpa.meta.namespace}/{target.name}" if hpa.meta.namespace else target.name
        )
        template = self.store.get("Resource", template_key)
        if template is None or template.kind != target.kind:
            return DONE
        rb_key = (
            f"{hpa.meta.namespace}/{binding_name(template)}"
            if hpa.meta.namespace
            else binding_name(template)
        )
        rb = self.store.get("ResourceBinding", rb_key)
        clusters = [tc.name for tc in rb.spec.clusters] if rb is not None else []
        current = int(template.spec.get("replicas", 0))
        now = self.clock()
        last = self._last_eval.get(key)
        if last is not None and now - last < self.sync_period_seconds:
            return DONE
        metrics = self._collect(hpa, clusters)
        if current == 0:
            self._update_status(hpa, current, current)
            return DONE

        # desired = max over metrics of each flavor's calculator proposal
        # (replica_calculator.go:62-314 via controllers.replica_calculator);
        # no computable metric keeps the current size. Per-pod sets come
        # from the members' workload_pods (the federated podList); workloads
        # without per-pod detail fall back to the aggregate utilization
        # sample. An uncomputable metric (MetricsError) is skipped like the
        # reference's invalid-metric tally.
        from .replica_calculator import (
            MetricsError, PodSample, ReplicaCalculator,
        )

        calc = ReplicaCalculator()
        pods, pods_complete = self._pod_list(hpa, clusters)
        # calibration = materialized replicas / template replicas
        # (federatedhpa_controller.go:601 — member scale specs vs template)
        assigned = (
            sum(int(tc.replicas or 0) for tc in rb.spec.clusters)
            if rb is not None
            else 0
        )
        calibration = assigned / current if assigned and current else 1.0

        def _milli(v: float) -> int:
            return max(1, int(round(float(v) * 1000)))

        proposals = []
        for metric in hpa.spec.metrics or []:
            mtype = getattr(metric, "type", "Resource") or "Resource"
            try:
                if mtype == "Resource" and metric.target_average_utilization:
                    done = False
                    if pods_complete:
                        try:
                            n, _, _ = calc.get_resource_replicas(
                                current, metric.target_average_utilization,
                                metric.resource_name or "cpu", pods,
                                calibration,
                            )
                            proposals.append(n)
                            done = True
                        except MetricsError:
                            # per-pod data uncomputable (e.g. missing
                            # requests): the aggregate sample still drives
                            # scaling rather than freezing it
                            done = False
                    if not done and metrics is not None:
                        # aggregate fallback (no complete per-pod detail):
                        # ready-ratio calibration over the merged sample
                        avg_util, ready, total = metrics
                        agg_cal = ready / total if total else 1.0
                        raw = current * (
                            avg_util / metric.target_average_utilization
                        )
                        proposals.append(math.ceil(raw * agg_cal))
                elif mtype == "Resource" and metric.target_average_value:
                    if pods_complete:
                        n, _ = calc.get_raw_resource_replicas(
                            current, _milli(metric.target_average_value),
                            metric.resource_name or "cpu", pods, calibration,
                        )
                        proposals.append(n)
                elif mtype == "Pods" and metric.target_average_value:
                    # custom per-pod metric (custom.metrics.k8s.io): the
                    # sample set joins the federated pod list so missing/
                    # unready pods get the reference's backfill treatment
                    samples = [
                        s
                        for s in self._adapter().custom.get_metric_by_selector(
                            "pods",
                            hpa.meta.namespace,
                            metric.metric_name,
                            metric_selector=metric.metric_selector,
                        )
                        if s.cluster in clusters
                    ]
                    if not samples:
                        continue
                    msamples = {
                        s.object_name: _milli(s.value) for s in samples
                    }
                    plist = pods if pods_complete else [
                        PodSample(name=s.object_name, cluster=s.cluster)
                        for s in samples
                    ]
                    n, _ = calc.get_metric_replicas(
                        current, _milli(metric.target_average_value),
                        msamples, plist, calibration,
                    )
                    proposals.append(n)
                elif mtype == "Object" and (
                    metric.target_value or metric.target_average_value
                ):
                    obj = metric.described_object
                    if obj is None:
                        continue
                    samples = [
                        s
                        for s in self._adapter().custom.get_metric_by_name(
                            _resource_plural(obj.kind or ""),
                            hpa.meta.namespace,
                            obj.name,
                            metric.metric_name,
                            metric_selector=metric.metric_selector,
                        )
                        if s.cluster in clusters
                    ]
                    if not samples:
                        continue
                    usage = sum(_milli(s.value) for s in samples)
                    if metric.target_value:
                        n, _ = calc.get_object_metric_replicas(
                            current, _milli(metric.target_value), usage,
                            pods if pods_complete else [
                                PodSample(name=f"p{i}")
                                for i in range(max(current, 1))
                            ],
                            calibration,
                        )
                    else:
                        status_replicas = (
                            len(pods) if pods_complete else current
                        )
                        n, _ = calc.get_object_per_pod_metric_replicas(
                            max(status_replicas, 1),
                            _milli(metric.target_average_value), usage,
                            calibration,
                        )
                    proposals.append(n)
                elif mtype == "External":
                    samples = self._adapter().external.get_external_metric(
                        hpa.meta.namespace,
                        metric.metric_name,
                        selector=metric.metric_selector,
                    )
                    if not samples:
                        continue
                    usage = sum(s.value for s in samples)
                    if metric.target_value:
                        proposals.append(
                            math.ceil(usage / metric.target_value)
                        )
                    elif metric.target_average_value:
                        # GetExternalPerPodMetricReplicas: per-pod average
                        proposals.append(
                            math.ceil(usage / metric.target_average_value)
                        )
            except MetricsError:
                # reference: tally as invalid metric and keep going — the
                # remaining metrics still drive scaling
                continue
        if not proposals and metrics is None and not pods_complete:
            self._update_status(hpa, current, current)
            return DONE
        self._last_eval[key] = now
        desired = max(proposals) if proposals else current
        desired = min(max(desired, hpa.spec.min_replicas), hpa.spec.max_replicas)

        # scale-down stabilization: act on the max recommendation inside the
        # window (federatedhpa_controller.go:921-960); the first evaluation
        # seeds the window with the current size for continuity
        window = hpa.spec.stabilization_window_seconds
        prior = self._recommendations.get(key)
        if prior is None:
            prior = [(now, current)]
        recs = [(t, r) for t, r in prior if now - t <= window]
        recs.append((now, desired))
        self._recommendations[key] = recs
        if desired < current:
            desired = max(r for _, r in recs)

        if desired != current:
            template.spec["replicas"] = desired
            self.store.apply(template)  # detector re-derives binding replicas
            hpa.status.last_scale_time = now
        self._update_status(hpa, current, desired)
        return DONE

    def _update_status(self, hpa: FederatedHPA, current: int, desired: int) -> None:
        if (
            hpa.status.current_replicas != current
            or hpa.status.desired_replicas != desired
        ):
            hpa.status.current_replicas = current
            hpa.status.desired_replicas = desired
            self.store.apply(hpa)


class CronFederatedHPAController:
    """Cron-driven scaling (pkg/controllers/cronfederatedhpa/). Each tick,
    rules whose schedule matches the current minute fire once."""

    def __init__(self, store: Store, runtime: Runtime, clock=time.time) -> None:
        self.store = store
        self.clock = clock
        self._last_fired: dict[tuple[str, str], int] = {}  # (key, rule) -> minute
        runtime.add_ticker(self.tick)

    def tick(self) -> None:
        now = self.clock()
        minute = int(now // 60)
        for cron_hpa in self.store.list("CronFederatedHPA"):
            for rule in cron_hpa.spec.rules:
                if rule.suspend:
                    continue
                k = (cron_hpa.meta.namespaced_name, rule.name)
                if self._last_fired.get(k) == minute:
                    continue
                if not cron_matches(rule.schedule, now):
                    continue
                self._last_fired[k] = minute
                self._fire(cron_hpa, rule, now)

    def _fire(self, cron_hpa: CronFederatedHPA, rule, now: float) -> None:
        from ..api.autoscaling import ExecutionHistoryItem

        target = cron_hpa.spec.scale_target_ref
        applied = None
        message = ""
        if target.kind == "FederatedHPA":
            key = (
                f"{cron_hpa.meta.namespace}/{target.name}"
                if cron_hpa.meta.namespace
                else target.name
            )
            hpa = self.store.get("FederatedHPA", key)
            if hpa is None:
                message = "target FederatedHPA not found"
            else:
                if rule.target_min_replicas is not None:
                    hpa.spec.min_replicas = rule.target_min_replicas
                if rule.target_max_replicas is not None:
                    hpa.spec.max_replicas = rule.target_max_replicas
                self.store.apply(hpa)
                applied = rule.target_min_replicas
        else:
            key = (
                f"{cron_hpa.meta.namespace}/{target.name}"
                if cron_hpa.meta.namespace
                else target.name
            )
            template = self.store.get("Resource", key)
            if template is None or rule.target_replicas is None:
                message = "target workload not found"
            else:
                template.spec["replicas"] = rule.target_replicas
                self.store.apply(template)
                applied = rule.target_replicas
        cron_hpa.status.execution_histories.append(
            ExecutionHistoryItem(
                rule_name=rule.name,
                execution_time=now,
                applied_replicas=applied,
                message=message,
            )
        )
        self.store.apply(cron_hpa)
