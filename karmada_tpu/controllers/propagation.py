"""Propagation controllers: binding -> Work -> member cluster -> status back.

Ref:
- binding-controller (pkg/controllers/binding/): ensureWork — ReviseReplica
  for divided placements, override application, suspend/preserve flags,
  orphan-Work cleanup (binding_controller.go:70-165, common.go:43-143).
- execution-controller (pkg/controllers/execution/): Work -> member apply /
  delete via objectwatcher, Applied condition.
- work-status-controller (pkg/controllers/status/work_status_controller.go):
  per-member informers reflect member object status+health into
  Work.Status.ManifestStatuses; recreates deleted-but-desired objects.
- binding-status controllers (status/rb_status_controller.go): aggregate
  manifest statuses into ResourceBinding.Status.AggregatedStatus via the
  interpreter, then the detector writes template status.
"""

from __future__ import annotations

from ..utils.clone import clone_resource
import math
from typing import Optional

from ..api.core import Condition, ObjectMeta, Resource, set_condition
from ..api.work import (
    FULLY_APPLIED,
    WORK_APPLIED,
    AggregatedStatusItem,
    ManifestStatus,
    ResourceBinding,
    Work,
    WorkSpec,
)
from ..api.policy import DIVIDED
from ..interpreter import ResourceInterpreter
from ..utils import DONE, REQUEUE, Runtime, Store
from ..utils.metrics import works_rendered
from ..utils.member import (
    ConflictError,
    MemberClientRegistry,
    MemberEvent,
    ObjectWatcher,
    UnreachableError,
)
from .overridemanager import OverrideManager

ES_PREFIX = "karmada-es-"
WORK_BINDING_LABEL = "resourcebinding.karmada.io/key"  # value: "<kind>:<key>"

BINDING_KINDS = ("ResourceBinding", "ClusterResourceBinding")


def binding_ref(kind: str, key: str) -> str:
    return f"{kind}:{key}"


def execution_namespace(cluster: str) -> str:
    return f"{ES_PREFIX}{cluster}"


def cluster_of_execution_namespace(ns: str) -> Optional[str]:
    return ns[len(ES_PREFIX):] if ns.startswith(ES_PREFIX) else None


def _work_signature(work: Work):
    w = work.spec.workload[0] if work.spec.workload else None
    return (
        w.spec if w else None,
        w.meta.labels if w else None,
        work.spec.suspend_dispatching,
        work.spec.preserve_resources_on_deletion,
    )


class WorkIndex:
    """Incremental indexes over Work objects, maintained from watch events
    (the informer-indexer analogue). Kills the O(bindings x works) scans
    the binding/status controllers would otherwise pay per reconcile:
    - by binding label (orphan cleanup, status aggregation)
    - by propagated target (cluster, gvk, namespace, name) for member-event
      routing in the work-status controller."""

    def __init__(self, store: Store) -> None:
        self.store = store
        self._by_binding: dict[str, set[str]] = {}
        self._by_target: dict[tuple, str] = {}
        self._work_meta: dict[str, tuple] = {}  # work key -> (ref, targets)
        # watch(replay=True) synthesizes Added for Works already in the store,
        # so the index seeds correctly against a populated store.
        store.watch("Work", self._on_event)

    def _on_event(self, event) -> None:
        key = event.key
        old_ref, old_targets = self._work_meta.pop(key, (None, ()))
        if old_ref is not None:
            self._by_binding.get(old_ref, set()).discard(key)
        for t in old_targets:
            if self._by_target.get(t) == key:
                del self._by_target[t]
        if event.type == "Deleted":
            return
        work = event.obj
        ref = work.meta.labels.get(WORK_BINDING_LABEL)
        cluster = cluster_of_execution_namespace(work.meta.namespace)
        targets = (
            tuple(
                (cluster, f"{w.api_version}/{w.kind}",
                 w.meta.namespace, w.meta.name)
                for w in work.spec.workload
            )
            if cluster is not None
            else ()
        )
        if ref:
            self._by_binding.setdefault(ref, set()).add(key)
        for t in targets:
            self._by_target[t] = key
        self._work_meta[key] = (ref, targets)

    def works_for(self, binding_ref: str) -> list:
        out = []
        for key in sorted(self._by_binding.get(binding_ref, ())):
            work = self.store.get("Work", key)
            if work is not None:
                out.append(work)
        return out

    def work_for_target(self, cluster: str, gvk: str, namespace: str, name: str):
        key = self._by_target.get((cluster, gvk, namespace, name))
        return self.store.get("Work", key) if key else None


class BindingController:
    """ResourceBinding -> per-target-cluster Work objects."""

    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        interpreter: ResourceInterpreter,
        work_index: Optional[WorkIndex] = None,
    ) -> None:
        self.store = store
        self.interpreter = interpreter
        self.work_index = work_index or WorkIndex(store)
        self.overrides = OverrideManager(store)
        # binding ref -> (global fingerprint, {cluster: (replicas,
        # cluster_token)}) of the last ensureWork pass: an incremental storm
        # (scale +1) changes one target's count, so only that Work is
        # rebuilt instead of revising/overriding/cloning the template once
        # per target per reconcile. cluster_token covers the live cluster
        # fields override rules match on (labels/provider/region/zone).
        # Keyed on template (uid, generation) — the plane's spec-change
        # discipline (the scheduler gate relies on generation the same way).
        self._built: dict[str, tuple] = {}
        # (template uid, replica-exclusion flag) -> ((generation,
        # resource_version), content hash): a scale storm bumps every
        # template's generation while changing only the replica fields the
        # per-target revise overwrites anyway, so generation alone would
        # void the build cache fleet-wide each wave
        self._template_hashes: dict[tuple, tuple] = {}
        # Works this controller deleted itself (orphan cleanup): their
        # Deleted events must not void the freshly written cache entry
        self._own_deletes: set[str] = set()
        self.worker = runtime.new_worker("binding", self._reconcile)
        for kind in BINDING_KINDS:
            store.watch(
                kind, lambda e, k=kind: self.worker.enqueue((k, e.key))
            )
        store.watch("OverridePolicy", self._requeue_all)
        store.watch("ClusterOverridePolicy", self._requeue_all)
        # interpreter customizations change revise/retain semantics: the
        # cached build fingerprints are meaningless across such a change
        store.watch(
            "ResourceInterpreterCustomization", self._requeue_all,
            replay=False,
        )
        store.watch("Work", self._on_work_event, replay=False)
        # override rules match live cluster state: a label / topology edit
        # must requeue the bindings whose Works were built against the old
        # state (status heartbeats leave the token unchanged and are cheap)
        store.watch("Cluster", self._on_cluster_event, replay=False)
        self._cluster_tokens: dict[str, tuple] = {}

    @staticmethod
    def _cluster_token(cluster) -> Optional[tuple]:
        """The live cluster fields override rules can match on
        (ClusterAffinity: name/labels, FieldSelector: provider/region/zone).
        Both the build cache and the Cluster watch compare THIS tuple — keep
        them in lockstep via this single constructor."""
        if cluster is None:
            return None
        return (
            tuple(sorted(cluster.meta.labels.items())),
            cluster.spec.provider,
            cluster.spec.region,
            cluster.spec.zone,
        )

    _UNSEEDED = object()

    def _lookup_cluster_token(self, name: str) -> Optional[tuple]:
        """Cached token for cache-hit targets: the Cluster watch keeps the
        map current (synchronous delivery on the applying thread), so
        steady-storm reconciles pay one dict get per target instead of a
        store fetch + label sort. Lazily seeded from the store for clusters
        that have produced no event since startup."""
        tok = self._cluster_tokens.get(name, self._UNSEEDED)
        if tok is self._UNSEEDED:
            tok = self._cluster_token(self.store.get("Cluster", name))
            self._cluster_tokens[name] = tok
        return tok

    def _on_cluster_event(self, event) -> None:
        name = event.key
        if event.type == "Deleted":
            # tombstone (not pop): the post-build race check must see the
            # deletion, and a later re-join overwrites it
            self._cluster_tokens[name] = None
            token = None
        else:
            token = self._cluster_token(event.obj)
            if self._cluster_tokens.get(name) == token:
                return  # status-only change: override matching unaffected
            self._cluster_tokens[name] = token
        for ref, (_fp, built_targets) in list(self._built.items()):
            entry = built_targets.get(name)
            if entry is not None and entry[1] != token:
                kind, _, key = ref.partition(":")
                self.worker.enqueue((kind, key))

    def _on_work_event(self, event) -> None:
        # an externally deleted Work must be rebuilt even though the build
        # cache says nothing changed
        if event.type != "Deleted":
            return
        if event.key in self._own_deletes:
            self._own_deletes.discard(event.key)
            return
        ref = event.obj.meta.labels.get(WORK_BINDING_LABEL)
        if ref and self._built.pop(ref, None) is not None:
            kind, _, key = ref.partition(":")
            self.worker.enqueue((kind, key))

    def _requeue_all(self, _event) -> None:
        self._built.clear()  # override policies changed: full rebuild
        for kind in BINDING_KINDS:
            for rb in self.store.list(kind):
                self.worker.enqueue((kind, rb.meta.namespaced_name))

    def _reconcile(self, kind_key) -> Optional[str]:
        kind, key = kind_key
        ref = binding_ref(kind, key)
        rb = self.store.get(kind, key)
        if rb is None:
            self._built.pop(ref, None)
            self._cleanup_works(ref, keep_clusters=set())
            return DONE
        template = self.store.get("Resource", rb.spec.resource.namespaced_key)
        if template is None:
            self._built.pop(ref, None)
            return DONE
        # target set: scheduled clusters + clusters still draining eviction
        # tasks (their Works must survive until eviction completes,
        # binding_controller.go:145-165)
        targets = {tc.name: tc.replicas for tc in rb.spec.clusters}
        evicting = {t.from_cluster for t in rb.spec.graceful_eviction_tasks}
        # RequiredBy snapshots extend the target set: dependencies follow
        # their dependers (binding/common.go mergeTargetClusters)
        for snap in rb.spec.required_by:
            for tc in snap.clusters:
                targets.setdefault(tc.name, 0)
        divided = (
            rb.spec.placement is not None
            and rb.spec.placement.replica_scheduling_type() == DIVIDED
        )
        fp_global = (
            template.meta.uid,
            self._template_token(template, divided),
            divided,
            # the binding's TOTAL replicas only shape a target's manifest
            # through the Job completions split; for every other kind the
            # manifest depends on the per-target count alone, and a scale
            # storm must not void every target's cache entry
            rb.spec.replicas
            if (template.kind == "Job" and "completions" in template.spec)
            else 0,
            rb.spec.suspend_dispatching,
            tuple(sorted(rb.spec.suspend_dispatching_on_clusters or ())),
            rb.spec.preserve_resources_on_deletion,
            rb.spec.conflict_resolution,
        )
        prev_global, prev_targets = self._built.get(ref, (None, None))
        unchanged = prev_global == fp_global and prev_targets is not None
        built_targets: dict[str, tuple] = {}
        for cluster_name, replicas in targets.items():
            # apply_overrides matches rules against LIVE cluster state
            # (name / labels / provider / region / zone), so the per-target
            # cache entry carries a token over those fields: a cluster label
            # edit that flips an override rule's match rebuilds exactly the
            # Works on that cluster instead of going stale forever
            cluster_token = self._lookup_cluster_token(cluster_name)
            if unchanged and prev_targets.get(cluster_name) == (
                replicas,
                cluster_token,
            ):
                built_targets[cluster_name] = (replicas, cluster_token)
                continue  # this target's Work is already up to date
            # every transform below (revise_replica, apply_overrides)
            # returns a fresh object, so the template is cloned lazily:
            # exactly ONE copy per Work, never three (the redundant
            # deepcopy chain dominated propagation-storm wall time)
            workload = template
            if divided and rb.spec.replicas > 0:
                workload = self.interpreter.revise_replica(workload, replicas)
                if workload is template:
                    workload = clone_resource(template)
                # Job completions division (binding/common.go:287-299)
                if workload.kind == "Job" and "completions" in workload.spec:
                    total = int(workload.spec["completions"])
                    workload.spec["completions"] = math.ceil(
                        total * replicas / max(rb.spec.replicas, 1)
                    )
            # rebuild path: fetch the live object and stamp the token of the
            # state the Work is ACTUALLY built against
            cluster_obj = self.store.get("Cluster", cluster_name)
            built_targets[cluster_name] = (
                replicas, self._cluster_token(cluster_obj),
            )
            if cluster_obj is not None:
                workload = self.overrides.apply_overrides(workload, cluster_obj)
            if workload is template:
                workload = clone_resource(template)
            self._create_or_update_work(rb, kind, cluster_name, workload)
        self._cleanup_works(ref, keep_clusters=set(targets) | evicting)
        self._built[ref] = (fp_global, built_targets)
        # close the build/event race: a Cluster event landing mid-build found
        # no _built entry to requeue against, and this reconcile may have
        # built against the pre-event object — re-check the freshly written
        # tokens against the watch-maintained map and requeue on divergence
        for name, (_reps, tok) in built_targets.items():
            cur = self._cluster_tokens.get(name, self._UNSEEDED)
            if cur is not self._UNSEEDED and cur != tok:
                self.worker.enqueue((kind, key))
                break
        return DONE

    # replica fields the per-target ReviseReplica pass overwrites; a
    # template change confined to them cannot alter an unchanged target's
    # manifest (its value is re-derived from the binding's division)
    _REPLICA_FIELDS = ("replicas", "parallelism", "completions")

    def _template_token(self, template: Resource, divided: bool) -> int:
        """Build-cache content token for the template. A hash over the
        manifest-shaping fields (spec + labels + annotations) rather than
        the generation: metadata-only edits don't bump generation, and
        resource_version bumps on status-only writes — neither is a valid
        cache key alone. For divided bindings whose kind has no custom
        ReviseReplica hook the top-level replica fields are excluded, so a
        fleet-wide scale storm (only replica counts change) keeps unchanged
        targets cached; custom-revise kinds hash the full spec (their hooks
        may derive arbitrary fields from the template's replica count)."""
        gvk = f"{template.api_version}/{template.kind}"
        exclude = divided and not self.interpreter.has_custom_revise(gvk)
        key = (template.meta.uid, exclude)
        ver = (template.meta.generation, template.meta.resource_version)
        cached = self._template_hashes.get(key)
        if cached is not None and cached[0] == ver:
            return cached[1]
        spec_view = (
            {
                k: v
                for k, v in template.spec.items()
                if k not in self._REPLICA_FIELDS
            }
            if exclude
            else template.spec
        )
        token = hash(
            (
                repr(spec_view),
                repr(sorted(template.meta.labels.items())),
                repr(sorted(template.meta.annotations.items())),
            )
        )
        self._template_hashes[key] = (ver, token)
        return token

    def _create_or_update_work(
        self, rb: ResourceBinding, kind: str, cluster: str, workload: Resource
    ) -> None:
        ns = execution_namespace(cluster)
        name = f"{rb.meta.namespace + '.' if rb.meta.namespace else ''}{rb.meta.name}"
        key = f"{ns}/{name}"
        # per-target suspension: global flag OR the cluster is listed in
        # DispatchingOnClusters (binding/common.go:305-318)
        suspended = rb.spec.suspend_dispatching or (
            cluster in (rb.spec.suspend_dispatching_on_clusters or ())
        )
        existing = self.store.get("Work", key)
        if existing is not None and _work_signature(existing) == (
            workload.spec,
            workload.meta.labels,
            suspended,
            rb.spec.preserve_resources_on_deletion,
        ):
            return  # no semantic change — avoid churn (idempotent reconcile)
        work = existing or Work(meta=ObjectMeta(name=name, namespace=ns))
        work.meta.labels[WORK_BINDING_LABEL] = binding_ref(
            kind, rb.meta.namespaced_name
        )
        work.spec = WorkSpec(
            workload=[workload],
            suspend_dispatching=suspended,
            preserve_resources_on_deletion=rb.spec.preserve_resources_on_deletion,
            conflict_resolution=rb.spec.conflict_resolution,
        )
        self.store.apply(work)
        # only SEMANTIC creates/updates count (the signature gate above
        # returned on no-ops): this is the work-render throughput the
        # whole-plane storm tier measures (ROADMAP item 3)
        works_rendered.inc()

    def _cleanup_works(self, binding_key: str, keep_clusters: set[str]) -> None:
        for work in self.work_index.works_for(binding_key):
            cluster = cluster_of_execution_namespace(work.meta.namespace)
            if cluster not in keep_clusters:
                self._own_deletes.add(work.meta.namespaced_name)
                self.store.delete("Work", work.meta.namespaced_name)


class ExecutionController:
    """Work -> member cluster apply/delete (pkg/controllers/execution/)."""

    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        members: MemberClientRegistry,
        interpreter: ResourceInterpreter,
    ) -> None:
        self.store = store
        self.members = members
        self.watcher = ObjectWatcher(members, interpreter)
        # deletes parked while a cluster is unreachable; retried when the
        # cluster comes back (the asynchronous-retry analogue — burning
        # requeue budget against a dead cluster helps nobody)
        self._pending_deletes: dict[str, set[tuple[str, str, str]]] = {}
        self.worker = runtime.new_worker("execution", self._reconcile)
        store.watch("Work", self._on_work_event)
        store.watch("Cluster", self._on_cluster_event)

    def _on_cluster_event(self, event) -> None:
        pending = self._pending_deletes.pop(event.key, None)
        if pending:
            self.worker.enqueue(("delete", event.key, tuple(sorted(pending))))

    def _on_work_event(self, event) -> None:
        if event.type == "Deleted":
            # the Work is gone from the store; carry what we need to delete
            # the propagated objects (honoring PreserveResourcesOnDeletion,
            # execution_controller.go:229-257)
            work: Work = event.obj
            cluster = cluster_of_execution_namespace(work.meta.namespace)
            if cluster is None or work.spec.preserve_resources_on_deletion:
                return
            targets = tuple(
                (f"{w.api_version}/{w.kind}", w.meta.namespace, w.meta.name)
                for w in work.spec.workload
            )
            self.worker.enqueue(("delete", cluster, targets))
        else:
            self.worker.enqueue(("apply", event.key, None))

    def _reconcile(self, item) -> Optional[str]:
        action, key_or_cluster, targets = item
        if action == "delete":
            for gvk, ns, name in targets:
                try:
                    self.watcher.delete(key_or_cluster, gvk, ns, name)
                except UnreachableError:
                    self._pending_deletes.setdefault(key_or_cluster, set()).add(
                        (gvk, ns, name)
                    )
            return DONE
        key = key_or_cluster
        work = self.store.get("Work", key)
        cluster = cluster_of_execution_namespace(key.split("/", 1)[0])
        if work is None or cluster is None:
            return DONE
        cluster_obj = self.store.get("Cluster", cluster)
        if cluster_obj is not None and cluster_obj.spec.sync_mode == "Pull":
            return DONE  # the in-cluster agent applies Pull-mode works
        if work.spec.suspend_dispatching:
            if set_condition(
                work.status.conditions,
                Condition(
                    type="Dispatching", status=False, reason="SuspendDispatching"
                ),
            ):
                self.store.apply(work)
            return DONE
        try:
            for workload in work.spec.workload:
                self.watcher.create_or_update(
                    cluster, workload,
                    conflict_resolution=work.spec.conflict_resolution,
                )
        except ConflictError as e:
            if set_condition(
                work.status.conditions,
                Condition(
                    type=WORK_APPLIED, status=False,
                    reason="ResourceConflict", message=str(e),
                ),
            ):
                self.store.apply(work)
            return DONE  # permanent until the member object changes
        except UnreachableError:
            if set_condition(
                work.status.conditions,
                Condition(type=WORK_APPLIED, status=False, reason="ClusterUnreachable"),
            ):
                self.store.apply(work)
            return REQUEUE
        if set_condition(
            work.status.conditions,
            Condition(type=WORK_APPLIED, status=True, reason="AppliedSuccessful"),
        ):
            self.store.apply(work)
        return DONE


class WorkStatusController:
    """Member object events -> Work.Status.ManifestStatuses (+ recreation of
    deleted-but-desired objects)."""

    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        members: MemberClientRegistry,
        interpreter: ResourceInterpreter,
        work_index: Optional[WorkIndex] = None,
    ) -> None:
        self.store = store
        self.members = members
        self.interpreter = interpreter
        self.work_index = work_index or WorkIndex(store)
        self.worker = runtime.new_worker("work-status", self._reconcile)
        for name in members.names():
            client = members.get(name)
            if client is not None:
                client.watch(self._on_member_event)

    def watch_member(self, member) -> None:
        member.watch(self._on_member_event)

    def _on_member_event(self, event: MemberEvent) -> None:
        self.worker.enqueue(
            (event.cluster, event.gvk, event.namespace, event.name, event.type)
        )

    def _find_work(self, cluster: str, gvk: str, namespace: str, name: str):
        work = self.work_index.work_for_target(cluster, gvk, namespace, name)
        if work is not None:
            for workload in work.spec.workload:
                if (
                    f"{workload.api_version}/{workload.kind}" == gvk
                    and workload.meta.namespace == namespace
                    and workload.meta.name == name
                ):
                    return work, workload
        return None, None

    def _reconcile(self, key) -> Optional[str]:
        cluster, gvk, namespace, name, event_type = key
        work, desired = self._find_work(cluster, gvk, namespace, name)
        if work is None:
            return DONE
        member = self.members.get(cluster)
        if member is None:
            return DONE
        try:
            observed = member.get(gvk, namespace, name)
        except UnreachableError:
            return REQUEUE
        if observed is None:
            # recreate deleted-but-desired (work_status_controller.go:311)
            if not work.spec.preserve_resources_on_deletion:
                try:
                    ObjectWatcher(self.members, self.interpreter).create_or_update(
                        cluster, desired
                    )
                except UnreachableError:
                    return REQUEUE
            return DONE
        status = self.interpreter.reflect_status(observed)
        # health is Unknown until the member reports any status — a fresh
        # object is not "Unhealthy" (failover must not fire on it)
        if status is None:
            health = "Unknown"
        else:
            health = (
                "Healthy" if self.interpreter.interpret_health(observed) else "Unhealthy"
            )
        identifier = observed.object_reference()
        updated = False
        for ms in work.status.manifest_statuses:
            if (
                ms.identifier.gvk == identifier.gvk
                and ms.identifier.namespaced_key == identifier.namespaced_key
            ):
                if ms.status != status or ms.health != health:
                    ms.status = status
                    ms.health = health
                    updated = True
                break
        else:
            work.status.manifest_statuses.append(
                ManifestStatus(identifier=identifier, status=status, health=health)
            )
            updated = True
        if updated:
            self.store.apply(work)
        return DONE


class BindingStatusController:
    """Work.Status -> ResourceBinding.Status.AggregatedStatus (+ FullyApplied
    condition), then template status write-back via the detector."""

    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        detector,
        work_index: Optional[WorkIndex] = None,
    ) -> None:
        self.store = store
        self.detector = detector
        self.work_index = work_index or WorkIndex(store)
        self.worker = runtime.new_worker("binding-status", self._reconcile)
        store.watch("Work", self._on_work_event)

    def _on_work_event(self, event) -> None:
        key = event.obj.meta.labels.get(WORK_BINDING_LABEL)
        if key:
            self.worker.enqueue(key)

    def _reconcile(self, ref: str) -> Optional[str]:
        kind, _, key = ref.partition(":")
        if kind not in BINDING_KINDS:
            return DONE
        rb = self.store.get(kind, key)
        if rb is None:
            return DONE
        items: list[AggregatedStatusItem] = []
        applied_clusters = set()
        for work in self.work_index.works_for(ref):
            cluster = cluster_of_execution_namespace(work.meta.namespace)
            if cluster is None:
                continue
            applied_cond = next(
                (c for c in work.status.conditions if c.type == WORK_APPLIED),
                None,
            )
            applied = applied_cond is not None and applied_cond.status
            if applied:
                applied_clusters.add(cluster)
            if work.status.manifest_statuses:
                for ms in work.status.manifest_statuses:
                    items.append(
                        AggregatedStatusItem(
                            cluster_name=cluster,
                            status=ms.status,
                            applied=applied,
                            health=ms.health,
                        )
                    )
            elif applied_cond is not None and not applied:
                # a Work that failed to apply (conflict, unreachable) never
                # reports manifest statuses — the failure must still be
                # visible in the binding aggregation (the reference emits
                # per-manifest items with Applied=false + AppliedMessage)
                items.append(
                    AggregatedStatusItem(
                        cluster_name=cluster,
                        status=None,
                        applied=False,
                        health="Unknown",
                        applied_message=applied_cond.message,
                    )
                )
        items.sort(key=lambda i: i.cluster_name)
        target_clusters = {tc.name for tc in rb.spec.clusters}
        status_changed = rb.status.aggregated_status != items
        rb.status.aggregated_status = items
        cond_changed = set_condition(
            rb.status.conditions,
            Condition(
                type=FULLY_APPLIED,
                status=bool(target_clusters) and target_clusters <= applied_clusters,
                reason="FullyAppliedSuccess"
                if target_clusters <= applied_clusters
                else "FullyAppliedFailed",
            ),
        )
        if status_changed or cond_changed:
            self.store.apply(rb)
            if self.detector is not None:
                self.detector.write_back_status(rb)
        return DONE
