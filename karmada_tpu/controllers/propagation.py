"""Propagation controllers: binding -> Work -> member cluster -> status back.

Ref:
- binding-controller (pkg/controllers/binding/): ensureWork — ReviseReplica
  for divided placements, override application, suspend/preserve flags,
  orphan-Work cleanup (binding_controller.go:70-165, common.go:43-143).
- execution-controller (pkg/controllers/execution/): Work -> member apply /
  delete via objectwatcher, Applied condition.
- work-status-controller (pkg/controllers/status/work_status_controller.go):
  per-member informers reflect member object status+health into
  Work.Status.ManifestStatuses; recreates deleted-but-desired objects.
- binding-status controllers (status/rb_status_controller.go): aggregate
  manifest statuses into ResourceBinding.Status.AggregatedStatus via the
  interpreter, then the detector writes template status.
"""

from __future__ import annotations

from ..utils.clone import clone_resource
import hashlib
import json
import math
import os
from typing import Optional

from ..api.core import Condition, ObjectMeta, Resource, set_condition
from ..api.work import (
    FULLY_APPLIED,
    WORK_APPLIED,
    AggregatedStatusItem,
    ManifestStatus,
    ResourceBinding,
    Work,
    WorkloadTemplate,
    WorkloadTemplateRef,
    WorkSpec,
)
from ..api.policy import DIVIDED
from ..interpreter import ResourceInterpreter
from ..utils import DONE, REQUEUE, Runtime, Store
from ..utils.codec import from_jsonable, to_jsonable
from ..utils.metrics import works_rendered
from ..utils.member import (
    ConflictError,
    MemberClientRegistry,
    MemberEvent,
    ObjectWatcher,
    UnreachableError,
)
from .overridemanager import OverrideManager

ES_PREFIX = "karmada-es-"
WORK_BINDING_LABEL = "resourcebinding.karmada.io/key"  # value: "<kind>:<key>"

BINDING_KINDS = ("ResourceBinding", "ClusterResourceBinding")

TEMPLATE_DELTA_ENV = "KARMADA_TPU_BUS_TEMPLATE_DELTA"


def template_delta_enabled() -> bool:
    """Template-delta Work rendering kill switch (ISSUE 11 tentpole c):
    set KARMADA_TPU_BUS_TEMPLATE_DELTA=0 to force full-object rendering
    for every Work (the degraded/compat path)."""
    return os.environ.get(TEMPLATE_DELTA_ENV, "1").lower() not in (
        "0", "false", ""
    )


def binding_ref(kind: str, key: str) -> str:
    return f"{kind}:{key}"


def execution_namespace(cluster: str) -> str:
    return f"{ES_PREFIX}{cluster}"


def cluster_of_execution_namespace(ns: str) -> Optional[str]:
    return ns[len(ES_PREFIX):] if ns.startswith(ES_PREFIX) else None


def binding_namespace_shard(kind_key) -> str:
    """Per-namespace ownership token for worker sharding: drains of
    different namespaces ride different shard queues, so one namespace's
    storm (or poisoned key bisect) never head-of-line-blocks another's
    batch flush."""
    _, key = kind_key
    ns, sep, _ = key.partition("/")
    return ns if sep else ""


def _patch_key(patch: dict) -> tuple:
    return tuple(sorted(patch.items()))


def _work_signature(work: Work):
    ref = work.spec.workload_template
    if ref is not None and ref.digest:
        # template-delta works: content identity is (digest, patch) —
        # the manifest body lives in the content-addressed template
        w_sig = ("tpl", ref.digest, _patch_key(ref.patch))
        labels = None
    else:
        w = work.spec.workload[0] if work.spec.workload else None
        w_sig = w.spec if w else None
        labels = w.meta.labels if w else None
    return (
        w_sig,
        labels,
        work.spec.suspend_dispatching,
        work.spec.preserve_resources_on_deletion,
    )


class TemplateRehydrator:
    """Consumer-side template-delta cache: decodes each WorkloadTemplate
    manifest ONCE (content-addressed — a digest's body never changes) and
    renders each Work's manifest as clone(base) + patch, memoized per
    Work so repeated reconciles hand back the SAME object (the member
    ObjectWatcher's no-op cache pins on manifest identity). Returns None
    when the template has not been mirrored yet — callers REQUEUE and the
    WorkloadTemplate watch unparks them."""

    def __init__(self, store) -> None:
        self.store = store
        self._base: dict[str, Resource] = {}
        # work key -> (digest, patch key, rendered list)
        self._rendered: dict[str, tuple] = {}

    def manifests(self, work: Work) -> Optional[list]:
        ref = work.spec.workload_template
        if ref is None or not ref.digest:
            return work.spec.workload
        pkey = _patch_key(ref.patch)
        hit = self._rendered.get(work.meta.namespaced_name)
        if hit is not None and hit[0] == ref.digest and hit[1] == pkey:
            return hit[2]
        base = self._base.get(ref.digest)
        if base is None:
            tpl = self.store.get("WorkloadTemplate", ref.digest)
            if tpl is None:
                return None  # not mirrored yet: caller requeues
            base = from_jsonable(Resource, tpl.manifest)
            self._base[ref.digest] = base
        out = clone_resource(base)
        if ref.patch:
            out.spec.update(ref.patch)
        rendered = [out]
        self._rendered[work.meta.namespaced_name] = (
            ref.digest, pkey, rendered
        )
        return rendered

    def forget_digest(self, digest: str) -> None:
        self._base.pop(digest, None)

    def forget_work(self, key: str) -> None:
        self._rendered.pop(key, None)


def work_manifests(store, work: Work, rehydrator=None) -> Optional[list]:
    """The manifest list of a Work, rehydrating template-delta Works from
    their WorkloadTemplate (None = template not mirrored yet). One-shot
    helper; long-lived consumers hold a TemplateRehydrator for the
    decode/render caches."""
    return (rehydrator or TemplateRehydrator(store)).manifests(work)


class WorkIndex:
    """Incremental indexes over Work objects, maintained from watch events
    (the informer-indexer analogue). Kills the O(bindings x works) scans
    the binding/status controllers would otherwise pay per reconcile:
    - by binding label (orphan cleanup, status aggregation)
    - by propagated target (cluster, gvk, namespace, name) for member-event
      routing in the work-status controller."""

    def __init__(self, store: Store) -> None:
        self.store = store
        self._by_binding: dict[str, set[str]] = {}
        self._by_target: dict[tuple, str] = {}
        # work key -> (ref, targets, template digest)
        self._work_meta: dict[str, tuple] = {}
        # template digest -> referencing work keys (the template GC's
        # refcount surface: a digest nobody references is collectable)
        self._by_digest: dict[str, set[str]] = {}
        # watch(replay=True) synthesizes Added for Works already in the store,
        # so the index seeds correctly against a populated store.
        store.watch("Work", self._on_event)

    def _on_event(self, event) -> None:
        key = event.key
        old_ref, old_targets, old_digest = self._work_meta.pop(
            key, (None, (), None)
        )
        if old_ref is not None:
            self._by_binding.get(old_ref, set()).discard(key)
        if old_digest is not None:
            refs = self._by_digest.get(old_digest)
            if refs is not None:
                refs.discard(key)
                if not refs:
                    del self._by_digest[old_digest]
        for t in old_targets:
            if self._by_target.get(t) == key:
                del self._by_target[t]
        if event.type == "Deleted":
            return
        work = event.obj
        ref = work.meta.labels.get(WORK_BINDING_LABEL)
        cluster = cluster_of_execution_namespace(work.meta.namespace)
        tref = work.spec.workload_template
        digest = tref.digest if tref is not None and tref.digest else None
        if cluster is None:
            targets = ()
        elif digest is not None:
            # template-delta works carry target identity on the ref —
            # the index never needs the template body
            targets = (
                (cluster, f"{tref.api_version}/{tref.kind}",
                 tref.namespace, tref.name),
            )
        else:
            targets = tuple(
                (cluster, f"{w.api_version}/{w.kind}",
                 w.meta.namespace, w.meta.name)
                for w in work.spec.workload
            )
        if ref:
            self._by_binding.setdefault(ref, set()).add(key)
        if digest is not None:
            self._by_digest.setdefault(digest, set()).add(key)
        for t in targets:
            self._by_target[t] = key
        self._work_meta[key] = (ref, targets, digest)

    def digest_refcount(self, digest: str) -> int:
        return len(self._by_digest.get(digest, ()))

    def works_for(self, binding_ref: str) -> list:
        out = []
        for key in sorted(self._by_binding.get(binding_ref, ())):
            work = self.store.get("Work", key)
            if work is not None:
                out.append(work)
        return out

    def work_for_target(self, cluster: str, gvk: str, namespace: str, name: str):
        key = self._by_target.get((cluster, gvk, namespace, name))
        return self.store.get("Work", key) if key else None


class BindingController:
    """ResourceBinding -> per-target-cluster Work objects."""

    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        interpreter: ResourceInterpreter,
        work_index: Optional[WorkIndex] = None,
    ) -> None:
        self.store = store
        self.interpreter = interpreter
        self.work_index = work_index or WorkIndex(store)
        self.overrides = OverrideManager(store)
        # binding ref -> (global fingerprint, {cluster: (replicas,
        # cluster_token)}) of the last ensureWork pass: an incremental storm
        # (scale +1) changes one target's count, so only that Work is
        # rebuilt instead of revising/overriding/cloning the template once
        # per target per reconcile. cluster_token covers the live cluster
        # fields override rules match on (labels/provider/region/zone).
        # Keyed on template (uid, generation) — the plane's spec-change
        # discipline (the scheduler gate relies on generation the same way).
        self._built: dict[str, tuple] = {}
        # (template uid, replica-exclusion flag) -> ((generation,
        # resource_version), content hash): a scale storm bumps every
        # template's generation while changing only the replica fields the
        # per-target revise overwrites anyway, so generation alone would
        # void the build cache fleet-wide each wave
        self._template_hashes: dict[tuple, tuple] = {}
        # Works this controller deleted itself (orphan cleanup): their
        # Deleted events must not void the freshly written cache entry
        self._own_deletes: set[str] = set()
        # template-delta rendering: (binding ref -> ((uid, generation,
        # rv), digest, pruned manifest doc)) content cache — keyed by
        # REF so binding deletion evicts it (a uid key would grow with
        # all-time template churn) — digests already published to the
        # store, binding ref -> digest for GC, and the digests whose
        # refcount must be re-checked after the next flush
        self._tpl_cache: dict[str, tuple] = {}
        self._tpl_published: set[str] = set()
        self._built_digest: dict[str, str] = {}
        self._gc_digests: set[str] = set()
        # per-drain write set (ISSUE 11 tentpole b): reconciles buffer
        # their Work applies/deletes and the drain flushes them as ONE
        # batched write (store.apply_many -> one lock+delivery sweep
        # in-proc, one ApplyBatch RPC over the bus facade)
        self._buffering = False
        self._pending_applies: list = []
        self._pending_deletes: list = []
        self.worker = runtime.new_worker(
            "binding", self._reconcile,
            reconcile_batch=self._reconcile_batch,
            shard_fn=binding_namespace_shard,
        )
        for kind in BINDING_KINDS:
            store.watch(
                kind, lambda e, k=kind: self.worker.enqueue((k, e.key))
            )
        store.watch("OverridePolicy", self._requeue_all)
        store.watch("ClusterOverridePolicy", self._requeue_all)
        # interpreter customizations change revise/retain semantics: the
        # cached build fingerprints are meaningless across such a change
        store.watch(
            "ResourceInterpreterCustomization", self._requeue_all,
            replay=False,
        )
        store.watch("Work", self._on_work_event, replay=False)
        # override rules match live cluster state: a label / topology edit
        # must requeue the bindings whose Works were built against the old
        # state (status heartbeats leave the token unchanged and are cheap)
        store.watch("Cluster", self._on_cluster_event, replay=False)
        self._cluster_tokens: dict[str, tuple] = {}

    @staticmethod
    def _cluster_token(cluster) -> Optional[tuple]:
        """The live cluster fields override rules can match on
        (ClusterAffinity: name/labels, FieldSelector: provider/region/zone).
        Both the build cache and the Cluster watch compare THIS tuple — keep
        them in lockstep via this single constructor."""
        if cluster is None:
            return None
        return (
            tuple(sorted(cluster.meta.labels.items())),
            cluster.spec.provider,
            cluster.spec.region,
            cluster.spec.zone,
        )

    _UNSEEDED = object()

    def _lookup_cluster_token(self, name: str) -> Optional[tuple]:
        """Cached token for cache-hit targets: the Cluster watch keeps the
        map current (synchronous delivery on the applying thread), so
        steady-storm reconciles pay one dict get per target instead of a
        store fetch + label sort. Lazily seeded from the store for clusters
        that have produced no event since startup."""
        tok = self._cluster_tokens.get(name, self._UNSEEDED)
        if tok is self._UNSEEDED:
            tok = self._cluster_token(self.store.get("Cluster", name))
            self._cluster_tokens[name] = tok
        return tok

    def _on_cluster_event(self, event) -> None:
        name = event.key
        if event.type == "Deleted":
            # tombstone (not pop): the post-build race check must see the
            # deletion, and a later re-join overwrites it
            self._cluster_tokens[name] = None
            token = None
        else:
            token = self._cluster_token(event.obj)
            if self._cluster_tokens.get(name) == token:
                return  # status-only change: override matching unaffected
            self._cluster_tokens[name] = token
        for ref, (_fp, built_targets) in list(self._built.items()):
            entry = built_targets.get(name)
            if entry is not None and entry[1] != token:
                kind, _, key = ref.partition(":")
                self.worker.enqueue((kind, key))

    def _on_work_event(self, event) -> None:
        # an externally deleted Work must be rebuilt even though the build
        # cache says nothing changed
        if event.type != "Deleted":
            return
        if event.key in self._own_deletes:
            self._own_deletes.discard(event.key)
            return
        ref = event.obj.meta.labels.get(WORK_BINDING_LABEL)
        if ref and self._built.pop(ref, None) is not None:
            kind, _, key = ref.partition(":")
            self.worker.enqueue((kind, key))

    def _requeue_all(self, _event) -> None:
        self._built.clear()  # override policies changed: full rebuild
        for kind in BINDING_KINDS:
            for rb in self.store.list(kind):
                self.worker.enqueue((kind, rb.meta.namespaced_name))

    def _reconcile_batch(self, kind_keys) -> dict:
        """Batched drain: reconciles buffer their Work writes and ONE
        flush commits the whole drain's write set (ISSUE 11: per-drain
        write sets instead of per-object applies). Safe under the
        worker's poisoned-key bisect — reconciles are idempotent and the
        signature gate no-ops re-runs of already-flushed work."""
        out: dict = {}
        self._buffering = True
        try:
            for kind_key in kind_keys:
                out[kind_key] = self._reconcile(kind_key)
        finally:
            self._buffering = False
            self._flush()
        return out

    def _apply_work(self, work: Work) -> None:
        if self._buffering:
            self._pending_applies.append(work)
        else:
            self.store.apply(work)

    def _delete_work(self, key: str) -> None:
        self._own_deletes.add(key)
        if self._buffering:
            self._pending_deletes.append(("Work", key))
        else:
            self.store.delete("Work", key)

    def _flush(self) -> None:
        applies, self._pending_applies = self._pending_applies, []
        deletes, self._pending_deletes = self._pending_deletes, []
        if applies:
            apply_many = getattr(self.store, "apply_many", None)
            if apply_many is not None:
                for obj, err in apply_many(applies):
                    print(
                        f"# binding controller: work apply rejected for "
                        f"{obj.meta.namespaced_name}: {err}",
                        flush=True,
                    )
                    # the unbatched path RAISED here, skipping the
                    # _built update so the worker retried; batched, the
                    # fingerprint is already cached — drop it and
                    # re-enqueue the binding or the Work is never
                    # rewritten until something else changes
                    self._requeue_binding_of(obj)
            else:
                for work in applies:
                    self.store.apply(work)
        if deletes:
            delete_many = getattr(self.store, "delete_many", None)
            if delete_many is not None:
                for (kind, key), err in delete_many(deletes):
                    print(
                        f"# binding controller: work delete failed for "
                        f"{key}: {err}",
                        flush=True,
                    )
                    self._own_deletes.discard(key)
                    still = self.store.get(kind, key)
                    if still is not None:
                        self._requeue_binding_of(still)
            else:
                for kind, key in deletes:
                    self.store.delete(kind, key)
        self._gc_templates()

    def _requeue_binding_of(self, work) -> None:
        """A buffered write for this Work failed at the flush: invalidate
        the binding's build fingerprint and re-reconcile it (the batched
        analogue of the raise→REQUEUE the per-object path had)."""
        ref = work.meta.labels.get(WORK_BINDING_LABEL, "")
        kind, sep, key = ref.partition(":")
        if not sep:
            return
        self._built.pop(ref, None)
        self.worker.enqueue((kind, key))

    def _gc_templates(self) -> None:
        """Collect content-addressed templates nothing references any
        more — checked AFTER the flush so a drain that re-pointed works
        at a new digest (bumping the old one to zero) and a drain that
        re-used a candidate digest both see the settled refcounts. Two
        independent liveness proofs must BOTH fail before a delete: the
        work index (which, over a bus facade, lags the primary by the
        write-echo window — a just-flushed Work is not indexed yet) and
        the controller's own binding→digest bookkeeping (current by
        construction). A digest either gate calls live stays; a stale
        candidate just re-queues on the binding's next transition."""
        if not self._gc_digests:
            return
        digests, self._gc_digests = self._gc_digests, set()
        live = set(self._built_digest.values())
        for digest in digests:
            if digest in live:
                continue
            if self.work_index.digest_refcount(digest) == 0:
                self._tpl_published.discard(digest)
                self.store.delete("WorkloadTemplate", digest)
            else:
                # the index still sees references: either echo lag (the
                # re-pointed Works haven't mirrored back yet) or a true
                # revival — re-check after the next flush; deletes must
                # never race the echo window
                self._gc_digests.add(digest)

    def _ensure_template(self, ref: str, template: Resource) -> str:
        """Digest + publish of the content-addressed WorkloadTemplate for
        this template's current content. The manifest doc is pruned
        exactly like the Work admission mutator prunes full-rendered
        manifests (status/uid/resourceVersion/creationTimestamp), so
        rehydration is byte-equivalent to full rendering."""
        ver = (
            template.meta.uid,
            template.meta.generation,
            template.meta.resource_version,
        )
        cached = self._tpl_cache.get(ref)
        if cached is not None and cached[0] == ver:
            digest, doc = cached[1], cached[2]
        else:
            doc = to_jsonable(template)
            doc["status"] = {}
            meta = doc.get("meta") or {}
            meta["uid"] = ""
            meta["resource_version"] = 0
            meta["creation_timestamp"] = 0.0
            digest = hashlib.blake2b(
                json.dumps(doc, sort_keys=True, separators=(",", ":"))
                .encode(), digest_size=16,
            ).hexdigest()
            self._tpl_cache[ref] = (ver, digest, doc)
        if digest not in self._tpl_published:
            if self.store.get("WorkloadTemplate", digest) is None:
                # published DIRECTLY (never buffered): the template must
                # be in the store — and on the bus stream — before any
                # buffered Work referencing it flushes
                self.store.apply(WorkloadTemplate(
                    meta=ObjectMeta(name=digest), manifest=doc
                ))
            self._tpl_published.add(digest)
        return digest

    def _template_patch(
        self, template: Resource, rb: ResourceBinding, divided: bool,
        replicas: int,
    ) -> Optional[dict]:
        """The per-cluster spec patch for template-delta rendering, or
        None when this target is not templatable (custom revise hook —
        the hook may derive arbitrary fields from the count)."""
        if not divided or rb.spec.replicas <= 0:
            return {}
        patch = self.interpreter.revise_patch(template, replicas)
        if patch is None:
            return None
        if template.kind == "Job" and "completions" in template.spec:
            total = int(template.spec["completions"])
            patch["completions"] = math.ceil(
                total * replicas / max(rb.spec.replicas, 1)
            )
        return patch

    def _reconcile(self, kind_key) -> Optional[str]:
        kind, key = kind_key
        ref = binding_ref(kind, key)
        rb = self.store.get(kind, key)
        if rb is None:
            self._built.pop(ref, None)
            self._cleanup_works(ref, keep_clusters=set())
            self._forget_digest(ref)
            if not self._buffering:
                self._flush()
            return DONE
        template = self.store.get("Resource", rb.spec.resource.namespaced_key)
        if template is None:
            self._built.pop(ref, None)
            self._forget_digest(ref)
            if not self._buffering:
                self._flush()
            return DONE
        # target set: scheduled clusters + clusters still draining eviction
        # tasks (their Works must survive until eviction completes,
        # binding_controller.go:145-165)
        targets = {tc.name: tc.replicas for tc in rb.spec.clusters}
        evicting = {t.from_cluster for t in rb.spec.graceful_eviction_tasks}
        # RequiredBy snapshots extend the target set: dependencies follow
        # their dependers (binding/common.go mergeTargetClusters)
        for snap in rb.spec.required_by:
            for tc in snap.clusters:
                targets.setdefault(tc.name, 0)
        divided = (
            rb.spec.placement is not None
            and rb.spec.placement.replica_scheduling_type() == DIVIDED
        )
        fp_global = (
            template.meta.uid,
            self._template_token(template, divided),
            divided,
            # the binding's TOTAL replicas only shape a target's manifest
            # through the Job completions split; for every other kind the
            # manifest depends on the per-target count alone, and a scale
            # storm must not void every target's cache entry
            rb.spec.replicas
            if (template.kind == "Job" and "completions" in template.spec)
            else 0,
            rb.spec.suspend_dispatching,
            tuple(sorted(rb.spec.suspend_dispatching_on_clusters or ())),
            rb.spec.preserve_resources_on_deletion,
            rb.spec.conflict_resolution,
            # rendering MODE is part of the build identity: flipping the
            # template-delta kill switch must rebuild every Work in the
            # other representation
            template_delta_enabled(),
        )
        prev_global, prev_targets = self._built.get(ref, (None, None))
        unchanged = prev_global == fp_global and prev_targets is not None
        built_targets: dict[str, tuple] = {}
        # template-delta rendering (tentpole c): one content-addressed
        # template for the whole workload family, per-cluster Works carry
        # only (digest, replica patch) — the full manifest never clones
        # or crosses the bus once per target. Per-TARGET fallback: a
        # custom revise hook or a matching override rule makes that
        # target full-render while the rest of the fleet stays delta.
        tpl_mode = template_delta_enabled() and isinstance(
            template.spec, dict
        )
        tpl_digest: Optional[str] = None
        fell_back_full = False  # some target REBUILT full this pass
        for cluster_name, replicas in targets.items():
            # apply_overrides matches rules against LIVE cluster state
            # (name / labels / provider / region / zone), so the per-target
            # cache entry carries a token over those fields: a cluster label
            # edit that flips an override rule's match rebuilds exactly the
            # Works on that cluster instead of going stale forever
            cluster_token = self._lookup_cluster_token(cluster_name)
            if unchanged and prev_targets.get(cluster_name) == (
                replicas,
                cluster_token,
            ):
                built_targets[cluster_name] = (replicas, cluster_token)
                continue  # this target's Work is already up to date
            cluster_obj = self.store.get("Cluster", cluster_name)
            built_targets[cluster_name] = (
                replicas, self._cluster_token(cluster_obj),
            )
            patch = (
                self._template_patch(template, rb, divided, replicas)
                if tpl_mode
                else None
            )
            if patch is not None and cluster_obj is not None:
                # override probe: any matching rule transforms the
                # manifest per cluster — that target must full-render.
                # Match-only (no clone, no overrider application): the
                # fallback path below runs the real transform once.
                if self.overrides.overrides_match(template, cluster_obj):
                    patch = None
            if patch is not None:
                if tpl_digest is None:
                    tpl_digest = self._ensure_template(ref, template)
                self._create_or_update_work(
                    rb, kind, cluster_name, None,
                    template_ref=WorkloadTemplateRef(
                        digest=tpl_digest,
                        api_version=template.api_version,
                        kind=template.kind,
                        namespace=template.meta.namespace,
                        name=template.meta.name,
                        patch=patch,
                    ),
                )
                continue
            fell_back_full = True
            # full-render fallback: every transform below (revise_replica,
            # apply_overrides) returns a fresh object, so the template is
            # cloned lazily — exactly ONE copy per Work, never three (the
            # redundant deepcopy chain dominated propagation-storm wall
            # time before the delta path existed)
            workload = template
            if divided and rb.spec.replicas > 0:
                workload = self.interpreter.revise_replica(workload, replicas)
                if workload is template:
                    workload = clone_resource(template)
                # Job completions division (binding/common.go:287-299)
                if workload.kind == "Job" and "completions" in workload.spec:
                    total = int(workload.spec["completions"])
                    workload.spec["completions"] = math.ceil(
                        total * replicas / max(rb.spec.replicas, 1)
                    )
            if cluster_obj is not None:
                workload = self.overrides.apply_overrides(workload, cluster_obj)
            if workload is template:
                workload = clone_resource(template)
            self._create_or_update_work(rb, kind, cluster_name, workload)
        self._cleanup_works(ref, keep_clusters=set(targets) | evicting)
        self._built[ref] = (fp_global, built_targets)
        # template GC bookkeeping: a binding whose content digest moved
        # (or went full-render) queues its OLD digest for a post-flush
        # refcount check
        if tpl_digest is not None:
            prev_digest = self._built_digest.get(ref)
            if prev_digest is not None and prev_digest != tpl_digest:
                self._gc_digests.add(prev_digest)
            self._built_digest[ref] = tpl_digest
        elif not tpl_mode:
            # genuinely full-rendered now (kill switch flipped, or the
            # workload stopped being templatable): drop the ref and let
            # the refcount check collect the orphaned template
            self._forget_digest(ref)
        elif fell_back_full and not any(
            w.spec.workload_template is not None
            and w.spec.workload_template.digest
            == self._built_digest.get(ref)
            for w in self.work_index.works_for(ref)
        ):
            # delta mode, no digest this pass, and some target REBUILT
            # full (e.g. an override rule now matches every cluster) —
            # and the indexed works no longer carry the old digest: the
            # binding has genuinely left delta rendering, so drop the
            # bookkeeping and let the refcount check collect the orphan.
            # The fell_back_full gate keeps a steady all-unchanged pass
            # (whose works still reference the digest, however laggy the
            # index) from dropping LIVE bookkeeping; the index gate keeps
            # the transition pass itself from racing its own flush.
            self._forget_digest(ref)
        else:
            # delta mode, every target signature-unchanged (or the index
            # still shows delta works): the digest stays live — queue a
            # harmless post-flush re-check and KEEP the bookkeeping
            prev_digest = self._built_digest.get(ref)
            if prev_digest is not None:
                self._gc_digests.add(prev_digest)
        if not self._buffering:
            self._flush()
        # close the build/event race: a Cluster event landing mid-build found
        # no _built entry to requeue against, and this reconcile may have
        # built against the pre-event object — re-check the freshly written
        # tokens against the watch-maintained map and requeue on divergence
        for name, (_reps, tok) in built_targets.items():
            cur = self._cluster_tokens.get(name, self._UNSEEDED)
            if cur is not self._UNSEEDED and cur != tok:
                self.worker.enqueue((kind, key))
                break
        return DONE

    # replica fields the per-target ReviseReplica pass overwrites; a
    # template change confined to them cannot alter an unchanged target's
    # manifest (its value is re-derived from the binding's division)
    _REPLICA_FIELDS = ("replicas", "parallelism", "completions")

    def _template_token(self, template: Resource, divided: bool) -> int:
        """Build-cache content token for the template. A hash over the
        manifest-shaping fields (spec + labels + annotations) rather than
        the generation: metadata-only edits don't bump generation, and
        resource_version bumps on status-only writes — neither is a valid
        cache key alone. For divided bindings whose kind has no custom
        ReviseReplica hook the top-level replica fields are excluded, so a
        fleet-wide scale storm (only replica counts change) keeps unchanged
        targets cached; custom-revise kinds hash the full spec (their hooks
        may derive arbitrary fields from the template's replica count)."""
        gvk = f"{template.api_version}/{template.kind}"
        exclude = divided and not self.interpreter.has_custom_revise(gvk)
        key = (template.meta.uid, exclude)
        ver = (template.meta.generation, template.meta.resource_version)
        cached = self._template_hashes.get(key)
        if cached is not None and cached[0] == ver:
            return cached[1]
        spec_view = (
            {
                k: v
                for k, v in template.spec.items()
                if k not in self._REPLICA_FIELDS
            }
            if exclude
            else template.spec
        )
        token = hash(
            (
                repr(spec_view),
                repr(sorted(template.meta.labels.items())),
                repr(sorted(template.meta.annotations.items())),
            )
        )
        self._template_hashes[key] = (ver, token)
        return token

    def _create_or_update_work(
        self,
        rb: ResourceBinding,
        kind: str,
        cluster: str,
        workload: Optional[Resource],
        *,
        template_ref: Optional[WorkloadTemplateRef] = None,
    ) -> None:
        ns = execution_namespace(cluster)
        name = f"{rb.meta.namespace + '.' if rb.meta.namespace else ''}{rb.meta.name}"
        key = f"{ns}/{name}"
        # per-target suspension: global flag OR the cluster is listed in
        # DispatchingOnClusters (binding/common.go:305-318)
        suspended = rb.spec.suspend_dispatching or (
            cluster in (rb.spec.suspend_dispatching_on_clusters or ())
        )
        if template_ref is not None:
            desired_sig = (
                ("tpl", template_ref.digest, _patch_key(template_ref.patch)),
                None,
            )
        else:
            desired_sig = (workload.spec, workload.meta.labels)
        existing = self.store.get("Work", key)
        if existing is not None and _work_signature(existing) == (
            desired_sig
            + (suspended, rb.spec.preserve_resources_on_deletion)
        ):
            return  # no semantic change — avoid churn (idempotent reconcile)
        work = existing or Work(meta=ObjectMeta(name=name, namespace=ns))
        work.meta.labels[WORK_BINDING_LABEL] = binding_ref(
            kind, rb.meta.namespaced_name
        )
        work.spec = WorkSpec(
            workload=[workload] if workload is not None else [],
            workload_template=template_ref,
            suspend_dispatching=suspended,
            preserve_resources_on_deletion=rb.spec.preserve_resources_on_deletion,
            conflict_resolution=rb.spec.conflict_resolution,
        )
        self._apply_work(work)
        # only SEMANTIC creates/updates count (the signature gate above
        # returned on no-ops): this is the work-render throughput the
        # whole-plane storm tier measures (ROADMAP item 3)
        works_rendered.inc()

    def _forget_digest(self, binding_key: str) -> None:
        self._tpl_cache.pop(binding_key, None)
        digest = self._built_digest.pop(binding_key, None)
        if digest is not None:
            self._gc_digests.add(digest)

    def _cleanup_works(self, binding_key: str, keep_clusters: set[str]) -> None:
        for work in self.work_index.works_for(binding_key):
            cluster = cluster_of_execution_namespace(work.meta.namespace)
            if cluster not in keep_clusters:
                self._delete_work(work.meta.namespaced_name)


class ExecutionController:
    """Work -> member cluster apply/delete (pkg/controllers/execution/)."""

    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        members: MemberClientRegistry,
        interpreter: ResourceInterpreter,
    ) -> None:
        self.store = store
        self.members = members
        self.watcher = ObjectWatcher(members, interpreter)
        self.rehydrator = TemplateRehydrator(store)
        # deletes parked while a cluster is unreachable; retried when the
        # cluster comes back (the asynchronous-retry analogue — burning
        # requeue budget against a dead cluster helps nobody)
        self._pending_deletes: dict[str, set[tuple[str, str, str]]] = {}
        # work keys parked on a template that has not replicated yet
        # (bus replay/restore can deliver a Work before its template);
        # the WorkloadTemplate watch unparks them
        self._awaiting_template: dict[str, set] = {}
        # per-drain write set: Work condition updates flush as one batch
        self._buffering = False
        self._pending_applies: list = []
        self.worker = runtime.new_worker(
            "execution", self._reconcile,
            reconcile_batch=self._reconcile_batch,
        )
        store.watch("Work", self._on_work_event)
        store.watch("Cluster", self._on_cluster_event)
        store.watch("WorkloadTemplate", self._on_template_event, replay=False)

    def _on_cluster_event(self, event) -> None:
        pending = self._pending_deletes.pop(event.key, None)
        if pending:
            self.worker.enqueue(("delete", event.key, tuple(sorted(pending))))

    def _on_template_event(self, event) -> None:
        if event.type == "Deleted":
            self.rehydrator.forget_digest(event.key)
            return
        parked = self._awaiting_template.pop(event.key, None)
        if parked:
            for item in parked:
                self.worker.enqueue(item)

    def _on_work_event(self, event) -> None:
        if event.type == "Deleted":
            # the Work is gone from the store; carry what we need to delete
            # the propagated objects (honoring PreserveResourcesOnDeletion,
            # execution_controller.go:229-257)
            work: Work = event.obj
            self.rehydrator.forget_work(event.key)
            # a Work deleted while parked on a never-arriving template
            # must not leak its parked entry
            for parked in self._awaiting_template.values():
                parked.discard(("apply", event.key, None))
            cluster = cluster_of_execution_namespace(work.meta.namespace)
            if cluster is None or work.spec.preserve_resources_on_deletion:
                return
            tref = work.spec.workload_template
            if tref is not None and tref.digest:
                # template-delta works carry target identity on the ref
                targets = (
                    (f"{tref.api_version}/{tref.kind}",
                     tref.namespace, tref.name),
                )
            else:
                targets = tuple(
                    (f"{w.api_version}/{w.kind}",
                     w.meta.namespace, w.meta.name)
                    for w in work.spec.workload
                )
            self.worker.enqueue(("delete", cluster, targets))
        else:
            self.worker.enqueue(("apply", event.key, None))

    def _reconcile_batch(self, items) -> dict:
        out: dict = {}
        self._buffering = True
        try:
            for item in items:
                out[item] = self._reconcile(item)
        finally:
            self._buffering = False
            self._flush()
        return out

    def _apply_status(self, work: Work) -> None:
        if self._buffering:
            self._pending_applies.append(work)
        else:
            self.store.apply(work)

    def _flush(self) -> None:
        applies, self._pending_applies = self._pending_applies, []
        if not applies:
            return
        apply_many = getattr(self.store, "apply_many", None)
        if apply_many is not None:
            for work, _err in apply_many(applies):
                # rejected status write: retry the Work (the unbatched
                # path raised and the worker requeued)
                self.worker.enqueue(
                    ("apply", work.meta.namespaced_name, None)
                )
        else:
            for work in applies:
                self.store.apply(work)

    def _reconcile(self, item) -> Optional[str]:
        action, key_or_cluster, targets = item
        if action == "delete":
            for gvk, ns, name in targets:
                try:
                    self.watcher.delete(key_or_cluster, gvk, ns, name)
                except UnreachableError:
                    self._pending_deletes.setdefault(key_or_cluster, set()).add(
                        (gvk, ns, name)
                    )
            return DONE
        key = key_or_cluster
        work = self.store.get("Work", key)
        cluster = cluster_of_execution_namespace(key.split("/", 1)[0])
        if work is None or cluster is None:
            return DONE
        cluster_obj = self.store.get("Cluster", cluster)
        if cluster_obj is not None and cluster_obj.spec.sync_mode == "Pull":
            return DONE  # the in-cluster agent applies Pull-mode works
        if work.spec.suspend_dispatching:
            if set_condition(
                work.status.conditions,
                Condition(
                    type="Dispatching", status=False, reason="SuspendDispatching"
                ),
            ):
                self._apply_status(work)
            return DONE
        manifests = self.rehydrator.manifests(work)
        if manifests is None:
            # template not mirrored yet: park on its digest (the watch
            # unparks) AND requeue under backoff as a belt-and-braces
            self._awaiting_template.setdefault(
                work.spec.workload_template.digest, set()
            ).add(item)
            return REQUEUE
        try:
            for workload in manifests:
                self.watcher.create_or_update(
                    cluster, workload,
                    conflict_resolution=work.spec.conflict_resolution,
                )
        except ConflictError as e:
            if set_condition(
                work.status.conditions,
                Condition(
                    type=WORK_APPLIED, status=False,
                    reason="ResourceConflict", message=str(e),
                ),
            ):
                self._apply_status(work)
            return DONE  # permanent until the member object changes
        except UnreachableError:
            if set_condition(
                work.status.conditions,
                Condition(type=WORK_APPLIED, status=False, reason="ClusterUnreachable"),
            ):
                self._apply_status(work)
            return REQUEUE
        if set_condition(
            work.status.conditions,
            Condition(type=WORK_APPLIED, status=True, reason="AppliedSuccessful"),
        ):
            self._apply_status(work)
        return DONE


class WorkStatusController:
    """Member object events -> Work.Status.ManifestStatuses (+ recreation of
    deleted-but-desired objects)."""

    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        members: MemberClientRegistry,
        interpreter: ResourceInterpreter,
        work_index: Optional[WorkIndex] = None,
    ) -> None:
        self.store = store
        self.members = members
        self.interpreter = interpreter
        self.work_index = work_index or WorkIndex(store)
        self.rehydrator = TemplateRehydrator(store)
        # member-event keys parked on a template that has not mirrored
        # yet (the recreate path needs the rehydrated manifest); the
        # WorkloadTemplate watch unparks them — REQUEUE alone drops the
        # key after MAX_RETRIES in cooperative mode
        self._awaiting_template: dict[str, set] = {}
        self.worker = runtime.new_worker("work-status", self._reconcile)
        # rehydrator eviction: without these the decode/render caches
        # grow with ALL-TIME work/template churn
        store.watch("Work", self._on_work_event, replay=False)
        store.watch(
            "WorkloadTemplate", self._on_template_event, replay=False
        )
        for name in members.names():
            client = members.get(name)
            if client is not None:
                client.watch(self._on_member_event)

    def watch_member(self, member) -> None:
        member.watch(self._on_member_event)

    def _on_member_event(self, event: MemberEvent) -> None:
        self.worker.enqueue(
            (event.cluster, event.gvk, event.namespace, event.name, event.type)
        )

    def _find_work(self, cluster: str, gvk: str, namespace: str, name: str):
        """(work, desired manifest | None) for a member target. For
        template-delta works the identity check rides the ref and the
        manifest rehydrates lazily; a missing template answers (work,
        None) so the recreate path can REQUEUE instead of dropping."""
        work = self.work_index.work_for_target(cluster, gvk, namespace, name)
        if work is None:
            return None, None
        tref = work.spec.workload_template
        if tref is not None and tref.digest:
            if (
                f"{tref.api_version}/{tref.kind}" == gvk
                and tref.namespace == namespace
                and tref.name == name
            ):
                manifests = self.rehydrator.manifests(work)
                return work, manifests[0] if manifests else None
            return None, None
        for workload in work.spec.workload:
            if (
                f"{workload.api_version}/{workload.kind}" == gvk
                and workload.meta.namespace == namespace
                and workload.meta.name == name
            ):
                return work, workload
        return None, None

    def _on_work_event(self, event) -> None:
        if event.type == "Deleted":
            self.rehydrator.forget_work(event.key)

    def _on_template_event(self, event) -> None:
        if event.type == "Deleted":
            self.rehydrator.forget_digest(event.key)
            return
        parked = self._awaiting_template.pop(event.key, None)
        if parked:
            for key in parked:
                self.worker.enqueue(key)

    def _reconcile(self, key) -> Optional[str]:
        cluster, gvk, namespace, name, event_type = key
        work, desired = self._find_work(cluster, gvk, namespace, name)
        if work is None:
            return DONE
        member = self.members.get(cluster)
        if member is None:
            return DONE
        try:
            observed = member.get(gvk, namespace, name)
        except UnreachableError:
            return REQUEUE
        if observed is None:
            # recreate deleted-but-desired (work_status_controller.go:311)
            if not work.spec.preserve_resources_on_deletion:
                if desired is None:
                    # template not mirrored yet: park on the digest (the
                    # watch unparks) AND requeue as a belt-and-braces
                    self._awaiting_template.setdefault(
                        work.spec.workload_template.digest, set()
                    ).add(key)
                    return REQUEUE
                try:
                    ObjectWatcher(self.members, self.interpreter).create_or_update(
                        cluster, desired
                    )
                except UnreachableError:
                    return REQUEUE
            return DONE
        status = self.interpreter.reflect_status(observed)
        # health is Unknown until the member reports any status — a fresh
        # object is not "Unhealthy" (failover must not fire on it)
        if status is None:
            health = "Unknown"
        else:
            health = (
                "Healthy" if self.interpreter.interpret_health(observed) else "Unhealthy"
            )
        identifier = observed.object_reference()
        updated = False
        for ms in work.status.manifest_statuses:
            if (
                ms.identifier.gvk == identifier.gvk
                and ms.identifier.namespaced_key == identifier.namespaced_key
            ):
                if ms.status != status or ms.health != health:
                    ms.status = status
                    ms.health = health
                    updated = True
                break
        else:
            work.status.manifest_statuses.append(
                ManifestStatus(identifier=identifier, status=status, health=health)
            )
            updated = True
        if updated:
            self.store.apply(work)
        return DONE


class BindingStatusController:
    """Work.Status -> ResourceBinding.Status.AggregatedStatus (+ FullyApplied
    condition), then template status write-back via the detector."""

    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        detector,
        work_index: Optional[WorkIndex] = None,
    ) -> None:
        self.store = store
        self.detector = detector
        self.work_index = work_index or WorkIndex(store)
        # per-drain write set: binding status updates flush as one batch
        # (then write back template statuses for exactly those bindings)
        self._buffering = False
        self._pending: list = []
        self.worker = runtime.new_worker(
            "binding-status", self._reconcile,
            reconcile_batch=self._reconcile_batch,
        )
        store.watch("Work", self._on_work_event)

    def _on_work_event(self, event) -> None:
        key = event.obj.meta.labels.get(WORK_BINDING_LABEL)
        if key:
            self.worker.enqueue(key)

    def _reconcile_batch(self, refs) -> dict:
        out: dict = {}
        self._buffering = True
        try:
            for ref in refs:
                out[ref] = self._reconcile(ref)
        finally:
            self._buffering = False
            self._flush()
        return out

    def _commit(self, rb) -> None:
        if self._buffering:
            self._pending.append(rb)
            return
        self.store.apply(rb)
        if self.detector is not None:
            self.detector.write_back_status(rb)

    def _flush(self) -> None:
        pending, self._pending = self._pending, []
        if not pending:
            return
        apply_many = getattr(self.store, "apply_many", None)
        failed: set[int] = set()
        if apply_many is not None:
            for rb, _err in apply_many(pending):
                failed.add(id(rb))
                # rejected status write: re-aggregate this binding (the
                # unbatched path raised and the worker requeued)
                self.worker.enqueue(
                    binding_ref(type(rb).KIND, rb.meta.namespaced_name)
                )
        else:
            for rb in pending:
                self.store.apply(rb)
        if self.detector is not None:
            for rb in pending:
                if id(rb) not in failed:
                    self.detector.write_back_status(rb)

    def _reconcile(self, ref: str) -> Optional[str]:
        kind, _, key = ref.partition(":")
        if kind not in BINDING_KINDS:
            return DONE
        rb = self.store.get(kind, key)
        if rb is None:
            return DONE
        items: list[AggregatedStatusItem] = []
        applied_clusters = set()
        for work in self.work_index.works_for(ref):
            cluster = cluster_of_execution_namespace(work.meta.namespace)
            if cluster is None:
                continue
            applied_cond = next(
                (c for c in work.status.conditions if c.type == WORK_APPLIED),
                None,
            )
            applied = applied_cond is not None and applied_cond.status
            if applied:
                applied_clusters.add(cluster)
            if work.status.manifest_statuses:
                for ms in work.status.manifest_statuses:
                    items.append(
                        AggregatedStatusItem(
                            cluster_name=cluster,
                            status=ms.status,
                            applied=applied,
                            health=ms.health,
                        )
                    )
            elif applied_cond is not None and not applied:
                # a Work that failed to apply (conflict, unreachable) never
                # reports manifest statuses — the failure must still be
                # visible in the binding aggregation (the reference emits
                # per-manifest items with Applied=false + AppliedMessage)
                items.append(
                    AggregatedStatusItem(
                        cluster_name=cluster,
                        status=None,
                        applied=False,
                        health="Unknown",
                        applied_message=applied_cond.message,
                    )
                )
        items.sort(key=lambda i: i.cluster_name)
        target_clusters = {tc.name for tc in rb.spec.clusters}
        status_changed = rb.status.aggregated_status != items
        rb.status.aggregated_status = items
        cond_changed = set_condition(
            rb.status.conditions,
            Condition(
                type=FULLY_APPLIED,
                status=bool(target_clusters) and target_clusters <= applied_clusters,
                reason="FullyAppliedSuccess"
                if target_clusters <= applied_clusters
                else "FullyAppliedFailed",
            ),
        )
        if status_changed or cond_changed:
            self._commit(rb)
        return DONE
