"""Override manager: applies (Cluster)OverridePolicies to per-cluster copies.

Ref: pkg/util/overridemanager (987 LoC): plaintext JSONPatch overriders plus
image/command/args/labels/annotations shorthands, rule-per-target-cluster,
cluster-scoped policies applied before namespaced ones, each sorted by name
(overridemanager.go applyRules ordering).
"""

from __future__ import annotations

from ..utils.clone import clone_resource
from typing import Any, Optional, Sequence

from ..api.cluster import Cluster
from ..api.core import Resource
from ..api.policy import (
    ClusterOverridePolicy,
    OverridePolicy,
    Overriders,
    ResourceSelector,
)


def resource_matches_selector(obj: Resource, sel: ResourceSelector) -> bool:
    if sel.api_version and sel.api_version != obj.api_version:
        return False
    if sel.kind and sel.kind != obj.kind:
        return False
    if sel.namespace and sel.namespace != obj.meta.namespace:
        return False
    if sel.name and sel.name != obj.meta.name:
        return False
    if sel.label_selector is not None and not sel.label_selector.matches(
        obj.meta.labels
    ):
        return False
    return True


def resource_matches_selectors(obj: Resource, selectors: Sequence[ResourceSelector]) -> bool:
    return any(resource_matches_selector(obj, s) for s in selectors)


# --- JSONPatch-style path ops ------------------------------------------------


def _resolve_parent(root: Any, path: str) -> tuple[Any, str]:
    parts = [p for p in path.strip("/").split("/") if p != ""]
    if not parts:
        raise ValueError(f"empty override path {path!r}")
    node = root
    for p in parts[:-1]:
        if isinstance(node, list):
            node = node[int(p)]
        else:
            node = node.setdefault(p, {})
    return node, parts[-1]


def apply_json_patch(doc: dict, op: str, path: str, value: Any) -> None:
    parent, leaf = _resolve_parent(doc, path)
    if isinstance(parent, list):
        idx = int(leaf) if leaf != "-" else len(parent)
        if op == "add":
            parent.insert(idx, value)
        elif op == "replace":
            parent[idx] = value
        elif op == "remove":
            del parent[idx]
        else:
            raise ValueError(f"unknown op {op}")
    else:
        if op in ("add", "replace"):
            parent[leaf] = value
        elif op == "remove":
            parent.pop(leaf, None)
        else:
            raise ValueError(f"unknown op {op}")


def _split_image(image: str) -> tuple[str, str, str]:
    """image -> (registry, repository, tag/digest)."""
    tag = ""
    rest = image
    if "@" in image:
        rest, tag = image.split("@", 1)
        tag = "@" + tag
    elif ":" in image.rsplit("/", 1)[-1]:
        rest, t = image.rsplit(":", 1)
        tag = ":" + t
    if "/" in rest:
        first, remainder = rest.split("/", 1)
        if "." in first or ":" in first or first == "localhost":
            return first, remainder, tag
    return "", rest, tag


def _join_image(registry: str, repo: str, tag: str) -> str:
    head = f"{registry}/{repo}" if registry else repo
    return head + tag


def apply_overriders(obj: Resource, overriders: Overriders) -> None:
    for po in overriders.plaintext:
        doc = {"spec": obj.spec, "metadata": {"labels": obj.meta.labels,
                                              "annotations": obj.meta.annotations}}
        apply_json_patch(doc, po.operator, po.path, po.value)
    for io in overriders.image_overrider:
        containers = obj.spec.get("template", {}).get("spec", {}).get("containers", [])
        if obj.kind == "Pod":
            containers = obj.spec.get("containers", [])
        for ctr in containers:
            image = ctr.get("image", "")
            if not image:
                continue
            registry, repo, tag = _split_image(image)
            if io.component == "Registry":
                registry = _edit(registry, io.operator, io.value)
            elif io.component == "Repository":
                repo = _edit(repo, io.operator, io.value)
            elif io.component == "Tag":
                new = _edit(tag.lstrip(":@"), io.operator, io.value)
                tag = f":{new}" if new else ""
            ctr["image"] = _join_image(registry, repo, tag)
    for co in overriders.command_overrider:
        _edit_container_list(obj, co.container_name, "command", co.operator, co.value)
    for ao in overriders.args_overrider:
        _edit_container_list(obj, ao.container_name, "args", ao.operator, ao.value)
    for lo in overriders.labels_overrider:
        _apply_map_overrider(obj.meta.labels, lo.operator, lo.value)
    for ano in overriders.annotations_overrider:
        _apply_map_overrider(obj.meta.annotations, ano.operator, ano.value)
    for fo in getattr(overriders, "field_overrider", []):
        _apply_field_overrider(obj, fo)


def _apply_field_overrider(obj: Resource, fo) -> None:
    """FieldOverrider (override_types.go:266-310): the field at field_path
    holds an embedded JSON/YAML document as a string — parse it, patch at
    each operation's sub-path, re-serialize in the same format."""
    import json as _json

    import yaml as _yaml

    if not fo.json and not fo.yaml:
        return  # no operations: never parse/re-serialize (format-preserving)
    doc = {"spec": obj.spec, "metadata": {"labels": obj.meta.labels,
                                          "annotations": obj.meta.annotations}}
    parent, leaf = _resolve_parent(doc, fo.field_path)
    current = parent[leaf] if isinstance(parent, dict) else parent[int(leaf)]
    if not isinstance(current, str):
        raise ValueError(
            f"fieldOverrider path {fo.field_path!r} must point at an "
            "embedded-document string"
        )
    is_json = bool(fo.json)
    embedded = _json.loads(current) if is_json else _yaml.safe_load(current)
    for op in fo.json or fo.yaml:
        apply_json_patch(embedded, op.operator, op.sub_path, op.value)
    rendered = (
        _json.dumps(embedded)
        if is_json
        else _yaml.safe_dump(embedded, default_flow_style=False)
    )
    if isinstance(parent, dict):
        parent[leaf] = rendered
    else:
        parent[int(leaf)] = rendered


def _edit(current: str, op: str, value: str) -> str:
    if op == "replace":
        return value
    if op == "add":
        return current + value
    if op == "remove":
        return ""
    raise ValueError(f"unknown image op {op}")


def _edit_container_list(
    obj: Resource, container_name: str, field: str, op: str, value: list[str]
) -> None:
    pod_spec = obj.spec if obj.kind == "Pod" else obj.spec.get("template", {}).get(
        "spec", {}
    )
    for ctr in pod_spec.get("containers", []):
        if container_name and ctr.get("name") != container_name:
            continue
        current = list(ctr.get(field, []))
        if op == "add":
            current.extend(value)
        elif op == "remove":
            current = [v for v in current if v not in set(value)]
        ctr[field] = current


def _apply_map_overrider(target: dict[str, str], op: str, value: dict[str, str]) -> None:
    if op in ("add", "replace"):
        target.update(value)
    elif op == "remove":
        for k in value:
            target.pop(k, None)


class OverrideManager:
    """Applies matching override policies for a (resource, cluster) pair.
    ClusterOverridePolicies first, then namespace-scoped, each name-sorted
    (overridemanager.go ApplyOverridePolicies)."""

    def __init__(self, store) -> None:
        self.store = store

    def overrides_match(self, obj: Resource, cluster: Cluster) -> bool:
        """Would ``apply_overrides`` transform this (resource, cluster)
        pair? Match-only probe — no clone, no overrider application (the
        template-delta renderer asks this per target per rebuild; paying
        the full transform just to discard it doubled every overridden
        target's cost). Sound against the chained-match subtlety in
        ``apply_overrides`` (later policies match the progressively
        overridden object): any transform chain begins with some policy
        matching the ORIGINAL object, so "no policy matches the original"
        ⇔ "apply_overrides returns the object unchanged"."""
        for policy in self._policies_for(obj):
            if not resource_matches_selectors(
                obj, policy.spec.resource_selectors
            ):
                continue
            for rule in policy.spec.override_rules:
                if (
                    rule.target_cluster is None
                    or rule.target_cluster.matches(cluster)
                ):
                    return True
        return False

    def _policies_for(self, obj: Resource) -> list:
        cops = sorted(
            self.store.list("ClusterOverridePolicy"), key=lambda p: p.meta.name
        )
        ops = sorted(
            (
                p
                for p in self.store.list("OverridePolicy")
                if p.meta.namespace == obj.meta.namespace
            ),
            key=lambda p: p.meta.name,
        )
        return list(cops) + list(ops)

    def apply_overrides(self, obj: Resource, cluster: Cluster) -> Resource:
        # clone lazily: most (resource, cluster) pairs match no rule, and
        # the unconditional copy was a top propagation-storm cost. Callers
        # treat an identical return as "no overrides applied".
        out = None
        for policy in self._policies_for(obj):
            cur = out if out is not None else obj
            if not resource_matches_selectors(cur, policy.spec.resource_selectors):
                continue
            for rule in policy.spec.override_rules:
                if rule.target_cluster is not None and not rule.target_cluster.matches(
                    cluster
                ):
                    continue
                if out is None:
                    out = clone_resource(obj)
                apply_overriders(out, rule.overriders)
        return out if out is not None else obj
