"""Remedy controller + cluster-api discovery + pull-mode agent.

Ref:
- remedy-controller (pkg/controllers/remediation/, pkg/apis/remedy):
  `Remedy` CRs match clusters by decision conditions (cluster condition
  types) and apply actions (TrafficControl) recorded on the cluster.
- clusterdiscovery (pkg/clusterdiscovery/clusterapi/): auto-join clusters
  surfaced by an infrastructure inventory.
- karmada-agent (cmd/agent): runs inside Pull-mode member clusters — pulls
  Works destined for its cluster from the control plane, applies them
  locally, pushes status back. Here the agent is an object bound to one
  member cluster running the same execution/status logic in pull direction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api.cluster import PULL, Cluster
from ..api.core import Condition, ObjectMeta, is_condition_true, set_condition
from ..api.work import WORK_APPLIED, ManifestStatus, Work
from ..utils import DONE, REQUEUE, Runtime, Store
from ..utils.member import MemberCluster, UnreachableError
from .propagation import execution_namespace

REMEDY_ACTION_TRAFFIC_CONTROL = "TrafficControl"
REMEDY_ACTIONS_ANNOTATION = "remedy.karmada.io/traffic-control"


@dataclass
class DecisionMatch:
    cluster_condition_type: str = "ServiceDomainNameResolutionReady"
    cluster_condition_status: str = "False"


@dataclass
class RemedySpec:
    cluster_affinity: Optional[object] = None  # api.policy.ClusterAffinity
    decision_matches: list[DecisionMatch] = field(default_factory=list)
    actions: list[str] = field(default_factory=lambda: [REMEDY_ACTION_TRAFFIC_CONTROL])


@dataclass
class Remedy:
    KIND = "Remedy"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: RemedySpec = field(default_factory=RemedySpec)


class RemedyController:
    def __init__(self, store: Store, runtime: Runtime) -> None:
        self.store = store
        self.worker = runtime.new_worker("remedy", self._reconcile)
        store.watch("Remedy", lambda e: self._requeue_clusters())
        store.watch("Cluster", lambda e: self.worker.enqueue(e.key))

    def _requeue_clusters(self) -> None:
        for cluster in self.store.list("Cluster"):
            self.worker.enqueue(cluster.name)

    def _matches(self, remedy: Remedy, cluster: Cluster) -> bool:
        if remedy.spec.cluster_affinity is not None and not (
            remedy.spec.cluster_affinity.matches(cluster)
        ):
            return False
        if not remedy.spec.decision_matches:
            return True  # unconditional remedy
        for match in remedy.spec.decision_matches:
            for cond in cluster.status.conditions:
                # statuses are "True"/"False" strings or bools depending on
                # the producer; normalize without truthiness ("False" is
                # truthy as a string)
                status = (
                    cond.status
                    if isinstance(cond.status, str)
                    else ("True" if cond.status else "False")
                )
                if (
                    cond.type == match.cluster_condition_type
                    and status == match.cluster_condition_status
                ):
                    return True
        return False

    def _reconcile(self, key: str) -> Optional[str]:
        cluster = self.store.get("Cluster", key)
        if cluster is None:
            return DONE
        actions: set[str] = set()
        for remedy in self.store.list("Remedy"):
            if self._matches(remedy, cluster):
                actions.update(remedy.spec.actions)
        current = cluster.meta.annotations.get(REMEDY_ACTIONS_ANNOTATION)
        wanted = ",".join(sorted(actions)) if actions else None
        if wanted != current:
            if wanted is None:
                cluster.meta.annotations.pop(REMEDY_ACTIONS_ANNOTATION, None)
            else:
                cluster.meta.annotations[REMEDY_ACTIONS_ANNOTATION] = wanted
            self.store.apply(cluster)
        return DONE


SERVICE_DNS_CONDITION = "ServiceDomainNameResolutionReady"


class ServiceNameResolutionDetector:
    """In-cluster coredns-failure detector example
    (pkg/servicenameresolutiondetector/, cmd/service-name-resolution-detector-
    example): periodically probes service-name resolution inside one member
    cluster and reports the ServiceDomainNameResolutionReady condition on the
    Cluster object — the decision condition the Remedy controller matches on.

    The probe is pluggable; the default resolves by checking that the
    cluster's DNS Service (kube-system/kube-dns) exists and the member is
    reachable — the in-proc stand-in for an A-record lookup through coredns.
    """

    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        member: MemberCluster,
        probe=None,
    ) -> None:
        self.store = store
        self.member = member
        self.probe = probe or self._default_probe
        self.active = True  # cleared on unjoin/replacement (tickers are
        # permanent, so deactivation is the deregistration mechanism)
        runtime.add_ticker(self.detect_once)

    def _default_probe(self) -> bool:
        try:
            return self.member.get("v1/Service", "kube-system", "kube-dns") is not None
        except UnreachableError:
            return False

    def detect_once(self) -> None:
        if not self.active:
            return
        cluster = self.store.get("Cluster", self.member.name)
        if cluster is None:
            return
        healthy = bool(self.probe())
        changed = set_condition(
            cluster.status.conditions,
            Condition(
                type=SERVICE_DNS_CONDITION,
                status=healthy,
                reason="DomainNameResolved" if healthy else "DomainNameResolutionFailed",
            ),
        )
        if changed:
            self.store.apply(cluster)


class ClusterDiscoveryController:
    """Auto-join clusters from an infrastructure inventory
    (pkg/clusterdiscovery/clusterapi). The inventory is a callable returning
    (name, MemberCluster) pairs — the cluster-api informer analogue."""

    def __init__(self, control_plane, inventory) -> None:
        self.control_plane = control_plane
        self.inventory = inventory
        control_plane.runtime.add_ticker(self.discover_once)

    def discover_once(self) -> None:
        from ..utils.builders import new_cluster

        for name, member in self.inventory():
            if self.control_plane.store.get("Cluster", name) is None:
                cluster = new_cluster(name)
                self.control_plane.join_cluster(cluster, member)


class KarmadaAgent:
    """Pull-mode agent for one member cluster (cmd/agent): pulls Works for
    its execution namespace, applies them into the local cluster, reflects
    status into the Work — the same propagation semantics with the member
    driving. Push-mode controllers skip Pull clusters."""

    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        member: MemberCluster,
        interpreter,
        clock=None,
    ) -> None:
        import time as _time

        from .propagation import TemplateRehydrator

        self.store = store
        self.member = member
        self.interpreter = interpreter
        self.clock = clock or _time.time
        self.ns = execution_namespace(member.name)
        # template-delta rehydration (ISSUE 11): Works arriving over the
        # bus may carry (digest, patch) instead of a full manifest; the
        # agent renders them against the mirrored WorkloadTemplate
        self.rehydrator = TemplateRehydrator(store)
        self._awaiting_template: dict[str, set] = {}
        # per-drain write set: status reflections flush as one batched
        # write-through (one ApplyBatch RPC over the bus facade)
        self._buffering = False
        self._pending: list = []
        self.worker = runtime.new_worker(
            f"agent-{member.name}", self._reconcile,
            reconcile_batch=self._reconcile_batch,
        )
        store.watch("Work", self._on_work_event)
        store.watch("WorkloadTemplate", self._on_template_event, replay=False)
        member.watch(self._on_member_event)
        runtime.add_ticker(self._renew_lease)

    def _renew_lease(self) -> None:
        """Heartbeat: the agent renews its cluster Lease while it can reach
        the control plane; the cluster-status controller derives Pull-mode
        Ready from this freshness (the plane cannot probe a Pull member)."""
        if not self.member.reachable:
            return
        from ..api.cluster import Lease
        from ..api.core import ObjectMeta

        lease = self.store.get("Lease", self.member.name) or Lease(
            meta=ObjectMeta(name=self.member.name)
        )
        lease.renew_time = self.clock()
        self.store.apply(lease)

    def _on_work_event(self, event) -> None:
        if event.obj.meta.namespace == self.ns:
            if event.type == "Deleted":
                self.rehydrator.forget_work(event.key)
                # drop any parked entry for the deleted Work (its
                # template may never arrive)
                for parked in self._awaiting_template.values():
                    parked.discard(event.key)
            self.worker.enqueue(event.key)

    def _on_template_event(self, event) -> None:
        if event.type == "Deleted":
            self.rehydrator.forget_digest(event.key)
            return
        parked = self._awaiting_template.pop(event.key, None)
        if parked:
            for key in parked:
                self.worker.enqueue(key)

    def _on_member_event(self, event) -> None:
        for work in self.store.list("Work", self.ns):
            tref = work.spec.workload_template
            if tref is not None and tref.digest:
                if (
                    f"{tref.api_version}/{tref.kind}" == event.gvk
                    and tref.namespace == event.namespace
                    and tref.name == event.name
                ):
                    self.worker.enqueue(work.meta.namespaced_name)
                continue
            for w in work.spec.workload:
                if (
                    f"{w.api_version}/{w.kind}" == event.gvk
                    and w.meta.namespace == event.namespace
                    and w.meta.name == event.name
                ):
                    self.worker.enqueue(work.meta.namespaced_name)

    def _reconcile_batch(self, keys) -> dict:
        out: dict = {}
        self._buffering = True
        try:
            for key in keys:
                out[key] = self._reconcile(key)
        finally:
            self._buffering = False
            self._flush()
        return out

    def _commit(self, work) -> None:
        if self._buffering:
            self._pending.append(work)
        else:
            self.store.apply(work)

    def _flush(self) -> None:
        pending, self._pending = self._pending, []
        if not pending:
            return
        apply_many = getattr(self.store, "apply_many", None)
        if apply_many is not None:
            for work, _err in apply_many(pending):
                # rejected status reflection: retry the Work (the
                # unbatched path raised and the worker requeued)
                self.worker.enqueue(work.meta.namespaced_name)
        else:
            for work in pending:
                self.store.apply(work)

    def _reconcile(self, key: str) -> Optional[str]:
        work = self.store.get("Work", key)
        if work is None or work.spec.suspend_dispatching:
            return DONE
        if not self.member.reachable:
            return DONE  # agent inside the cluster: unreachable means dead
        manifests = self.rehydrator.manifests(work)
        if manifests is None:
            # template not mirrored yet (bus replay can deliver the Work
            # first): park on the digest, the template watch unparks
            self._awaiting_template.setdefault(
                work.spec.workload_template.digest, set()
            ).add(key)
            return REQUEUE
        changed = False
        for desired in manifests:
            gvk = f"{desired.api_version}/{desired.kind}"
            observed = self.member.get(
                gvk, desired.meta.namespace, desired.meta.name
            )
            if observed is None:
                import copy

                self.member.apply(copy.deepcopy(desired))
                observed = self.member.get(
                    gvk, desired.meta.namespace, desired.meta.name
                )
            status = self.interpreter.reflect_status(observed)
            health = (
                "Unknown"
                if status is None
                else (
                    "Healthy"
                    if self.interpreter.interpret_health(observed)
                    else "Unhealthy"
                )
            )
            identifier = observed.object_reference()
            for ms in work.status.manifest_statuses:
                if ms.identifier.namespaced_key == identifier.namespaced_key:
                    if ms.status != status or ms.health != health:
                        ms.status, ms.health = status, health
                        changed = True
                    break
            else:
                work.status.manifest_statuses.append(
                    ManifestStatus(identifier=identifier, status=status, health=health)
                )
                changed = True
        if set_condition(
            work.status.conditions,
            Condition(type=WORK_APPLIED, status=True, reason="AppliedSuccessful"),
        ):
            changed = True
        if changed:
            self._commit(work)
        return DONE
