"""Pod-level replica calculator for FederatedHPA.

The kube HPA replica calculator re-derived over the federation's merged pod
list, with karmada's calibration twist (results determined by global ready
pods or metrics are divided by ``calibration`` = materialized replicas /
template replicas).

Ref (semantics re-derived, structure redesigned for the store-native plane):
- pkg/controllers/federatedhpa/replica_calculator.go:62-314 (the five
  calculators + usage-ratio count), :316-378 (groupPods / pod requests)
- pkg/controllers/federatedhpa/metrics/utilization.go:26-66 (ratio helpers)
- pkg/controllers/federatedhpa/federatedhpa_controller.go:601 (calibration)

The pod model is a flat ``PodSample`` per federated pod instead of
corev1.Pod + a separate PodMetricsInfo map: one record carries phase,
readiness ages, the resource request, and the (optional) metric sample.
Timestamps are modeled as ages-relative-to-now so tests and controllers
need no wall-clock fixtures.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

DEFAULT_TOLERANCE = 0.1
DEFAULT_CPU_INITIALIZATION_PERIOD = 300.0  # --horizontal-pod-autoscaler-*
DEFAULT_INITIAL_READINESS_DELAY = 30.0


class MetricsError(ValueError):
    """Raised where the reference calculator returns an error (no pods, no
    ready metrics, missing requests, disjoint request/metric sets)."""


@dataclass
class PodSample:
    """One pod of the federated pod list (pods + its metric sample).

    ``value`` is the metric sample in milli-units (None = the metrics
    source returned nothing for this pod — the reference's missingPods).
    Ages are seconds relative to "now":
    - start_age: since pod start (None = no startTime recorded, which the
      reference treats as CPU-unready);
    - transition_age: since the Ready condition last transitioned (None =
      no Ready condition recorded — also CPU-unready);
    - sample_age: age of the metric sample; with ``window`` it models the
      reference's metric.Timestamp/metric.Window staleness check.
    Defaults describe a healthy long-running pod so member clusters can
    publish minimal samples.
    """

    name: str
    cluster: str = ""
    phase: str = "Running"  # Running | Pending | Failed | Succeeded
    ready: bool = True
    deleted: bool = False  # deletionTimestamp set
    request: Optional[int] = None  # resource request, milli-units
    value: Optional[int] = None  # metric sample, milli-units
    start_age: Optional[float] = 1e9
    transition_age: Optional[float] = 1e9
    sample_age: float = 0.0
    window: float = 60.0  # metric sample window (metricServerDefault)


@dataclass
class GroupedPods:
    ready_count: int = 0
    unready: set = field(default_factory=set)
    missing: set = field(default_factory=set)
    ignored: set = field(default_factory=set)


def group_pods(
    pods: list[PodSample],
    metrics: dict[str, int],
    resource: str,
    cpu_initialization_period: float,
    delay_of_initial_readiness: float,
) -> GroupedPods:
    """replica_calculator.go:316-360 groupPods. Failed/deleted pods are
    ignored, Pending pods are unready, pods without a metric sample are
    missing, and — for CPU only — pods whose sample predates readiness
    (still initialising, or never-ready within the initial delay) are
    unready."""
    g = GroupedPods()
    for pod in pods:
        if pod.deleted or pod.phase == "Failed":
            g.ignored.add(pod.name)
            continue
        if pod.phase == "Pending":
            g.unready.add(pod.name)
            continue
        if pod.name not in metrics:
            g.missing.add(pod.name)
            continue
        if resource == "cpu":
            if pod.transition_age is None or pod.start_age is None:
                g.unready.add(pod.name)
                continue
            if pod.start_age < cpu_initialization_period:
                # within the initialisation period: drop the sample if the
                # pod is unready or the sample predates one full metric
                # window after the last readiness transition
                # (metric.Timestamp < lastTransition + window  <=>
                #  sample_age > transition_age - window)
                unready = (
                    not pod.ready
                    or pod.sample_age > pod.transition_age - pod.window
                )
            else:
                # past initialisation: ignore only pods that are unready
                # and have never been ready (the transition happened within
                # the initial-readiness delay of pod start:
                # start + delay > lastTransition)
                unready = not pod.ready and (
                    pod.start_age - pod.transition_age
                    < delay_of_initial_readiness
                )
            if unready:
                g.unready.add(pod.name)
                continue
        g.ready_count += 1
    return g


def calculate_pod_requests(
    pods: list[PodSample], resource: str
) -> dict[str, int]:
    """replica_calculator.go:362-378 — every pod must carry a request for
    the scaled resource."""
    requests: dict[str, int] = {}
    for pod in pods:
        if pod.request is None:
            raise MetricsError(
                f"missing request for {resource} in Pod {pod.name}"
            )
        requests[pod.name] = pod.request
    return requests


def resource_utilization_ratio(
    metrics: dict[str, int],
    requests: dict[str, int],
    target_utilization: int,
) -> tuple[float, int, int]:
    """utilization.go:26-52 GetResourceUtilizationRatio ->
    (usage_ratio, current_utilization_pct, raw_average_value). Metrics
    without a matching request are treated as extraneous and skipped."""
    metrics_total = requests_total = entries = 0
    for name, value in metrics.items():
        if name not in requests:
            continue
        metrics_total += value
        requests_total += requests[name]
        entries += 1
    if requests_total == 0:
        raise MetricsError("no metrics returned matched known pods")
    current_utilization = (metrics_total * 100) // requests_total
    return (
        current_utilization / target_utilization,
        current_utilization,
        metrics_total // entries,
    )


def metric_usage_ratio(
    metrics: dict[str, int], target_usage: int
) -> tuple[float, int]:
    """utilization.go:54-66 GetMetricUsageRatio -> (ratio, avg_usage)."""
    current_usage = sum(metrics.values()) // len(metrics)
    return current_usage / target_usage, current_usage


class ReplicaCalculator:
    """replica_calculator.go:41-56 — tolerance dead-band + CPU readiness
    windows, shared by every metric flavor."""

    def __init__(
        self,
        tolerance: float = DEFAULT_TOLERANCE,
        cpu_initialization_period: float = DEFAULT_CPU_INITIALIZATION_PERIOD,
        delay_of_initial_readiness: float = DEFAULT_INITIAL_READINESS_DELAY,
    ) -> None:
        self.tolerance = tolerance
        self.cpu_initialization_period = cpu_initialization_period
        self.delay_of_initial_readiness = delay_of_initial_readiness

    # -- Resource target: Utilization --------------------------------------

    def get_resource_replicas(
        self,
        current_replicas: int,
        target_utilization: int,
        resource: str,
        pods: list[PodSample],
        calibration: float = 1.0,
    ) -> tuple[int, int, int]:
        """replica_calculator.go:62-145 GetResourceReplicas ->
        (replicas, utilization_pct, raw_average_value)."""
        if not pods:
            raise MetricsError(
                "no pods returned by selector while calculating replica count"
            )
        metrics = {p.name: p.value for p in pods if p.value is not None}
        if not metrics:
            raise MetricsError("no metrics returned from resource metrics API")
        g = group_pods(
            pods, metrics, resource,
            self.cpu_initialization_period, self.delay_of_initial_readiness,
        )
        for name in g.ignored | g.unready:
            metrics.pop(name, None)
        requests = calculate_pod_requests(pods, resource)
        if not metrics:
            raise MetricsError("did not receive metrics for any ready pods")

        usage_ratio, utilization, raw_avg = resource_utilization_ratio(
            metrics, requests, target_utilization
        )
        scale_up_with_unready = bool(g.unready) and usage_ratio > 1.0
        if not scale_up_with_unready and not g.missing:
            if abs(1.0 - usage_ratio) <= self.tolerance:
                return current_replicas, utilization, raw_avg
            return (
                math.ceil(usage_ratio * g.ready_count / calibration),
                utilization,
                raw_avg,
            )

        if g.missing:
            if usage_ratio < 1.0:
                # scale-down: missing pods count as using all of the
                # request (or the target for targets above 100%)
                fallback = max(100, target_utilization)
                for name in g.missing:
                    metrics[name] = requests[name] * fallback // 100
            elif usage_ratio > 1.0:
                for name in g.missing:
                    metrics[name] = 0
        if scale_up_with_unready:
            for name in g.unready:
                metrics[name] = 0

        new_ratio, _, _ = resource_utilization_ratio(
            metrics, requests, target_utilization
        )
        if abs(1.0 - new_ratio) <= self.tolerance or (
            usage_ratio < 1.0 < new_ratio
        ) or (usage_ratio > 1.0 > new_ratio):
            return current_replicas, utilization, raw_avg
        new_replicas = math.ceil(new_ratio * len(metrics) / calibration)
        if (new_ratio < 1.0 and new_replicas > current_replicas) or (
            new_ratio > 1.0 and new_replicas < current_replicas
        ):
            return current_replicas, utilization, raw_avg
        return new_replicas, utilization, raw_avg

    # -- Resource target: AverageValue / Pods metric ------------------------

    def get_raw_resource_replicas(
        self,
        current_replicas: int,
        target_usage: int,
        resource: str,
        pods: list[PodSample],
        calibration: float = 1.0,
    ) -> tuple[int, int]:
        """replica_calculator.go:147-157 GetRawResourceReplicas ->
        (replicas, avg_usage)."""
        metrics = {p.name: p.value for p in pods if p.value is not None}
        return self._plain_metric_replicas(
            metrics, current_replicas, target_usage, resource, pods,
            calibration,
        )

    def get_metric_replicas(
        self,
        current_replicas: int,
        target_usage: int,
        metrics: dict[str, int],
        pods: list[PodSample],
        calibration: float = 1.0,
    ) -> tuple[int, int]:
        """replica_calculator.go:159-170 GetMetricReplicas (Pods metric
        flavor: the sample set comes from custom.metrics.k8s.io, the pod
        list from the workload) -> (replicas, avg_usage)."""
        return self._plain_metric_replicas(
            metrics, current_replicas, target_usage, "", pods, calibration
        )

    def _plain_metric_replicas(
        self,
        metrics: dict[str, int],
        current_replicas: int,
        target_usage: int,
        resource: str,
        pods: list[PodSample],
        calibration: float,
    ) -> tuple[int, int]:
        """replica_calculator.go:172-241 calcPlainMetricReplicas."""
        if not pods:
            raise MetricsError(
                "no pods returned by selector while calculating replica count"
            )
        metrics = dict(metrics)
        g = group_pods(
            pods, metrics, resource,
            self.cpu_initialization_period, self.delay_of_initial_readiness,
        )
        for name in g.ignored | g.unready:
            metrics.pop(name, None)
        if not metrics:
            raise MetricsError("did not receive metrics for any ready pods")

        usage_ratio, usage = metric_usage_ratio(metrics, target_usage)
        scale_up_with_unready = bool(g.unready) and usage_ratio > 1.0
        if not scale_up_with_unready and not g.missing:
            if abs(1.0 - usage_ratio) <= self.tolerance:
                return current_replicas, usage
            return (
                math.ceil(usage_ratio * g.ready_count / calibration),
                usage,
            )

        if g.missing:
            if usage_ratio < 1.0:
                # scale-down: missing pods count as using the full target
                for name in g.missing:
                    metrics[name] = target_usage
            elif usage_ratio > 1.0:
                for name in g.missing:
                    metrics[name] = 0
        if scale_up_with_unready:
            for name in g.unready:
                metrics[name] = 0

        new_ratio, _ = metric_usage_ratio(metrics, target_usage)
        if abs(1.0 - new_ratio) <= self.tolerance or (
            usage_ratio < 1.0 < new_ratio
        ) or (usage_ratio > 1.0 > new_ratio):
            return current_replicas, usage
        new_replicas = math.ceil(new_ratio * len(metrics) / calibration)
        if (new_ratio < 1.0 and new_replicas > current_replicas) or (
            new_ratio > 1.0 and new_replicas < current_replicas
        ):
            return current_replicas, usage
        return new_replicas, usage

    # -- Object metric ------------------------------------------------------

    def get_object_metric_replicas(
        self,
        current_replicas: int,
        target_usage: int,
        object_usage: int,
        pods: list[PodSample],
        calibration: float = 1.0,
    ) -> tuple[int, int]:
        """replica_calculator.go:243-254 GetObjectMetricReplicas (Value
        target on a described object) -> (replicas, usage)."""
        usage_ratio = object_usage / target_usage
        return (
            self.get_usage_ratio_replica_count(
                current_replicas, usage_ratio, pods, calibration
            ),
            object_usage,
        )

    def get_object_per_pod_metric_replicas(
        self,
        status_replicas: int,
        target_average_usage: int,
        object_usage: int,
        calibration: float = 1.0,
    ) -> tuple[int, int]:
        """replica_calculator.go:256-273 GetObjectPerPodMetricReplicas
        (AverageValue target on a described object) -> (replicas,
        per_pod_usage)."""
        replica_count = status_replicas
        usage_ratio = object_usage / (target_average_usage * replica_count)
        if abs(1.0 - usage_ratio) > self.tolerance:
            replica_count = math.ceil(
                object_usage / target_average_usage / calibration
            )
        usage = math.ceil(object_usage / status_replicas)
        return math.ceil(replica_count / calibration), usage

    def get_usage_ratio_replica_count(
        self,
        current_replicas: int,
        usage_ratio: float,
        pods: list[PodSample],
        calibration: float = 1.0,
    ) -> int:
        """replica_calculator.go:275-295 — ready-pod-scaled count, with the
        scale-to-zero special case bypassing tolerance."""
        if current_replicas != 0:
            if abs(1.0 - usage_ratio) <= self.tolerance:
                return current_replicas
            ready = self.get_ready_pods_count(pods)
            return math.ceil(usage_ratio * ready / calibration)
        return math.ceil(usage_ratio)

    @staticmethod
    def get_ready_pods_count(pods: list[PodSample]) -> int:
        """replica_calculator.go:300-314."""
        if not pods:
            raise MetricsError(
                "no pods returned by selector while calculating replica count"
            )
        return sum(
            1 for p in pods if p.phase == "Running" and p.ready
        )
