"""HPA scale-target marking + member-decided replica sync + unified auth.

Ref:
- hpaScaleTargetMarker (pkg/controllers/hpascaletargetmarker, 316 LoC):
  labels workloads targeted by a FederatedHPA so other controllers know the
  replica field is HPA-owned.
- deploymentReplicasSyncer (pkg/controllers/deploymentreplicassyncer,
  206 LoC): when member-side HPAs own replicas, sync the member-decided sum
  back onto the template so the control plane doesn't fight the members.
- unified-auth-controller (pkg/controllers/unifiedauth/, 335 LoC): sync
  RBAC for admin subjects into member clusters as Works.
"""

from __future__ import annotations

from typing import Optional

from ..api.core import ObjectMeta, Resource
from ..api.work import Work, WorkSpec
from ..utils import DONE, Runtime, Store
from .propagation import execution_namespace

HPA_TARGET_LABEL = "autoscaling.karmada.io/scale-target"
# marks workloads whose replica field is member-owned (retained on apply)
RETAIN_REPLICAS_LABEL = "resourcetemplate.karmada.io/retain-replicas"


class HpaScaleTargetMarker:
    def __init__(self, store: Store, runtime: Runtime) -> None:
        self.store = store
        self.worker = runtime.new_worker("hpa-marker", self._reconcile)
        store.watch("FederatedHPA", lambda e: self.worker.enqueue((e.key, e.type)))

    def _reconcile(self, key_type) -> Optional[str]:
        key, event_type = key_type
        hpa = self.store.get("FederatedHPA", key)
        ns = key.rpartition("/")[0]
        if hpa is None:
            # unmark any template that pointed at this HPA
            for res in self.store.list("Resource", ns or None):
                if res.meta.labels.get(HPA_TARGET_LABEL) == key:
                    del res.meta.labels[HPA_TARGET_LABEL]
                    self.store.apply(res)
            return DONE
        target = hpa.spec.scale_target_ref
        tkey = f"{ns}/{target.name}" if ns else target.name
        template = self.store.get("Resource", tkey)
        if template is None or template.kind != target.kind:
            return DONE
        changed = False
        if template.meta.labels.get(HPA_TARGET_LABEL) != key:
            template.meta.labels[HPA_TARGET_LABEL] = key
            changed = True
        if template.meta.labels.get(RETAIN_REPLICAS_LABEL) != "true":
            template.meta.labels[RETAIN_REPLICAS_LABEL] = "true"
            changed = True
        if changed:
            self.store.apply(template)
        return DONE


class DeploymentReplicasSyncer:
    """Member-decided replicas -> template (for HPA-marked workloads).
    Runs as a ticker: sums the member manifests' spec.replicas and writes the
    total back when it drifts."""

    def __init__(self, store: Store, runtime: Runtime, members) -> None:
        self.store = store
        self.members = members
        runtime.add_ticker(self.sync_once)

    def sync_once(self) -> None:
        for template in self.store.list("Resource"):
            if (
                template.kind != "Deployment"
                or HPA_TARGET_LABEL not in template.meta.labels
            ):
                continue
            key = template.meta.namespaced_name
            rb = self.store.get(
                "ResourceBinding", f"{template.meta.namespace}/{template.meta.name}-deployment"
            )
            if rb is None:
                continue
            total = 0
            seen = False
            for tc in rb.spec.clusters:
                member = self.members.get(tc.name)
                if member is None or not member.reachable:
                    continue
                obj = member.get(
                    "apps/v1/Deployment",
                    template.meta.namespace,
                    template.meta.name,
                )
                if obj is not None:
                    total += int(obj.spec.get("replicas", 0))
                    seen = True
            if seen and total != int(template.spec.get("replicas", 0)):
                template.spec["replicas"] = total
                self.store.apply(template)


class UnifiedAuthController:
    """Admin RBAC sync into members (pkg/controllers/unifiedauth): every
    cluster receives a ClusterRole/ClusterRoleBinding pair granting the
    configured subjects cluster-wide access through the aggregated proxy."""

    ROLE_NAME = "karmada-controller-manager:karmada-view"

    def __init__(self, store: Store, runtime: Runtime, subjects=("system:admin",)) -> None:
        self.store = store
        self.subjects = list(subjects)
        self.worker = runtime.new_worker("unified-auth", self._reconcile)
        store.watch("Cluster", lambda e: self.worker.enqueue(e.key))

    def _reconcile(self, key: str) -> Optional[str]:
        cluster = self.store.get("Cluster", key)
        if cluster is None:
            return DONE
        role = Resource(
            api_version="rbac.authorization.k8s.io/v1",
            kind="ClusterRole",
            meta=ObjectMeta(name=self.ROLE_NAME),
            spec={"rules": [{"apiGroups": ["*"], "resources": ["*"],
                             "verbs": ["get", "list", "watch"]}]},
        )
        binding = Resource(
            api_version="rbac.authorization.k8s.io/v1",
            kind="ClusterRoleBinding",
            meta=ObjectMeta(name=self.ROLE_NAME),
            spec={
                "roleRef": {"kind": "ClusterRole", "name": self.ROLE_NAME},
                "subjects": [{"kind": "User", "name": s} for s in self.subjects],
            },
        )
        ns = execution_namespace(cluster.name)
        wkey = f"{ns}/unified-auth"
        existing = self.store.get("Work", wkey)
        sig = [role.spec, binding.spec]
        if existing is not None and [w.spec for w in existing.spec.workload] == sig:
            return DONE
        self.store.apply(
            Work(
                meta=ObjectMeta(name="unified-auth", namespace=ns),
                spec=WorkSpec(workload=[role, binding]),
            )
        )
        return DONE
