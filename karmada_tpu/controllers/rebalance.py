"""Continuous descheduler: drift detection + bounded-disruption
re-placement (ISSUE 14 tentpole c).

Ref: the reference's workload rebalancer is ONE-SHOT — an operator
creates a WorkloadRebalancer naming workloads and the controller stamps
``RescheduleTriggeredAt`` once (workloadrebalancer_controller.go; PR 7
fixed the lastScheduledTime consumption so the trigger is exactly-once).
The descheduler (descheduler.go:141-241) reclaims unschedulable
replicas but never re-optimizes placements that merely drifted from
what a fresh solve would choose. This tier folds both into a background
loop: every round scores EVERY resident placement against current
availability/spread/caps by running one batched DRY solve through the
scheduler's own engine (the device-resident packed state — the scoring
pass rides the same fleet tables, batch-identity caches and quota
admission as a real wave, so a drift score can never recommend a
placement the real solve would not produce), then re-places the
worst-drifted bindings through the standard ``RescheduleTriggeredAt``
machinery, bounded by ``KARMADA_TPU_DESCHEDULE_MAX_DISRUPTION`` per
round.

Drift of one binding = the L1 replica distance between its resident
``spec.clusters`` and the fresh-solve ideal (fresh mode credits
surviving placements, so a placement the solve would keep scores 0 —
steady planes trigger nothing). Rounds are bounded-disruption by
construction: at most ``budget`` bindings are stamped, highest drift
first with arrival order breaking ties, and a binding whose previous
trigger is still unconsumed (``reschedule_triggered_at`` newer than
``last_scheduled_time``) is never re-stamped — the trigger is
exactly-once per drift episode. The numpy oracle
(``refimpl.preempt_np.rebalance_np``) re-derives the trigger set with
per-binding sequential divides sharing no selection code.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from ..utils import Store

#: disruption budget env knob (registered in utils.flags ENV_FLAGS)
BUDGET_ENV = "KARMADA_TPU_DESCHEDULE_MAX_DISRUPTION"
_DEFAULT_BUDGET = 64


def disruption_budget() -> int:
    """The per-round trigger cap; 0 disables the tier entirely."""
    raw = os.environ.get(BUDGET_ENV, "").strip()
    if not raw:
        return _DEFAULT_BUDGET
    try:
        return max(int(raw), 0)
    except ValueError:
        return _DEFAULT_BUDGET


class ContinuousDescheduler:
    """Background drift detector over the whole binding plane.

    Constructed with the SchedulerController so scoring rides its
    engine (``dry_solve``) — the device-resident packed state, quota
    snapshot and caches are shared, never duplicated."""

    def __init__(
        self,
        store: Store,
        runtime,
        scheduler,
        clock=None,
    ) -> None:
        self.store = store
        self.scheduler = scheduler
        self.clock = clock or time.time
        #: addon on/off switch — ticker registration is permanent, so
        #: disable gates the TICKER path (the Descheduler pattern);
        #: explicit rebalance_once() calls always run (bench/test drivers
        #: drive rounds manually with the ticker off)
        self.active = True
        #: stats of the last round (bench/test surface)
        self.last_round: dict = {}
        runtime.add_ticker(self._tick)

    def _tick(self) -> None:
        if self.active:
            self.rebalance_once()

    def _candidates(self):
        """(kind, rb, problem) for every bound binding eligible for a
        drift score: assigned replicas, a real workload, no in-flight
        eviction, and no still-unconsumed reschedule trigger (the
        exactly-once rule)."""
        out = []
        for kind in ("ResourceBinding", "ClusterResourceBinding"):
            for rb in self.store.list(kind):
                if (
                    rb.spec.scheduler_name != self.scheduler.scheduler_name
                    or rb.spec.replicas <= 0
                    or not rb.spec.clusters
                    or rb.spec.graceful_eviction_tasks
                ):
                    continue
                if rb.spec.reschedule_triggered_at is not None and (
                    rb.status.last_scheduled_time is None
                    or rb.spec.reschedule_triggered_at
                    > rb.status.last_scheduled_time
                ):
                    continue  # previous trigger not consumed yet
                key = rb.meta.namespaced_name
                problem = self.scheduler._problem_for(key, rb, True)
                out.append((kind, rb, problem))
        return out

    def rebalance_once(self) -> Optional[dict]:
        """One bounded-disruption drift round. Returns the round stats
        (also kept as ``last_round``) or None when disabled/empty."""
        budget = disruption_budget()
        from ..utils.metrics import (
            desched_disruption_budget,
            desched_disruption_used,
        )

        desched_disruption_budget.set(budget)
        if budget <= 0:
            return None
        cands = self._candidates()
        if not cands:
            desched_disruption_used.set(0)
            return None
        results = self.scheduler.dry_solve([p for _, _, p in cands])
        drifts = []  # (drift, arrival index, kind, rb)
        for idx, ((kind, rb, problem), res) in enumerate(
            zip(cands, results)
        ):
            if not res.success:
                continue  # nowhere better to go: no drift trigger
            current = {tc.name: tc.replicas for tc in rb.spec.clusters}
            moved = 0
            for name in set(current) | set(res.clusters):
                moved += abs(
                    int(res.clusters.get(name, 0))
                    - int(current.get(name, 0))
                )
            if moved > 0:
                drifts.append((moved, idx, kind, rb))
        drifts.sort(key=lambda t: (-t[0], t[1]))
        triggered = drifts[:budget]
        if not triggered:
            desched_disruption_used.set(0)
            stats = {
                "scored": len(cands),
                "drifted": len(drifts),
                "budget": budget,
                "triggered": [],
            }
            self.last_round = stats
            return stats
        now = self.clock()
        changed = []
        prior_by_id = {}
        for _moved, _idx, _kind, rb in triggered:
            prior_by_id[id(rb)] = rb.spec.reschedule_triggered_at
            rb.spec.reschedule_triggered_at = now
            rb.meta.generation += 1
            changed.append(rb)
        rejected_ids: set = set()
        apply_many = getattr(self.store, "apply_many", None)
        if apply_many is not None:
            for rb, err in apply_many(changed):
                # rejected stamp: roll back so the next round retries
                # (the prior consumed trigger is restored, not zeroed —
                # the WorkloadRebalancerController rollback discipline)
                rb.meta.generation -= 1
                rb.spec.reschedule_triggered_at = prior_by_id[id(rb)]
                rejected_ids.add(id(rb))
                print(
                    f"# descheduler: trigger rejected for "
                    f"{rb.meta.namespaced_name}: {err}",
                    flush=True,
                )
        else:
            for rb in changed:
                self.store.apply(rb)
        # stats/gauges/counters report what COMMITTED: a rejected stamp
        # was rolled back and never disrupted anything
        committed = [rb for rb in changed if id(rb) not in rejected_ids]
        desched_disruption_used.set(len(committed))
        from ..utils.metrics import preemptions_total
        from ..utils.reasons import REASONS

        reason = REASONS["RebalanceTriggered"].code
        for rb in committed:
            # once per trigger episode: the stamp itself is exactly-once
            # (unconsumed triggers are filtered above), so the counter
            # dedups on the binding's NEW generation — a re-listed
            # binding in the same episode never double-counts
            if self.scheduler._reason_dedup.observe(
                ("rebalance", rb.meta.namespaced_name),
                reason,
                rb.meta.generation,
            ):
                preemptions_total.inc(reason=reason)
        stats = {
            "scored": len(cands),
            "drifted": len(drifts),
            "budget": budget,
            "triggered": [rb.meta.namespaced_name for rb in committed],
        }
        self.last_round = stats
        return stats
