"""DependenciesDistributor: propagate a workload's dependencies alongside it.

Ref: pkg/dependenciesdistributor/dependencies_distributor.go:333-595 — when
a policy sets propagateDeps, the interpreter's GetDependencies (configmaps,
secrets, PVCs, service accounts) produces *attached* ResourceBindings that
shadow the independent binding's schedule result (RequiredBy snapshots), so
dependencies land wherever the workload lands.
"""

from __future__ import annotations

from typing import Optional

from ..api.core import ObjectMeta
from ..api.work import BindingSnapshot, ResourceBinding, ResourceBindingSpec
from ..interpreter import ResourceInterpreter
from ..utils import DONE, Runtime, Store

DEPENDED_BY_LABEL = "resourcebinding.karmada.io/depended-by"


def attached_binding_name(dep_kind: str, dep_name: str) -> str:
    return f"{dep_name}-{dep_kind.lower()}"


class DependenciesDistributor:
    def __init__(
        self, store: Store, runtime: Runtime, interpreter: ResourceInterpreter
    ) -> None:
        self.store = store
        self.interpreter = interpreter
        self.worker = runtime.new_worker("dependencies", self._reconcile)
        store.watch("ResourceBinding", self._on_binding_event)

    def _on_binding_event(self, event) -> None:
        rb = event.obj
        # skip attached bindings driving themselves; everything else may need
        # (re)distribution or cleanup (e.g. propagateDeps turned off)
        if DEPENDED_BY_LABEL not in rb.meta.labels:
            self.worker.enqueue(event.key)

    def _reconcile(self, key: str) -> Optional[str]:
        rb = self.store.get("ResourceBinding", key)
        if rb is None or not rb.spec.propagate_deps:
            self._cleanup_attached(key)
            return DONE
        if not rb.spec.clusters:
            return DONE  # nothing scheduled yet
        template = self.store.get("Resource", rb.spec.resource.namespaced_key)
        if template is None:
            return DONE
        deps = self.interpreter.get_dependencies(template)
        seen_keys = set()
        for dep in deps:
            dep_template = self.store.get(
                "Resource", f"{dep.namespace}/{dep.name}" if dep.namespace else dep.name
            )
            if dep_template is None or dep_template.kind != dep.kind:
                continue  # dependency not present on the control plane
            name = attached_binding_name(dep.kind, dep.name)
            akey = f"{dep.namespace}/{name}" if dep.namespace else name
            seen_keys.add(akey)
            existing = self.store.get("ResourceBinding", akey)
            snapshot = BindingSnapshot(
                namespace=rb.meta.namespace,
                name=rb.meta.name,
                clusters=list(rb.spec.clusters),
            )
            if existing is not None and DEPENDED_BY_LABEL in existing.meta.labels:
                changed = self._merge_required_by(existing, snapshot)
                if changed:
                    self._sync_clusters(existing)
                    self.store.apply(existing)
                continue
            if existing is not None:
                # independent binding already exists for the dependency; the
                # reference merges RequiredBy into it (suppressed schedule)
                changed = self._merge_required_by(existing, snapshot)
                if changed:
                    self.store.apply(existing)
                continue
            attached = ResourceBinding(
                meta=ObjectMeta(
                    name=name,
                    namespace=dep.namespace,
                    labels={DEPENDED_BY_LABEL: rb.meta.namespaced_name},
                ),
                spec=ResourceBindingSpec(
                    resource=dep_template.object_reference(),
                    replicas=0,
                    required_by=[snapshot],
                    # attached bindings shadow the parent's schedule; the
                    # scheduler must not re-place them
                    scheduler_name="",
                ),
            )
            self._sync_clusters(attached)
            self.store.apply(attached)
        # drop stale attachments no longer in the dependency set
        for other in self.store.list("ResourceBinding"):
            if (
                other.meta.labels.get(DEPENDED_BY_LABEL) == key
                and other.meta.namespaced_name not in seen_keys
            ):
                self.store.delete("ResourceBinding", other.meta.namespaced_name)
        return DONE

    def _merge_required_by(self, binding: ResourceBinding, snap: BindingSnapshot) -> bool:
        for i, existing in enumerate(binding.spec.required_by):
            if (
                existing.namespace == snap.namespace
                and existing.name == snap.name
            ):
                if [
                    (c.name, c.replicas) for c in existing.clusters
                ] != [(c.name, c.replicas) for c in snap.clusters]:
                    binding.spec.required_by[i] = snap
                    self._sync_clusters(binding)
                    return True
                return False
        binding.spec.required_by.append(snap)
        self._sync_clusters(binding)
        return True

    def _sync_clusters(self, binding: ResourceBinding) -> None:
        """Attached bindings aggregate the union of all RequiredBy cluster
        sets as their own schedule result (zero-replica placement)."""
        if DEPENDED_BY_LABEL not in binding.meta.labels and binding.spec.clusters:
            return  # independent binding keeps its own schedule
        from ..api.work import TargetCluster

        clusters: dict[str, int] = {}
        for snap in binding.spec.required_by:
            for tc in snap.clusters:
                clusters.setdefault(tc.name, 0)
        binding.spec.clusters = [
            TargetCluster(name=n) for n in sorted(clusters)
        ]

    def _cleanup_attached(self, parent_key: str) -> None:
        for other in self.store.list("ResourceBinding"):
            if other.meta.labels.get(DEPENDED_BY_LABEL) == parent_key:
                self.store.delete("ResourceBinding", other.meta.namespaced_name)
