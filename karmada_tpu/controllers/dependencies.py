"""DependenciesDistributor: propagate a workload's dependencies alongside it.

Ref: pkg/dependenciesdistributor/dependencies_distributor.go:333-595 — when
a policy sets propagateDeps, the interpreter's GetDependencies (configmaps,
secrets, PVCs, service accounts) produces *attached* ResourceBindings that
shadow the independent binding's schedule result (RequiredBy snapshots), so
dependencies land wherever the workload lands.
"""

from __future__ import annotations

from typing import Optional

from ..api.core import ObjectMeta
from ..api.work import BindingSnapshot, ResourceBinding, ResourceBindingSpec
from ..interpreter import ResourceInterpreter
from ..utils import DONE, Runtime, Store

DEPENDED_BY_LABEL = "resourcebinding.karmada.io/depended-by"


def attached_binding_name(dep_kind: str, dep_name: str) -> str:
    return f"{dep_name}-{dep_kind.lower()}"


class DependenciesDistributor:
    def __init__(
        self, store: Store, runtime: Runtime, interpreter: ResourceInterpreter
    ) -> None:
        self.store = store
        self.interpreter = interpreter
        self.worker = runtime.new_worker("dependencies", self._reconcile)
        # parent binding key -> attached binding keys; an informer-style
        # index replacing the full-store scans the cleanup paths ran per
        # reconcile (O(bindings) per event drowned propagation storms).
        # Pre-existing attachments are seeded by the watch's replay of
        # ADDED events (informer initial-list semantics). The reverse map
        # prunes the index when a binding loses or changes its depended-by
        # label (adoption / re-parenting), so cleanup never deletes a
        # binding that is no longer attached.
        self._attached: dict[str, set[str]] = {}
        self._attached_parent: dict[str, str] = {}
        store.watch("ResourceBinding", self._on_binding_event)

    def _on_binding_event(self, event) -> None:
        rb = event.obj
        # attached bindings don't drive themselves, but they feed the index;
        # everything else may need (re)distribution or cleanup (e.g.
        # propagateDeps turned off)
        parent = rb.meta.labels.get(DEPENDED_BY_LABEL)
        old = self._attached_parent.get(event.key)
        if old is not None and (event.type == "Deleted" or old != parent):
            self._attached.get(old, set()).discard(event.key)
            del self._attached_parent[event.key]
        if parent is not None:
            if event.type != "Deleted":
                self._attached.setdefault(parent, set()).add(event.key)
                self._attached_parent[event.key] = parent
            return
        self.worker.enqueue(event.key)

    def _reconcile(self, key: str) -> Optional[str]:
        rb = self.store.get("ResourceBinding", key)
        if rb is None or not rb.spec.propagate_deps:
            self._cleanup_attached(key)
            return DONE
        if not rb.spec.clusters:
            return DONE  # nothing scheduled yet
        template = self.store.get("Resource", rb.spec.resource.namespaced_key)
        if template is None:
            return DONE
        deps = self.interpreter.get_dependencies(template)
        seen_keys = set()
        for dep in deps:
            dep_template = self.store.get(
                "Resource", f"{dep.namespace}/{dep.name}" if dep.namespace else dep.name
            )
            if dep_template is None or dep_template.kind != dep.kind:
                continue  # dependency not present on the control plane
            name = attached_binding_name(dep.kind, dep.name)
            akey = f"{dep.namespace}/{name}" if dep.namespace else name
            seen_keys.add(akey)
            existing = self.store.get("ResourceBinding", akey)
            snapshot = BindingSnapshot(
                namespace=rb.meta.namespace,
                name=rb.meta.name,
                clusters=list(rb.spec.clusters),
            )
            if existing is not None and DEPENDED_BY_LABEL in existing.meta.labels:
                changed = self._merge_required_by(existing, snapshot)
                if changed:
                    self._sync_clusters(existing)
                    self.store.apply(existing)
                continue
            if existing is not None:
                # independent binding already exists for the dependency; the
                # reference merges RequiredBy into it (suppressed schedule)
                changed = self._merge_required_by(existing, snapshot)
                if changed:
                    self.store.apply(existing)
                continue
            attached = ResourceBinding(
                meta=ObjectMeta(
                    name=name,
                    namespace=dep.namespace,
                    labels={DEPENDED_BY_LABEL: rb.meta.namespaced_name},
                ),
                spec=ResourceBindingSpec(
                    resource=dep_template.object_reference(),
                    replicas=0,
                    required_by=[snapshot],
                    # attached bindings shadow the parent's schedule; the
                    # scheduler must not re-place them
                    scheduler_name="",
                ),
            )
            self._sync_clusters(attached)
            self.store.apply(attached)
        # drop stale attachments no longer in the dependency set
        for akey in list(self._attached.get(key, ())) :
            if akey not in seen_keys:
                self.store.delete("ResourceBinding", akey)
        return DONE

    def _merge_required_by(self, binding: ResourceBinding, snap: BindingSnapshot) -> bool:
        for i, existing in enumerate(binding.spec.required_by):
            if (
                existing.namespace == snap.namespace
                and existing.name == snap.name
            ):
                if [
                    (c.name, c.replicas) for c in existing.clusters
                ] != [(c.name, c.replicas) for c in snap.clusters]:
                    binding.spec.required_by[i] = snap
                    self._sync_clusters(binding)
                    return True
                return False
        binding.spec.required_by.append(snap)
        self._sync_clusters(binding)
        return True

    def _sync_clusters(self, binding: ResourceBinding) -> None:
        """Attached bindings aggregate the union of all RequiredBy cluster
        sets as their own schedule result (zero-replica placement)."""
        if DEPENDED_BY_LABEL not in binding.meta.labels and binding.spec.clusters:
            return  # independent binding keeps its own schedule
        from ..api.work import TargetCluster

        clusters: dict[str, int] = {}
        for snap in binding.spec.required_by:
            for tc in snap.clusters:
                clusters.setdefault(tc.name, 0)
        binding.spec.clusters = [
            TargetCluster(name=n) for n in sorted(clusters)
        ]

    def _cleanup_attached(self, parent_key: str) -> None:
        for akey in list(self._attached.get(parent_key, ())):
            self.store.delete("ResourceBinding", akey)
