"""MultiClusterIngress controller.

Ref: pkg/controllers/multiclusteringress + pkg/apis/networking/v1alpha1
MultiClusterIngress: an ingress whose backend services are backed by
multiple clusters. The controller resolves each rule's backend service to
the clusters that can serve it (via the MCS machinery) and dispatches a
plain Ingress + derived backends into those clusters.
"""

from __future__ import annotations

from typing import Optional

from ..api.core import ObjectMeta, Resource
from ..api.work import Work, WorkSpec
from ..utils import DONE, Runtime, Store
from ..utils.member import MemberClientRegistry
from .propagation import execution_namespace


class MultiClusterIngressController:
    def __init__(
        self, store: Store, runtime: Runtime, members: MemberClientRegistry
    ) -> None:
        self.store = store
        self.members = members
        self.worker = runtime.new_worker("multiclusteringress", self._reconcile)
        store.watch("MultiClusterIngress", lambda e: self.worker.enqueue(e.key))
        runtime.add_ticker(self._sweep)

    def _sweep(self) -> None:
        for mci in self.store.list("MultiClusterIngress"):
            self.worker.enqueue(mci.meta.namespaced_name)

    def _service_clusters(self, namespace: str, service: str) -> list[str]:
        """Clusters that can serve a backend service: those holding the
        service natively or via an MCS-derived service."""
        out = []
        for name in self.members.names():
            member = self.members.get(name)
            if member is None or not member.reachable:
                continue
            if (
                member.get("v1/Service", namespace, service) is not None
                or member.get("v1/Service", namespace, f"derived-{service}")
                is not None
            ):
                out.append(name)
        return sorted(out)

    def _reconcile(self, key: str) -> Optional[str]:
        mci = self.store.get("MultiClusterIngress", key)
        ns, _, name = key.rpartition("/")
        if mci is None:
            return DONE
        # gather backend services from the rules
        backends = set()
        for rule in mci.spec.rules:
            for path in rule.get("http", {}).get("paths", []):
                svc = path.get("backend", {}).get("service", {}).get("name")
                if svc:
                    backends.add(svc)
        target_clusters: set[str] = set()
        for svc in backends:
            target_clusters.update(self._service_clusters(ns, svc))
        ingress = Resource(
            api_version="networking.k8s.io/v1",
            kind="Ingress",
            meta=ObjectMeta(name=name, namespace=ns),
            spec={"rules": list(mci.spec.rules)},
        )
        for cluster in sorted(target_clusters):
            work_ns = execution_namespace(cluster)
            wkey = f"{work_ns}/mci-{ns}.{name}"
            existing = self.store.get("Work", wkey)
            if existing is not None and existing.spec.workload[0].spec == ingress.spec:
                continue
            self.store.apply(
                Work(
                    meta=ObjectMeta(name=f"mci-{ns}.{name}", namespace=work_ns),
                    spec=WorkSpec(workload=[ingress]),
                )
            )
        if mci.status.get("clusters") != sorted(target_clusters):
            mci.status = {"clusters": sorted(target_clusters)}
            self.store.apply(mci)
        return DONE
